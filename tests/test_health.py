"""Chip-loss self-healing and tail tolerance (mxnet_tpu/serving/health.py):
device-fatal classification, the retry contract (OOM and DEVICE_LOST are
NEVER retried), the retry budget, quarantine + half-open re-admission,
the degraded-mode ladder — and THE chip-loss acceptance test: a two-
tenant serve loses 1 of 2 chips mid-traffic under the lock-order
sanitizer; the sentinel quarantines it, the ladder re-plans onto the
survivor, the failed batch's live batchmates are re-dispatched (nothing
silently lost), and after the cooldown the chip re-admits and capacity
restores — all proven from telemetry counters and trace-ring events."""
import threading
import time
import types

import numpy as np
import pytest

from mxnet_tpu.observability import catalog
from mxnet_tpu.resilience.retry import is_transient, retry_transient
from mxnet_tpu.serving import ModelConfig, ModelServer, Overloaded
from mxnet_tpu.serving import chaos as schaos
from mxnet_tpu.serving import health
from mxnet_tpu.serving import load as sload
from mxnet_tpu.serving.queueing import RetryBudget

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny():
    return sload.tiny_model()


def _cfg(tiny, name="m", **kw):
    sym_json, pbytes, feat, _ = tiny
    d = dict(feature_shape=feat, buckets=(1, 2, 4, 8), max_queue=16,
             deadline_ms=2000.0, max_wait_ms=3.0, breaker_cooldown_s=0.25)
    d.update(kw)
    return ModelConfig(name, sym_json, pbytes, **d)


class _StubTracer:
    def __init__(self):
        self.events = []

    def record_event(self, name, **tags):
        self.events.append((name, tags))


class _StubServer:
    def __init__(self):
        self.tracer = _StubTracer()
        self._models = {}


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ----------------------------------------------------------- classification
def test_is_device_fatal_markers_and_chip_attribution():
    e = RuntimeError("DEVICE_LOST: chip 3 went away")
    assert health.is_device_fatal(e)
    assert health.device_fatal_reason(e) == "device_lost"
    assert health.chip_of(e) == 3

    assert health.device_fatal_reason(
        RuntimeError("transfer failed to enqueue on stream")) == "enqueue"
    assert health.device_fatal_reason(
        RuntimeError("DATA_LOSS: corrupt result buffer")) == "data_loss"

    # an explicit chip_idx attribute beats the message mention
    e2 = RuntimeError("DEVICE_LOST: chip 7 suspect")
    e2.chip_idx = 1
    assert health.chip_of(e2) == 1
    # no attribution at all -> None (caller falls back to the bound device)
    assert health.chip_of(RuntimeError("device lost")) is None

    # ordinary errors are not device-fatal
    assert not health.is_device_fatal(ValueError("bad input"))
    assert not health.is_device_fatal(RuntimeError("INVALID_ARGUMENT"))

    # classification survives exception wrapping (cause chain)
    try:
        try:
            raise RuntimeError("device lost: chip 2")
        except RuntimeError as inner:
            raise ValueError("dispatch failed") from inner
    except ValueError as outer:
        assert health.is_device_fatal(outer)
        assert health.chip_of(outer) == 2


def test_oom_wins_over_device_fatal():
    # RESOURCE_EXHAUSTED is a capacity fact with its own typed fate
    # (HBMExhausted) — never a quarantine trigger, even when the message
    # also mentions the device
    e = RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating "
                     "17179869184 bytes on device_lost chip 0")
    assert not health.is_device_fatal(e)
    assert not health.is_device_fatal(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory"))


# ------------------------------------------------------------ retry contract
class XlaRuntimeError(RuntimeError):
    """Named like the real jaxlib error so is_transient's name check
    engages — the regression shape for the classifier tests."""


def _always(exc):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise exc

    return fn, calls


def test_retry_never_retries_resource_exhausted():
    # THE regression: "resource exhausted" used to sit in the transient
    # markers, so a raw RESOURCE_EXHAUSTED was retried — re-OOMing the
    # device and masking the typed HBMExhausted classification
    for msg in ("RESOURCE_EXHAUSTED: out of memory allocating 123 bytes",
                "Resource exhausted: failed to allocate buffer"):
        exc = XlaRuntimeError(msg)
        assert not is_transient(exc)
        fn, calls = _always(exc)
        with pytest.raises(XlaRuntimeError):
            retry_transient(fn, attempts=3, base_delay=0.0,
                            sleep=lambda s: None)
        assert calls["n"] == 1      # failed ONCE, no retry


def test_retry_never_retries_device_fatal():
    exc = XlaRuntimeError("DEVICE_LOST: chip 0 unavailable, aborted")
    assert not is_transient(exc)    # device-fatal wins over the markers
    fn, calls = _always(exc)
    with pytest.raises(XlaRuntimeError):
        retry_transient(fn, attempts=4, base_delay=0.0,
                        sleep=lambda s: None)
    assert calls["n"] == 1

    # plain transient infra errors still retry
    ok = XlaRuntimeError("UNAVAILABLE: connection reset by peer")
    assert is_transient(ok)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            raise ok
        return "served"

    assert retry_transient(flaky, attempts=3, base_delay=0.0,
                           sleep=lambda s: None) == "served"
    assert state["n"] == 2


def test_retry_gate_denial_fails_fast():
    exc = XlaRuntimeError("UNAVAILABLE: connection reset")
    fn, calls = _always(exc)
    with pytest.raises(XlaRuntimeError):
        retry_transient(fn, attempts=5, base_delay=0.0, gate=lambda e: False,
                        sleep=lambda s: None)
    assert calls["n"] == 1          # denied budget: no second attempt


# -------------------------------------------------------------- retry budget
def test_retry_budget_math():
    b = RetryBudget(fraction=0.5, burst=2.0)
    assert b.try_spend("retry") and b.try_spend("hedge")
    assert not b.try_spend("retry")             # burst drained
    for _ in range(2):                           # 2 admits * 0.5 = 1 token
        b.deposit()
    assert b.try_spend("hedge")
    assert not b.try_spend("hedge")
    s = b.stats()
    assert s["spent"] == {"retry": 1, "hedge": 2}
    assert s["denied"] == {"retry": 1, "hedge": 1}
    assert s["fraction"] == 0.5
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError):
            RetryBudget(fraction=bad)


# ------------------------------------------------- sentinel (fake clock)
def test_sentinel_quarantine_and_optimistic_readmit():
    clk = _Clock()
    stub = _StubServer()
    s = health.DeviceSentinel(stub, cooldown_s=10.0, clock=clk)
    q0 = catalog.CHIP_QUARANTINES.value(reason="device_lost")

    s.quarantine(3, reason="device_lost", model="m")
    assert s.is_quarantined(3) and s.count() == 1
    assert catalog.CHIP_QUARANTINES.value(reason="device_lost") - q0 == 1
    assert catalog.QUARANTINED_CHIPS.value() == 1
    snap = s.snapshot()
    assert snap["quarantined"][3]["reason"] == "device_lost"

    # a repeat extends the cooldown but keeps the original `since`
    since = snap["quarantined"][3]["since"]
    clk.t += 4.0
    s.quarantine(3, reason="device_lost")
    snap = s.snapshot()
    assert snap["quarantined"][3]["since"] == since
    assert snap["quarantined"][3]["until"] == clk.t + 10.0

    clk.t += 9.0                                 # not due yet
    assert s.maybe_readmit() == []
    clk.t += 1.5                                 # past the cooldown
    assert s.maybe_readmit() == [3]
    assert s.count() == 0
    assert catalog.QUARANTINED_CHIPS.value() == 0
    assert [n for n, _ in stub.tracer.events] \
        == ["quarantine", "quarantine", "readmit"]


def test_sentinel_probe_failure_rearms_cooldown():
    clk = _Clock()
    stub = _StubServer()
    s = health.DeviceSentinel(stub, cooldown_s=5.0, clock=clk)
    stub._sentinel = s
    p0 = catalog.CHIP_QUARANTINES.value(reason="probe")
    s.quarantine(0, reason="enqueue")
    with schaos.quarantine_flap(stub, failures=2) as flap:
        clk.t += 6.0
        assert s.maybe_readmit() == []           # probe 1 fails: re-armed
        assert s.is_quarantined(0)
        clk.t += 2.0
        assert s.maybe_readmit() == []           # not due (cooldown re-armed)
        clk.t += 4.0
        assert s.maybe_readmit() == []           # probe 2 fails
        clk.t += 6.0
        assert s.maybe_readmit() == [0]          # probe 3 passes
    assert flap["probes"] == 3 and flap["failed"] == 2
    assert catalog.CHIP_QUARANTINES.value(reason="probe") - p0 == 2


# ------------------------------------------------------------ degraded ladder
def test_ladder_transitions_and_admission_gates():
    stub = _StubServer()
    st = types.SimpleNamespace(cfg=types.SimpleNamespace(name="lad",
                                                         tier="f32"))
    lad = health.DegradedLadder(stub, st)
    assert lad.rung == 0 and lad.name() == "healthy"

    req_be = types.SimpleNamespace(priority=None)
    req_g = types.SimpleNamespace(priority="guaranteed")
    lad.admit_check(req_be)                      # healthy: everyone in

    assert lad.escalate("test") == 1
    assert lad.escalate("test") == 2
    lad.admit_check(req_be)                      # rungs 1-2: still admitting
    assert lad.escalate("test") == 3
    with pytest.raises(Overloaded) as ei:
        lad.admit_check(req_be)                  # rung 3 sheds best-effort
    assert getattr(ei.value, "degraded", False)
    lad.admit_check(req_g)                       # ... but not guaranteed
    assert lad.escalate("test") == 4
    assert lad.escalate("test") == 4             # capped at static shed
    with pytest.raises(Overloaded):
        lad.admit_check(req_g)                   # rung 4 sheds everyone
    assert catalog.SERVE_DEGRADED_RUNG.value(model="lad") == 4

    for want in (3, 2, 1, 0):
        assert lad.de_escalate("healthy") == want
    assert lad.de_escalate("healthy") == 0       # capped at healthy
    assert catalog.SERVE_DEGRADED_RUNG.value(model="lad") == 0
    # EDGE-triggered: one trace event per actual change, none for the
    # capped no-op calls
    degraded = [t for n, t in stub.tracer.events if n == "degraded"]
    assert len(degraded) == 8
    assert [t["rung"] for t in degraded] == [1, 2, 3, 4, 3, 2, 1, 0]


def test_ladder_effect_reduces_buckets_live(tiny):
    srv = ModelServer([_cfg(tiny, name="cap")]).start(warm=True)
    _, _, feat, ref = tiny
    d = np.random.RandomState(5).randn(*feat).astype("float32")
    try:
        st = srv._models["cap"]
        assert st.cache.buckets == (1, 2, 4, 8)
        st.ladder.escalate("test:reduced")
        # the model's own worker applies the effect on its next tick
        deadline = time.monotonic() + 5.0
        while st.cache.buckets != (1, 2, 4):
            assert time.monotonic() < deadline, st.cache.buckets
            srv.predict("cap", d, timeout=30.0)
        np.testing.assert_allclose(srv.predict("cap", d, timeout=30.0),
                                   ref(d), rtol=1e-4, atol=1e-5)
        assert catalog.SERVE_DEGRADED_RUNG.value(model="cap") == 1
        # the transition is on the trace ring, not just the gauge
        events = srv.tracer.traces(model="cap", outcome="event")
        assert any(s["tags"].get("mode") == "reduced_buckets"
                   for t in events for s in t.spans
                   if s["stage"] == "degraded")
        st.ladder.de_escalate("test:healthy")
        deadline = time.monotonic() + 5.0
        while st.cache.buckets != (1, 2, 4, 8):
            assert time.monotonic() < deadline, st.cache.buckets
            srv.predict("cap", d, timeout=30.0)
        assert catalog.SERVE_DEGRADED_RUNG.value(model="cap") == 0
    finally:
        srv.close(timeout=10.0)


# ---------------------------------------------- THE chip-loss acceptance test
@pytest.mark.chaos
def test_chip_loss_quarantines_replans_and_restores(tiny, monkeypatch):
    """Two tenants serving, tenant `a` spread over 2 chips; chip 1 dies
    mid-traffic (every dispatch device-fatal until quarantined). The
    sentinel must quarantine it (counted), re-plan `a` onto the survivor
    (trace-ring `replan` event), re-dispatch the failed batch's live
    batchmates (every future answers, correctly), keep tenant `b`
    untouched — and after the cooldown re-admit the chip and restore the
    pre-loss placement. Runs under the lock-order sanitizer: zero
    findings."""
    from mxnet_tpu.analysis import lockwatch

    monkeypatch.setenv("MXNET_LOCKCHECK", "1")   # before any lock is made
    lockwatch.reset()
    _, _, feat, ref = tiny
    srv = ModelServer([_cfg(tiny, name="a", max_queue=64),
                       _cfg(tiny, name="b", max_queue=64)]).start(warm=True)
    payload = np.random.RandomState(9).randn(*feat).astype("float32")
    q0 = catalog.CHIP_QUARANTINES.value(reason="device_lost")
    ok0 = {m: catalog.SERVE_REQUESTS.value(model=m, outcome="ok")
           for m in ("a", "b")}
    try:
        st_a = srv._models["a"]
        with st_a.dispatch_mutex:
            assert st_a.cache.rebind(2) == (2, 4, 8)
        srv._sentinel.cooldown_s = 0.5

        with schaos.device_lost(srv, "a", chip_idx=1) as dl:
            futs = [srv.submit("a", payload) for _ in range(24)]
            futs += [srv.submit("b", payload) for _ in range(12)]
            for f in futs:
                np.testing.assert_allclose(f.result(30.0), ref(payload),
                                           rtol=1e-4, atol=1e-5)
            # the chip actually died, was quarantined, and the survivors
            # then served real traffic through the same executor
            assert dl["faulted"] >= 1 and dl["passed"] >= 1
            assert srv._sentinel.is_quarantined(1)
            assert st_a.cache.chips == 1         # re-planned onto survivor
            snap = srv._sentinel.snapshot()
            assert snap["restore"] == {"a": 2}   # pre-loss placement noted

        # counter proof: one device_lost quarantine, zero lost requests
        assert catalog.CHIP_QUARANTINES.value(reason="device_lost") \
            - q0 == 1
        d_ok = {m: catalog.SERVE_REQUESTS.value(model=m, outcome="ok")
                - ok0[m] for m in ("a", "b")}
        assert d_ok["a"] >= 24 and d_ok["b"] >= 12
        assert srv.stats("a")["deadline_violations"] == 0
        assert srv.stats("b")["deadline_violations"] == 0
        assert srv.stats("a")["counts"]["error"] == 0

        # trace-ring proof: quarantine and replan landed as events
        events = srv.tracer.traces(model="a", outcome="event")
        spans = [s for t in events for s in t.spans]
        assert any(s["stage"] == "replan"
                   and s["tags"].get("reason") == "chip_loss"
                   for s in spans)

        # half-open re-admission after the cooldown: the chip re-admits
        # and capacity restores to the pre-loss 2 chips (the worker tick
        # drives it; idle traffic keeps the worker looping)
        deadline = time.monotonic() + 10.0
        while st_a.cache.chips != 2:
            assert time.monotonic() < deadline, srv._sentinel.snapshot()
            srv.predict("a", payload, timeout=30.0)
            time.sleep(0.05)
        assert not srv._sentinel.is_quarantined(1)
        assert srv._sentinel.snapshot()["restore"] == {}
        events = srv.tracer.traces(outcome="event")
        assert any(s["stage"] == "readmit"
                   for t in events for s in t.spans)
        # still correct after restore
        np.testing.assert_allclose(srv.predict("a", payload, timeout=30.0),
                                   ref(payload), rtol=1e-4, atol=1e-5)
    finally:
        srv.close(timeout=10.0)
    lockwatch.assert_no_findings()


# --------------------------------------------------- hedging + retry budget
@pytest.mark.chaos
def test_hedging_rescues_stragglers(tiny):
    """Every 3rd dispatch stalls 0.5s; hedging (80ms trigger) must answer
    every request well before the stall — while the same straggler
    WITHOUT hedging shows the full 0.5s tail. (80ms, not lower: a hedge
    dispatch can itself land on the straggler's every-3rd slot, and the
    rescue then comes from the primary once the worker frees — the
    bigger trigger keeps that worst chain comfortably under the bar.)"""
    _, _, feat, ref = tiny
    srv = ModelServer([
        _cfg(tiny, name="hm", hedge=True, hedge_delay_ms=80.0,
             retry_budget=0.5),
        _cfg(tiny, name="nm", hedge=False),
    ]).start(warm=True)
    d = np.random.RandomState(11).randn(*feat).astype("float32")
    try:
        st = srv._models["hm"]
        with schaos.straggler_executor(srv, "hm", 0.5, every=3) as s1:
            lat_hedged = []
            for _ in range(12):
                t0 = time.monotonic()
                np.testing.assert_allclose(
                    srv.predict("hm", d, timeout=30.0), ref(d),
                    rtol=1e-4, atol=1e-5)
                lat_hedged.append(time.monotonic() - t0)
        assert s1["stalled"] >= 3
        assert st.hedges["fired"] >= s1["stalled"]
        assert st.hedges["won"] >= 1
        assert catalog.SERVE_HEDGES.value(model="hm", outcome="won") >= 1
        # every straggle was rescued: nothing waited out the full stall
        assert max(lat_hedged) < 0.45, lat_hedged

        with schaos.straggler_executor(srv, "nm", 0.5, every=3) as s2:
            lat_plain = []
            for _ in range(6):
                t0 = time.monotonic()
                srv.predict("nm", d, timeout=30.0)
                lat_plain.append(time.monotonic() - t0)
        assert s2["stalled"] >= 2
        assert max(lat_plain) >= 0.45, lat_plain  # the tail hedging cut
        assert srv.stats("hm")["deadline_violations"] == 0
    finally:
        srv.close(timeout=10.0)


@pytest.mark.chaos
def test_retry_budget_caps_hedge_traffic(tiny):
    """With EVERY dispatch slow, every request wants a hedge — the
    budget (10% + burst) must cap how many actually fire, and count the
    denials (typed, never silent)."""
    _, _, feat, _ = tiny
    srv = ModelServer([_cfg(tiny, name="bm", hedge=True, hedge_delay_ms=5.0,
                            retry_budget=0.1)]).start(warm=True)
    d = np.zeros(feat, "float32")
    den0 = catalog.RETRY_BUDGET_DENIED.value(model="bm", kind="hedge")
    try:
        st = srv._models["bm"]
        with schaos.straggler_executor(srv, "bm", 0.05, every=1):
            for _ in range(30):
                srv.predict("bm", d, timeout=30.0)
        h = dict(st.hedges)
        # the cap: burst (5) + 10% of 30 admits, with a little slack for
        # hedges of hedged dispatches
        assert h["fired"] <= 10, h
        assert h["budget_denied"] >= 5, h
        assert catalog.RETRY_BUDGET_DENIED.value(model="bm", kind="hedge") \
            - den0 == h["budget_denied"]
        assert srv.stats("bm")["retry_budget"]["denied"]["hedge"] \
            == h["budget_denied"]
    finally:
        srv.close(timeout=10.0)


# --------------------------------------------------------- invariance guard
def test_self_healing_is_hlo_invariant(tiny):
    """The whole subsystem is host-side: with the sentinel idle and
    hedging off (the defaults) the served StableHLO is BITWISE unchanged
    by health.py existing, importing, or a server running with it."""
    import jax

    from mxnet_tpu import symbol as sym_mod
    from mxnet_tpu.executor import _GraphLowering

    sym_json, pbytes, feat, ref = tiny

    def lowered_text():
        sym = sym_mod.load_json(sym_json)
        fn = _GraphLowering(sym).lower(is_train=False)
        inputs = {"data": np.zeros((2,) + feat, np.float32),
                  "fc1_weight": np.zeros((3, feat[0]), np.float32),
                  "fc1_bias": np.zeros((3,), np.float32)}
        return jax.jit(fn).lower(inputs, jax.random.PRNGKey(0)).as_text()

    before = lowered_text()
    srv = ModelServer([_cfg(tiny, name="inv")]).start(warm=True)
    try:
        assert srv._hedger is None               # nobody opted in
        assert srv._sentinel.count() == 0
        d = np.random.RandomState(2).randn(*feat).astype("float32")
        np.testing.assert_allclose(srv.predict("inv", d, timeout=30.0),
                                   ref(d), rtol=1e-4, atol=1e-5)
    finally:
        srv.close(timeout=10.0)
    assert lowered_text() == before
