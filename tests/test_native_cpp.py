"""Build and run the C++-level native tests (tests/cpp/native_test.cc) —
the reference's tests/cpp/{engine,storage} tier. Skips cleanly if no
toolchain is available."""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_native_cpp_suite(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    exe = str(tmp_path / "native_test")
    build = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-pthread",
         os.path.join(ROOT, "tests", "cpp", "native_test.cc"),
         os.path.join(ROOT, "mxnet_tpu", "native", "engine_storage.cc"),
         "-o", exe],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr[:800]
    run = subprocess.run([exe], capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, f"stdout:{run.stdout}\nstderr:{run.stderr}"
    assert "ALL NATIVE C++ TESTS PASSED" in run.stdout
