"""IO tests (reference: tests/python/unittest/test_io.py, test_recordio)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.io import (NDArrayIter, DataBatch, ResizeIter, PrefetchingIter,
                          CSVIter, ImageRecordIter)


def test_ndarray_iter_basic(rng):
    data = rng.randn(29, 3).astype("float32")
    label = rng.randint(0, 5, 29).astype("float32")
    it = NDArrayIter(data, label, batch_size=8, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (8, 3)
    assert batches[-1].pad == 3
    # discard mode drops the last partial batch
    it2 = NDArrayIter(data, label, batch_size=8, last_batch_handle="discard")
    assert len(list(it2)) == 3
    # reset reuses the iterator
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_shuffle_and_dict(rng):
    data = {"a": rng.randn(10, 2).astype("float32"),
            "b": rng.randn(10, 4).astype("float32")}
    it = NDArrayIter(data, None, batch_size=5, shuffle=True)
    batch = next(it)
    assert len(batch.data) == 2
    names = [d.name for d in it.provide_data]
    assert set(names) == {"a", "b"}


def test_resize_iter(rng):
    data = rng.randn(8, 2).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = ResizeIter(base, size=5)
    assert len(list(it)) == 5


def test_prefetching_iter(rng):
    data = rng.randn(16, 2).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = PrefetchingIter(base)
    n = 0
    for batch in it:
        n += 1
        assert batch.data[0].shape == (4, 2)
    assert n == 4
    it.reset()
    assert sum(1 for _ in it) == 4


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(f"record-{i}".encode() * (i + 1))
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == f"record-{i}".encode() * (i + 1)
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(10):
        w.write_idx(i, f"payload{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"payload7"
    assert r.read_idx(2) == b"payload2"
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"data!")
    h2, payload = recordio.unpack(s)
    assert payload == b"data!"
    assert h2.label == 3.0
    assert h2.id == 42
    # multi-label
    h3 = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], dtype="float32"), 7, 0)
    s3 = recordio.pack(h3, b"x")
    h4, p4 = recordio.unpack(s3)
    assert h4.flag == 3
    np.testing.assert_allclose(np.asarray(h4.label), [1, 2, 3])


def test_pack_img_and_image_record_iter(tmp_path, rng):
    rec_path = str(tmp_path / "img.rec")
    idx_path = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(12):
        img = (rng.rand(24, 24, 3) * 255).astype("uint8")
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, img_fmt=".png"))
    w.close()

    it = ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                         data_shape=(3, 16, 16), batch_size=4,
                         preprocess_threads=2)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 16, 16)
        assert batch.label[0].shape == (4,)
        n += 1
    assert n == 3
    it.reset()
    assert sum(1 for _ in it) == 3


def test_csv_iter(tmp_path, rng):
    data = rng.randn(10, 4).astype("float32")
    labels = rng.randint(0, 2, 10).astype("float32")
    dpath = str(tmp_path / "d.csv")
    lpath = str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels, delimiter=",")
    it = CSVIter(data_csv=dpath, data_shape=(4,), label_csv=lpath,
                 batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5], rtol=1e-5)


# ---------------------------------------------------------------- state
# Checkpointable-iterator protocol (resilient data pipeline): state() /
# set_state() capture epoch, cursor and shuffle-RNG seed so a fresh
# iterator resumes EXACTLY mid-epoch — no skipped or duplicated batches.

def _drain(it, n):
    return [it.next().data[0].asnumpy().copy() for _ in range(n)]


def test_ndarray_iter_state_mid_epoch_roundtrip(rng):
    from mxnet_tpu.io import has_state
    data = rng.randn(40, 3).astype("float32")
    mx.random.seed(23)
    it = NDArrayIter(data, None, batch_size=8, shuffle=True)
    assert has_state(it)
    _drain(it, 2)
    st = it.state()
    assert st["epoch"] == 0 and st["cursor"] == 8
    # a "restarted process": fresh iterator, different construction seed
    mx.random.seed(99)
    it2 = NDArrayIter(data, None, batch_size=8, shuffle=True)
    it2.set_state(st)
    for mine, orig in zip(_drain(it2, 3), _drain(it, 3)):
        np.testing.assert_array_equal(mine, orig)
    # replay across the epoch boundary: reset() continues the SAME
    # deterministic shuffle stream on both
    it.reset(), it2.reset()
    for mine, orig in zip(_drain(it2, 5), _drain(it, 5)):
        np.testing.assert_array_equal(mine, orig)


def test_ndarray_iter_state_covers_every_batch_exactly_once(rng):
    """Kill/resume mid-epoch: resumed batches + pre-kill batches tile the
    epoch with no overlap and no gap."""
    data = np.arange(32, dtype="float32").reshape(32, 1)
    mx.random.seed(7)
    it = NDArrayIter(data, None, batch_size=4, shuffle=True,
                     last_batch_handle="discard")
    seen = [b.ravel() for b in _drain(it, 3)]          # "killed" after 3
    st = it.state()
    mx.random.seed(1234)                               # restart w/ new seed
    it2 = NDArrayIter(data, None, batch_size=4, shuffle=True,
                      last_batch_handle="discard")
    it2.set_state(st)
    seen += [b.ravel() for b in _drain(it2, 5)]        # rest of the epoch
    flat = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(flat, np.arange(32, dtype="float32"))


def test_ndarray_iter_state_rejects_wrong_dataset(rng):
    a = NDArrayIter(rng.randn(10, 2).astype("f4"), None, batch_size=2)
    b = NDArrayIter(rng.randn(12, 2).astype("f4"), None, batch_size=2)
    with pytest.raises(mx.MXNetError, match="not the same dataset"):
        b.set_state(a.state())


def test_resize_and_csv_iter_state(tmp_path, rng):
    data = rng.randn(12, 3).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = ResizeIter(base, size=5)
    it.next(); it.next()
    st = it.state()
    it2 = ResizeIter(NDArrayIter(data, None, batch_size=4), size=5)
    it2.set_state(st)
    n = 0
    while True:
        try:
            a, b = it.next(), it2.next()
        except StopIteration:
            break
        np.testing.assert_array_equal(a.data[0].asnumpy(),
                                      b.data[0].asnumpy())
        n += 1
    assert n == 3

    dpath = str(tmp_path / "d.csv")
    np.savetxt(dpath, rng.randn(9, 4).astype("f4"), delimiter=",")
    c = CSVIter(data_csv=dpath, data_shape=(4,), batch_size=3)
    c.next()
    st = c.state()
    c2 = CSVIter(data_csv=dpath, data_shape=(4,), batch_size=3)
    c2.set_state(st)
    np.testing.assert_array_equal(c.next().data[0].asnumpy(),
                                  c2.next().data[0].asnumpy())


def test_mnist_iter_state(tmp_path, rng):
    import gzip, struct
    imgs = (rng.rand(24, 28, 28) * 255).astype("uint8")
    labels = rng.randint(0, 10, 24).astype("uint8")
    ipath, lpath = str(tmp_path / "img.gz"), str(tmp_path / "lbl.gz")
    with gzip.open(ipath, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 24, 28, 28) + imgs.tobytes())
    with gzip.open(lpath, "wb") as f:
        f.write(struct.pack(">II", 2049, 24) + labels.tobytes())
    mx.random.seed(3)
    it = mx.io.MNISTIter(image=ipath, label=lpath, batch_size=4,
                         shuffle=True)
    it.next()
    st = it.state()
    mx.random.seed(77)
    it2 = mx.io.MNISTIter(image=ipath, label=lpath, batch_size=4,
                          shuffle=True)
    it2.set_state(st)
    a, b = it.next(), it2.next()
    np.testing.assert_array_equal(a.data[0].asnumpy(), b.data[0].asnumpy())
    np.testing.assert_array_equal(a.label[0].asnumpy(), b.label[0].asnumpy())


def test_image_record_iter_state(tmp_path, rng):
    rec_path = str(tmp_path / "img.rec")
    idx_path = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(12):
        img = (rng.rand(16, 16, 3) * 255).astype("uint8")
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    kw = dict(path_imgrec=rec_path, path_imgidx=idx_path,
              data_shape=(3, 16, 16), batch_size=4, shuffle=True,
              preprocess_threads=1)
    mx.random.seed(13)
    it = ImageRecordIter(**kw)
    it.next()
    st = it.state()
    assert st["pos"] == 4 and st["epoch"] == 0
    mx.random.seed(555)
    it2 = ImageRecordIter(**kw)
    it2.set_state(st)
    a, b = it.next(), it2.next()
    np.testing.assert_array_equal(a.label[0].asnumpy(), b.label[0].asnumpy())
    np.testing.assert_allclose(a.data[0].asnumpy(), b.data[0].asnumpy())
    # across the epoch boundary the replayed order stays in lockstep
    it.reset(), it2.reset()
    a, b = it.next(), it2.next()
    np.testing.assert_array_equal(a.label[0].asnumpy(), b.label[0].asnumpy())


def test_prefetching_iter_state_credits_inflight_depth(rng):
    """The producer runs up to 4 batches AHEAD of the consumer; state()
    must be the resume point of the last DELIVERED batch, so staged
    batches are neither lost nor duplicated on resume."""
    import time as _time
    data = np.arange(48, dtype="float32").reshape(48, 1)
    mx.random.seed(31)
    it = PrefetchingIter(NDArrayIter(data, None, batch_size=4, shuffle=True,
                                     last_batch_handle="discard"))
    got = [it.next().data[0].asnumpy().ravel() for _ in range(3)]
    _time.sleep(0.2)          # let the producer fill its staging queue
    st = it.state()
    mx.random.seed(400)
    it2 = PrefetchingIter(NDArrayIter(data, None, batch_size=4,
                                      shuffle=True,
                                      last_batch_handle="discard"))
    it2.set_state(st)
    got += [it2.next().data[0].asnumpy().ravel() for _ in range(9)]
    flat = np.sort(np.concatenate(got))
    np.testing.assert_array_equal(flat, np.arange(48, dtype="float32"))
    it.close(), it2.close()


def test_prefetching_iter_reset_not_stranded_by_blocked_producer(rng):
    """Regression (reset race): a producer blocked in Queue.put after the
    drain must observe _stop via its bounded put; reset() verifies thread
    exit BEFORE touching the base iterators."""
    import threading as _threading
    data = np.zeros((400, 1), "float32")
    it = PrefetchingIter(NDArrayIter(data, None, batch_size=2))
    it.next()                         # producer running and queue full
    for _ in range(3):
        t = it._thread
        it.reset()                    # must not hang, must join the thread
        assert not t.is_alive()
        it.next()
    it.close()
    assert not it._thread or not it._thread.is_alive()


def test_prefetching_iter_close_and_context_manager(rng):
    data = np.zeros((64, 2), "float32")
    with PrefetchingIter(NDArrayIter(data, None, batch_size=4)) as it:
        it.next()
        t = it._thread
    assert not t.is_alive()           # no daemon-thread leak
    with pytest.raises(mx.MXNetError, match="closed"):
        it.next()
    it.close()                        # idempotent


def test_libsvm_iter_state(tmp_path):
    p = str(tmp_path / "d.svm")
    with open(p, "w") as f:
        for i in range(6):
            f.write("%d 1:%d 3:%d\n" % (i % 2, i + 1, i + 2))
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(4,), batch_size=2)
    it.next()
    st = it.state()
    it2 = mx.io.LibSVMIter(data_libsvm=p, data_shape=(4,), batch_size=2)
    it2.set_state(st)
    np.testing.assert_array_equal(it.next().label[0].asnumpy(),
                                  it2.next().label[0].asnumpy())


def test_prefetching_iter_terminal_conditions_are_sticky(rng):
    """Regression: once the producer exits (exhaustion OR error), further
    next() calls must re-raise the terminal condition immediately — a retry
    wrapper re-calling next() would otherwise block forever on a queue no
    thread will ever fill."""
    from mxnet_tpu.io import DataIter, DataBatch

    class Bad(DataIter):
        def __init__(self):
            super().__init__(2)
            self.n = 0

        def next(self):
            self.n += 1
            if self.n > 1:
                raise ValueError("decode exploded")
            return DataBatch(data=[nd.array(np.zeros((2, 2), "f4"))])

    p = PrefetchingIter(Bad())
    p.next()
    for _ in range(3):                      # sticky, instant, no hang
        with pytest.raises(ValueError, match="decode exploded"):
            p.next()
    p.close()

    base = NDArrayIter(rng.randn(4, 2).astype("f4"), None, batch_size=2)
    p2 = PrefetchingIter(base)
    assert sum(1 for _ in p2) == 2
    for _ in range(2):                      # exhaustion is sticky too
        with pytest.raises(StopIteration):
            p2.next()
    p2.reset()                              # reset clears the terminal
    assert sum(1 for _ in p2) == 2
    p2.close()
