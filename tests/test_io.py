"""IO tests (reference: tests/python/unittest/test_io.py, test_recordio)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.io import (NDArrayIter, DataBatch, ResizeIter, PrefetchingIter,
                          CSVIter, ImageRecordIter)


def test_ndarray_iter_basic(rng):
    data = rng.randn(29, 3).astype("float32")
    label = rng.randint(0, 5, 29).astype("float32")
    it = NDArrayIter(data, label, batch_size=8, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (8, 3)
    assert batches[-1].pad == 3
    # discard mode drops the last partial batch
    it2 = NDArrayIter(data, label, batch_size=8, last_batch_handle="discard")
    assert len(list(it2)) == 3
    # reset reuses the iterator
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_shuffle_and_dict(rng):
    data = {"a": rng.randn(10, 2).astype("float32"),
            "b": rng.randn(10, 4).astype("float32")}
    it = NDArrayIter(data, None, batch_size=5, shuffle=True)
    batch = next(it)
    assert len(batch.data) == 2
    names = [d.name for d in it.provide_data]
    assert set(names) == {"a", "b"}


def test_resize_iter(rng):
    data = rng.randn(8, 2).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = ResizeIter(base, size=5)
    assert len(list(it)) == 5


def test_prefetching_iter(rng):
    data = rng.randn(16, 2).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = PrefetchingIter(base)
    n = 0
    for batch in it:
        n += 1
        assert batch.data[0].shape == (4, 2)
    assert n == 4
    it.reset()
    assert sum(1 for _ in it) == 4


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(f"record-{i}".encode() * (i + 1))
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == f"record-{i}".encode() * (i + 1)
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(10):
        w.write_idx(i, f"payload{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"payload7"
    assert r.read_idx(2) == b"payload2"
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"data!")
    h2, payload = recordio.unpack(s)
    assert payload == b"data!"
    assert h2.label == 3.0
    assert h2.id == 42
    # multi-label
    h3 = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], dtype="float32"), 7, 0)
    s3 = recordio.pack(h3, b"x")
    h4, p4 = recordio.unpack(s3)
    assert h4.flag == 3
    np.testing.assert_allclose(np.asarray(h4.label), [1, 2, 3])


def test_pack_img_and_image_record_iter(tmp_path, rng):
    rec_path = str(tmp_path / "img.rec")
    idx_path = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(12):
        img = (rng.rand(24, 24, 3) * 255).astype("uint8")
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, img_fmt=".png"))
    w.close()

    it = ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                         data_shape=(3, 16, 16), batch_size=4,
                         preprocess_threads=2)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 16, 16)
        assert batch.label[0].shape == (4,)
        n += 1
    assert n == 3
    it.reset()
    assert sum(1 for _ in it) == 3


def test_csv_iter(tmp_path, rng):
    data = rng.randn(10, 4).astype("float32")
    labels = rng.randint(0, 2, 10).astype("float32")
    dpath = str(tmp_path / "d.csv")
    lpath = str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels, delimiter=",")
    it = CSVIter(data_csv=dpath, data_shape=(4,), label_csv=lpath,
                 batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5], rtol=1e-5)
