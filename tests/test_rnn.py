"""Legacy mx.rnn cell API tests (reference: tests/python/unittest/test_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import rnn


def test_rnn_cell_unroll_shapes():
    cell = rnn.RNNCell(num_hidden=16, prefix='rnn_')
    outputs, states = cell.unroll(3, mx.sym.Variable('data'), layout='NTC',
                                  merge_outputs=True)
    assert sorted(outputs.list_arguments()) == [
        'data', 'rnn_h2h_bias', 'rnn_h2h_weight', 'rnn_i2h_bias', 'rnn_i2h_weight']
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 8))
    assert out_shapes == [(2, 3, 16)]


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(num_hidden=10, prefix='lstm_')
    outputs, states = cell.unroll(4, mx.sym.Variable('data'), merge_outputs=True)
    assert len(states) == 2
    _, out_shapes, _ = outputs.infer_shape(data=(2, 4, 6))
    assert out_shapes == [(2, 4, 10)]


def test_gru_cell_unroll():
    cell = rnn.GRUCell(num_hidden=12, prefix='gru_')
    outputs, _ = cell.unroll(3, mx.sym.Variable('data'), merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(5, 3, 7))
    assert out_shapes == [(5, 3, 12)]


def test_sequential_stack():
    stack = rnn.SequentialRNNCell()
    for i in range(2):
        stack.add(rnn.LSTMCell(num_hidden=8, prefix='lstm_l%d_' % i))
    outputs, states = stack.unroll(3, mx.sym.Variable('data'), merge_outputs=True)
    assert len(states) == 4
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 5))
    assert out_shapes == [(2, 3, 8)]


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(rnn.LSTMCell(num_hidden=6, prefix='l_'),
                                 rnn.LSTMCell(num_hidden=6, prefix='r_'))
    outputs, states = cell.unroll(3, mx.sym.Variable('data'), merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 4))
    assert out_shapes == [(2, 3, 12)]


def test_residual_cell():
    cell = rnn.ResidualCell(rnn.GRUCell(num_hidden=4, prefix='gru_'))
    outputs, _ = cell.unroll(2, mx.sym.Variable('data'), merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(3, 2, 4))
    assert out_shapes == [(3, 2, 4)]


def test_zoneout_cell():
    cell = rnn.ZoneoutCell(rnn.RNNCell(num_hidden=4, prefix='rnn_'),
                           zoneout_outputs=0.3, zoneout_states=0.3)
    outputs, _ = cell.unroll(2, mx.sym.Variable('data'), merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(3, 2, 4))
    assert out_shapes == [(3, 2, 4)]


def test_fused_rnn_shapes():
    cell = rnn.FusedRNNCell(32, num_layers=2, mode='lstm', bidirectional=True,
                            get_next_state=True)
    outputs, states = cell.unroll(7, mx.sym.Variable('data'), layout='NTC',
                                  merge_outputs=True)
    assert outputs.list_arguments() == ['data', 'lstm_parameters']
    _, out_shapes, _ = outputs.infer_shape(data=(4, 7, 10))
    assert out_shapes == [(4, 7, 64)]
    assert len(states) == 2


def test_fused_pack_unpack_roundtrip():
    from mxnet_tpu.ops.rnn import rnn_packed_param_size
    cell = rnn.FusedRNNCell(8, num_layers=2, mode='lstm')
    n = rnn_packed_param_size('lstm', 2, False, 5, 8)
    packed = mx.nd.array(np.random.rand(n).astype('float32'))
    unpacked = cell.unpack_weights({'lstm_parameters': packed})
    assert 'lstm_parameters' not in unpacked
    assert len(unpacked) == 32  # 2 layers x (2 groups x 4 gates) x (w + b)
    repacked = cell.pack_weights(unpacked)
    np.testing.assert_allclose(repacked['lstm_parameters'].asnumpy(),
                               packed.asnumpy(), rtol=1e-6)


def test_fused_matches_unfused():
    """Fused RNN op and the stepped LSTMCell graph must agree numerically."""
    from mxnet_tpu.ops.rnn import rnn_packed_param_size
    T, B, I, H = 3, 2, 4, 5
    fused = rnn.FusedRNNCell(H, num_layers=1, mode='lstm', prefix='lstm_')
    n = rnn_packed_param_size('lstm', 1, False, I, H)
    rs = np.random.RandomState(0)
    packed = mx.nd.array(rs.uniform(-0.5, 0.5, (n,)).astype('float32'))

    data = mx.sym.Variable('data')
    fout, _ = fused.unroll(T, data, layout='NTC', merge_outputs=True)
    x = rs.uniform(-1, 1, (B, T, I)).astype('float32')
    ex = fout.bind(mx.cpu(), {'data': mx.nd.array(x), 'lstm_parameters': packed})
    fused_y = ex.forward(is_train=False)[0].asnumpy()

    unfused = fused.unfuse()
    uout, _ = unfused.unroll(T, mx.sym.Variable('data'), merge_outputs=True)
    args = fused.unpack_weights({'lstm_parameters': packed})
    # unfuse() names cells lstm_l0_; map per-gate weights to stacked i2h/h2h
    bind_args = {'data': mx.nd.array(x)}
    for group in ('i2h', 'h2h'):
        w = np.concatenate([args['lstm_l0_%s%s_weight' % (group, g)].asnumpy()
                            for g in ('_i', '_f', '_c', '_o')], axis=0)
        b = np.concatenate([args['lstm_l0_%s%s_bias' % (group, g)].asnumpy()
                            for g in ('_i', '_f', '_c', '_o')], axis=0)
        bind_args['lstm_l0_%s_weight' % group] = mx.nd.array(w)
        bind_args['lstm_l0_%s_bias' % group] = mx.nd.array(b)
    ex2 = uout.bind(mx.cpu(), bind_args)
    unfused_y = ex2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(fused_y, unfused_y, rtol=1e-4, atol=1e-5)


def test_encode_sentences():
    sents = [['the', 'cat'], ['the', 'dog', 'barks']]
    coded, vocab = rnn.encode_sentences(sents)
    assert len(coded) == 2
    assert coded[0][0] == coded[1][0]  # 'the' same id
    assert len(vocab) == 5  # 4 words + invalid key


def test_bucket_sentence_iter():
    sents = [[1, 2, 3], [2, 3], [1, 2, 3, 4, 5], [3, 4], [1, 2], [2, 2, 2]]
    it = rnn.BucketSentenceIter(sents, batch_size=2, buckets=[3, 5],
                                invalid_label=0)
    assert it.default_bucket_key == 5
    batches = list(it)
    assert all(b.data[0].shape[0] == 2 for b in batches)
    for b in batches:
        assert b.bucket_key in (3, 5)
        assert b.data[0].shape[1] == b.bucket_key
    # labels are data shifted left by one
    it.reset()
    b = next(it)
    d = b.data[0].asnumpy()
    l = b.label[0].asnumpy()
    np.testing.assert_allclose(l[:, :-1], d[:, 1:])
