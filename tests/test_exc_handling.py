"""Async-exception propagation tier (reference
``tests/python/unittest/test_exc_handling.py``): a failing op inside a graph
must surface as MXNetError at a WAIT POINT (asnumpy/wait_to_read/waitall),
must not crash worker threads, and must not poison subsequent independent
work."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import MXNetError, autograd, nd


def test_shape_mismatch_raises_mxnet_error():
    a = nd.ones((2, 3))
    b = nd.ones((4, 5))
    with pytest.raises(Exception):
        nd.dot(a, b).asnumpy()


def test_engine_survives_failed_op(rng):
    """After a failed op the engine keeps scheduling new, independent work
    (reference: failed kernel must not kill the worker thread)."""
    a = nd.ones((2, 3))
    with pytest.raises(Exception):
        nd.dot(a, nd.ones((4, 5))).asnumpy()
    # independent follow-up work is unaffected
    out = nd.dot(a, nd.ones((3, 2))).asnumpy()
    np.testing.assert_allclose(out, np.full((2, 2), 3.0))


class _Failing(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        raise ValueError("intentional custom-op failure")

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise ValueError("intentional custom-op backward failure")


@mx.operator.register("_test_failing_op")
class _FailingProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _Failing()


def test_custom_op_exception_surfaces_at_wait():
    """Python exception inside a CustomOp callback reaches the caller as an
    error at the sync point instead of crashing the process (reference
    test_exc_handling.py custom-op variant)."""
    x = nd.ones((2, 2))
    with pytest.raises(Exception, match="intentional|callback|XlaRuntimeError"):
        out = nd.Custom(x, op_type="_test_failing_op")
        out.asnumpy()          # wait point


def test_custom_op_failure_does_not_poison_engine():
    x = nd.ones((2, 2))
    with pytest.raises(Exception):
        nd.Custom(x, op_type="_test_failing_op").asnumpy()
    np.testing.assert_allclose((x * 2).asnumpy(), np.full((2, 2), 2.0))


def test_symbolic_bind_shape_error_is_mxnet_error():
    a = mx.sym.Variable("a")
    net = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
    # inconsistent: weight shape contradicts data shape
    ex = net.simple_bind(mx.cpu(), a=(2, 3))
    ex.arg_dict["fc_weight"]._set_data(nd.ones((4, 99))._data)
    with pytest.raises(MXNetError):
        ex.forward()
        ex.outputs[0].asnumpy()


def test_autograd_backward_without_forward_raises():
    a = mx.sym.Variable("a")
    net = mx.sym.relu(a)
    ex = net.simple_bind(mx.cpu(), a=(2, 2))
    with pytest.raises(MXNetError):
        ex.backward()


def test_naive_engine_mode_raises_eagerly(rng):
    """NaiveEngine (sync) mode surfaces errors at the op call itself —
    the reference's deterministic replay debugging mode
    (MXNET_ENGINE_TYPE=NaiveEngine)."""
    from mxnet_tpu import engine
    with engine.naive_mode():
        a = nd.ones((2, 3))
        with pytest.raises(Exception):
            nd.dot(a, nd.ones((4, 5)))  # raises HERE, no wait needed
        out = nd.dot(a, nd.ones((3, 2)))
        np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3.0))


def test_waitall_after_error():
    """waitall() after a failed async op must not hang or crash."""
    a = nd.ones((2, 3))
    try:
        nd.dot(a, nd.ones((4, 5)))
    except Exception:
        pass
    nd.waitall()
    np.testing.assert_allclose((a + 1).asnumpy(), np.full((2, 3), 2.0))

def test_waitall_reraises_host_engine_error():
    """waitall must RAISE the first deferred async error, not merely survive
    it (reference ThreadedEngine::WaitForAll re-throw,
    src/engine/threaded_engine.cc:429-481; VERDICT r3 weak #3)."""
    from mxnet_tpu import engine
    v = engine.new_var()
    engine.push(lambda: 1 / 0, mutable_vars=(v,))
    with pytest.raises(MXNetError, match="waitall"):
        nd.waitall()
    # the error was drained: the engine is clean afterwards
    nd.waitall()
    engine.free_var(v)


def test_waitall_reraises_async_device_error(monkeypatch):
    """A device computation that failed asynchronously must surface as
    MXNetError at waitall while the rest of the queue still drains."""
    import jax

    drained = []

    class _Poisoned:
        def block_until_ready(self):
            raise RuntimeError("INTERNAL: injected async device failure")

    class _Deleted:  # lifecycle noise that must NOT become an error
        def block_until_ready(self):
            raise RuntimeError("Array has been deleted.")

    class _Healthy:
        def block_until_ready(self):
            drained.append(True)

    monkeypatch.setattr(jax, "live_arrays",
                        lambda: [_Deleted(), _Poisoned(), _Healthy()])
    with pytest.raises(MXNetError, match="injected async device failure"):
        nd.waitall()
    assert drained == [True]   # queue fully drained despite the failure
