"""ONNX export/import round-trip tests (reference test strategy:
tests/python-pytest/onnx/ — export a model, re-import, compare forward)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.onnx import export_model, import_model
from mxnet_tpu.contrib.onnx.proto import (ModelProto, GraphProto, NodeProto,
                                          TensorProto, AttributeProto,
                                          ValueInfoProto)


def _lenet():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    a1 = mx.sym.Activation(c1, act_type="tanh", name="a1")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2),
                        name="p1")
    f = mx.sym.Flatten(p1, name="flat")
    fc1 = mx.sym.FullyConnected(f, num_hidden=32, name="fc1")
    a2 = mx.sym.Activation(fc1, act_type="relu", name="a2")
    fc2 = mx.sym.FullyConnected(a2, num_hidden=10, name="fc2")
    return mx.sym.softmax(fc2, axis=-1, name="out")


def _init_params(sym, data_shape):
    shapes, _, _ = sym.infer_shape(data=data_shape)
    rng = np.random.RandomState(7)
    params = {}
    for name, shp in zip(sym.list_arguments(), shapes):
        if name == "data":
            continue
        params[name] = mx.nd.array(rng.uniform(-0.1, 0.1, shp)
                                   .astype("float32"))
    return params


def _forward(sym, params, x):
    ex = sym.bind(mx.cpu(), dict(params, data=mx.nd.array(x)))
    return ex.forward(is_train=False)[0].asnumpy()


def test_proto_roundtrip():
    g = GraphProto(name="g")
    g.nodes.append(NodeProto("Conv", "n0", ["x", "w"], ["y"],
                             {"kernel_shape": [3, 3], "alpha": 0.5,
                              "mode": "constant"}))
    g.initializers.append(TensorProto.from_array(
        np.arange(6, dtype=np.float32).reshape(2, 3), "w"))
    g.inputs.append(ValueInfoProto("x", 1, (1, 3, "N", 8)))
    g.outputs.append(ValueInfoProto("y", 1, ()))
    m = ModelProto(graph=g, opset_version=11)
    buf = m.encode()
    m2 = ModelProto.decode(buf)
    assert m2.producer_name == "mxnet_tpu"
    assert m2.opset_imports[0].version == 11
    n = m2.graph.nodes[0]
    assert n.op_type == "Conv" and n.inputs == ["x", "w"]
    assert n.attrs["kernel_shape"] == [3, 3]
    assert abs(n.attrs["alpha"] - 0.5) < 1e-7
    assert n.attrs["mode"] == "constant"
    w = m2.graph.initializers[0].to_array()
    np.testing.assert_array_equal(w, np.arange(6).reshape(2, 3))
    vi = m2.graph.inputs[0]
    assert vi.shape == [1, 3, "N", 8]


def test_export_import_lenet_roundtrip(tmp_path):
    sym = _lenet()
    shape = (2, 1, 16, 16)
    params = _init_params(sym, shape)
    x = np.random.RandomState(3).randn(*shape).astype("float32")
    ref = _forward(sym, params, x)

    path = str(tmp_path / "lenet.onnx")
    export_model(sym, params, shape, np.float32, path)

    sym2, args2, aux2 = import_model(path)
    out = _forward(sym2, {**args2, **aux2}, x)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_export_import_batchnorm_concat(tmp_path):
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", fix_gamma=False,
                          use_global_stats=True)
    br1 = mx.sym.Convolution(bn, kernel=(1, 1), num_filter=4, name="br1")
    br2 = mx.sym.Convolution(bn, kernel=(1, 1), num_filter=4, name="br2")
    cat = mx.sym.Concat(br1, br2, dim=1, name="cat")
    pool = mx.sym.Pooling(cat, global_pool=True, pool_type="avg", name="gap")
    out = mx.sym.Flatten(pool, name="flatout")

    shape = (2, 3, 8, 8)
    shapes, _, _ = out.infer_shape(data=shape)
    rng = np.random.RandomState(11)
    params = {}
    for name, shp in zip(out.list_arguments(), shapes):
        if name == "data":
            continue
        if "moving_var" in name or "var" in name:
            params[name] = mx.nd.array(
                rng.uniform(0.5, 1.5, shp).astype("float32"))
        else:
            params[name] = mx.nd.array(
                rng.uniform(-0.5, 0.5, shp).astype("float32"))
    for name, shp in zip(out.list_auxiliary_states(),
                         out.infer_shape(data=shape)[2]):
        if "var" in name:
            params[name] = mx.nd.array(
                rng.uniform(0.5, 1.5, shp).astype("float32"))
        else:
            params[name] = mx.nd.array(rng.randn(*shp).astype("float32"))

    x = rng.randn(*shape).astype("float32")
    ex = out.bind(mx.cpu(), dict(params, data=mx.nd.array(x)))
    ref = ex.forward(is_train=False)[0].asnumpy()

    path = str(tmp_path / "bn.onnx")
    export_model(out, params, shape, np.float32, path)
    sym2, args2, aux2 = import_model(path)
    ex2 = sym2.bind(mx.cpu(), {**args2, **aux2, "data": mx.nd.array(x)})
    got = ex2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_export_elemwise_scalar(tmp_path):
    a = mx.sym.Variable("a")
    out = (a * 2.0 + 1.5)
    out = mx.sym.relu(out, name="r")
    path = str(tmp_path / "ew.onnx")
    x = np.random.RandomState(0).randn(3, 4).astype("float32")
    export_model(out, {}, (3, 4), np.float32, path)
    sym2, args2, aux2 = import_model(path)
    ex = sym2.bind(mx.cpu(), {**args2, "a": mx.nd.array(x)})
    got = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, np.maximum(x * 2 + 1.5, 0), rtol=1e-6)


def test_export_resnet_zoo(tmp_path):
    """The model-zoo export path the reference advertises (mx2onnx on
    resnet): hybridized gluon net -> Symbol -> onnx file, then re-import
    and numerically compare."""
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.squeezenet1_0(classes=10)
    net.initialize(mx.init.Xavier())
    x = np.random.RandomState(5).randn(1, 3, 64, 64).astype("float32")
    ref = net(mx.nd.array(x)).asnumpy()

    data = mx.sym.Variable("data")
    sym = net(data)
    params = {p.name: p.data() for p in net.collect_params().values()}
    path = str(tmp_path / "squeezenet.onnx")
    export_model(sym, params, x.shape, np.float32, path)

    sym2, args2, aux2 = import_model(path)
    ex = sym2.bind(mx.cpu(), {**args2, **aux2, "data": mx.nd.array(x)})
    got = ex.forward(is_train=False)[0].asnumpy()
    # different op spellings → different XLA fusion → fp32 reassociation
    # noise across 26 conv layers; compare with an absolute tolerance
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-3)


def test_export_import_structural_ops(tmp_path):
    """Round-trip the structural-op family: slice_axis, SliceChannel,
    squeeze/expand_dims, Pad, LRN — the breadth beyond conv nets
    (VERDICT r3: 'opset breadth untested beyond own tests')."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.contrib import onnx as onnx_mod

    data = mx.sym.Variable("data")                      # (B, 4, 6, 6)
    p = mx.sym.pad(data, mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    n = mx.sym.LRN(p, nsize=3, alpha=1e-3, beta=0.75, knorm=1.0)
    parts = mx.sym.SliceChannel(n, num_outputs=2, axis=1)
    left = mx.sym.slice_axis(parts[0], axis=2, begin=1, end=7)
    sq = mx.sym.squeeze(mx.sym.expand_dims(left, axis=0), axis=0)
    right = mx.sym.slice_axis(parts[1], axis=2, begin=1, end=7)
    out = mx.sym.broadcast_add(sq, right)
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (2, 4, 6, 6)).astype("float32")
    want = out.bind(mx.cpu(), {"data": mx.nd.array(x)}).forward()[0].asnumpy()

    path = str(tmp_path / "structural.onnx")
    onnx_mod.export_model(out, {}, [(2, 4, 6, 6)], onnx_file_path=path)
    sym2, args2, aux2 = onnx_mod.import_model(path)
    feed = {"data": mx.nd.array(x)}
    feed.update(args2)
    got = sym2.bind(mx.cpu(), feed,
                    aux_states=aux2 or None).forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_split_three_ways_and_alias(tmp_path):
    """num_outputs=3 round-trips via the importer's output-count inference
    (no 'split' attr on the wire), and mx.sym.split (the alias spelling)
    exports identically."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.contrib import onnx as onnx_mod

    data = mx.sym.Variable("data")
    parts = mx.sym.split(data, num_outputs=3, axis=1)
    out = mx.sym.broadcast_add(mx.sym.broadcast_add(parts[0], parts[1]),
                               parts[2])
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (2, 6, 4)).astype("float32")
    want = out.bind(mx.cpu(), {"data": mx.nd.array(x)}).forward()[0].asnumpy()
    path = str(tmp_path / "split3.onnx")
    onnx_mod.export_model(out, {}, [(2, 6, 4)], onnx_file_path=path)
    sym2, args2, aux2 = onnx_mod.import_model(path)
    got = sym2.bind(mx.cpu(), {"data": mx.nd.array(x), **args2},
                    aux_states=aux2 or None).forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


# ---------------------------------------------------------------------------
# round-5 import breadth (reference _import_helper.py table, ~92 ops)
# ---------------------------------------------------------------------------

def _import_graph(tmp_path, nodes, inits, in_infos, out_names, feeds):
    """Build a ModelProto directly, import it, bind with feeds, forward."""
    g = GraphProto(name="g")
    g.nodes.extend(nodes)
    for name, arr in inits.items():
        g.initializers.append(TensorProto.from_array(np.asarray(arr), name))
        g.inputs.append(ValueInfoProto(name, 1, np.asarray(arr).shape))
    for name, shape in in_infos.items():
        g.inputs.append(ValueInfoProto(name, 1, shape))
    for o in out_names:
        g.outputs.append(ValueInfoProto(o, 1, ()))
    path = str(tmp_path / "m.onnx")
    ModelProto(graph=g, opset_version=11).save(path)
    sym, arg_params, aux_params = import_model(path)
    args = dict(arg_params)
    for k, v in feeds.items():
        args[k] = mx.nd.array(np.asarray(v, dtype="float32"))
    ex = sym.bind(mx.cpu(), args, aux_states=aux_params)
    return [o.asnumpy() for o in ex.forward(is_train=False)]


def test_onnx_import_unary_binary_breadth(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.uniform(0.2, 0.9, (2, 3)).astype("float32")
    y = rng.uniform(0.2, 0.9, (2, 3)).astype("float32")
    cases = [
        ("Sin", np.sin(x)), ("Cos", np.cos(x)), ("Tan", np.tan(x)),
        ("Asin", np.arcsin(x)), ("Acos", np.arccos(x)),
        ("Atan", np.arctan(x)), ("Reciprocal", 1.0 / x),
        ("Softsign", x / (1 + np.abs(x))),
    ]
    for op, want in cases:
        (got,) = _import_graph(
            tmp_path, [NodeProto(op, "n", ["x"], ["out"])], {},
            {"x": x.shape}, ["out"], {"x": x})
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=op)
    for op, want in [("Greater", (x > y)), ("Less", (x < y)),
                     ("Equal", (x == y))]:
        (got,) = _import_graph(
            tmp_path, [NodeProto(op, "n", ["x", "y"], ["out"])], {},
            {"x": x.shape, "y": y.shape}, ["out"], {"x": x, "y": y})
        np.testing.assert_allclose(got, want.astype("float32"), err_msg=op)
    b1 = (x > 0.5).astype("float32")
    b2 = (y > 0.5).astype("float32")
    for op, want in [("And", np.logical_and(b1, b2)),
                     ("Or", np.logical_or(b1, b2)),
                     ("Xor", np.logical_xor(b1, b2))]:
        (got,) = _import_graph(
            tmp_path, [NodeProto(op, "n", ["x", "y"], ["out"])], {},
            {"x": b1.shape, "y": b2.shape}, ["out"], {"x": b1, "y": b2})
        np.testing.assert_allclose(got, want.astype("float32"), err_msg=op)
    (got,) = _import_graph(tmp_path, [NodeProto("Not", "n", ["x"], ["out"])],
                           {}, {"x": b1.shape}, ["out"], {"x": b1})
    np.testing.assert_allclose(got, 1.0 - b1)


def test_onnx_import_reduce_family(tmp_path):
    rng = np.random.RandomState(1)
    x = rng.uniform(0.1, 2.0, (2, 3, 4)).astype("float32")
    cases = [
        ("ReduceSum", x.sum(1, keepdims=True)),
        ("ReduceMax", x.max(1, keepdims=True)),
        ("ReduceMin", x.min(1, keepdims=True)),
        ("ReduceProd", x.prod(1, keepdims=True)),
        ("ReduceMean", x.mean(1, keepdims=True)),
        ("ReduceLogSum", np.log(x.sum(1, keepdims=True))),
        ("ReduceLogSumExp", np.log(np.exp(x).sum(1, keepdims=True))),
        ("ReduceSumSquare", (x ** 2).sum(1, keepdims=True)),
    ]
    for op, want in cases:
        (got,) = _import_graph(
            tmp_path, [NodeProto(op, "n", ["x"], ["out"], {"axes": [1]})],
            {}, {"x": x.shape}, ["out"], {"x": x})
        np.testing.assert_allclose(got, want, rtol=1e-4, err_msg=op)
    for op, want in [("ArgMax", x.argmax(2)[..., None]),
                     ("ArgMin", x.argmin(2)[..., None])]:
        (got,) = _import_graph(
            tmp_path, [NodeProto(op, "n", ["x"], ["out"], {"axis": 2})],
            {}, {"x": x.shape}, ["out"], {"x": x})
        np.testing.assert_allclose(got, want, err_msg=op)


def test_onnx_import_activations_and_norms(tmp_path):
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 4, 4).astype("float32")
    (got,) = _import_graph(
        tmp_path, [NodeProto("Selu", "n", ["x"], ["out"])], {},
        {"x": x.shape}, ["out"], {"x": x})
    a, l = 1.6732632423543772, 1.0507009873554805
    np.testing.assert_allclose(
        got, np.where(x > 0, l * x, l * a * (np.exp(x) - 1)), rtol=1e-5)
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("HardSigmoid", "n", ["x"], ["out"],
                   {"alpha": 0.25, "beta": 0.5})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    np.testing.assert_allclose(got, np.clip(0.25 * x + 0.5, 0, 1), rtol=1e-5)
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("LogSoftmax", "n", ["x"], ["out"], {"axis": 1})],
        {}, {"x": (2, 5)}, ["out"],
        {"x": rng.randn(2, 5).astype("float32")})
    assert np.allclose(np.exp(got).sum(1), 1.0, atol=1e-5)
    gamma = np.array([1.5, 0.5, 2.0], "float32")
    beta = np.array([0.1, -0.2, 0.3], "float32")
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("InstanceNormalization", "n", ["x", "g", "b"], ["out"],
                   {"epsilon": 1e-5})],
        {"g": gamma, "b": beta}, {"x": x.shape}, ["out"], {"x": x})
    m = x.mean(axis=(2, 3), keepdims=True)
    v = x.var(axis=(2, 3), keepdims=True)
    want = gamma[None, :, None, None] * (x - m) / np.sqrt(v + 1e-5) \
        + beta[None, :, None, None]
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("LpNormalization", "n", ["x"], ["out"],
                   {"axis": 1, "p": 2})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    want = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_onnx_import_structural_breadth(tmp_path):
    rng = np.random.RandomState(3)
    x = rng.randn(1, 4, 2, 2).astype("float32")
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("DepthToSpace", "n", ["x"], ["out"], {"blocksize": 2})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    assert got.shape == (1, 1, 4, 4)
    (back,) = _import_graph(
        tmp_path,
        [NodeProto("SpaceToDepth", "n", ["x"], ["out"], {"blocksize": 2})],
        {}, {"x": got.shape}, ["out"], {"x": got})
    np.testing.assert_allclose(back, x, rtol=1e-6)
    (shp,) = _import_graph(
        tmp_path, [NodeProto("Shape", "n", ["x"], ["out"])], {},
        {"x": x.shape}, ["out"], {"x": x})
    np.testing.assert_array_equal(shp, [1, 4, 2, 2])
    (size,) = _import_graph(
        tmp_path, [NodeProto("Size", "n", ["x"], ["out"])], {},
        {"x": x.shape}, ["out"], {"x": x})
    assert int(size.ravel()[0]) == 16
    # Constant feeds a Add downstream
    cval = np.full((2, 2), 3.0, "float32")
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("Constant", "c", [], ["cv"],
                   {"value": TensorProto.from_array(cval, "cv")}),
         NodeProto("Add", "a", ["x2", "cv"], ["out"])],
        {}, {"x2": (2, 2)}, ["out"],
        {"x2": np.ones((2, 2), "float32")})
    np.testing.assert_allclose(got, 4.0)
    # Mean over three inputs
    (got,) = _import_graph(
        tmp_path, [NodeProto("Mean", "m", ["a", "b", "c"], ["out"])], {},
        {"a": (2,), "b": (2,), "c": (2,)}, ["out"],
        {"a": [1., 2.], "b": [3., 4.], "c": [5., 6.]})
    np.testing.assert_allclose(got, [3., 4.])
    # opset-10 input-form Slice with initializer starts/ends
    xs = np.arange(20, dtype="float32").reshape(4, 5)
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("Slice", "s", ["x3", "st", "en", "ax"], ["out"])],
        {"st": np.array([1, 0], "int64"), "en": np.array([3, 4], "int64"),
         "ax": np.array([0, 1], "int64")},
        {"x3": xs.shape}, ["out"], {"x3": xs})
    np.testing.assert_allclose(got, xs[1:3, 0:4])


def test_onnx_import_gemm_forms(tmp_path):
    rng = np.random.RandomState(4)
    a = rng.randn(3, 4).astype("float32")
    c = rng.randn(5).astype("float32")
    for transA in (0, 1):
        for transB in (0, 1):
            A = a if not transA else a.T
            B = rng.randn(4, 5).astype("float32")
            Bv = B if not transB else B.T
            want = 0.5 * (A.T if transA else A) @ \
                (Bv.T if transB else Bv) + 2.0 * c
            (got,) = _import_graph(
                tmp_path,
                [NodeProto("Gemm", "g", ["A", "B", "C"], ["out"],
                           {"alpha": 0.5, "beta": 2.0,
                            "transA": transA, "transB": transB})],
                {"B": Bv, "C": c}, {"A": A.shape}, ["out"], {"A": A})
            np.testing.assert_allclose(got, want, rtol=1e-4,
                                       err_msg=f"t{transA}{transB}")


def test_onnx_import_pool_and_random(tmp_path):
    rng = np.random.RandomState(5)
    x = np.abs(rng.randn(1, 2, 4, 4)).astype("float32")
    (got,) = _import_graph(
        tmp_path,
        [NodeProto("LpPool", "n", ["x"], ["out"],
                   {"kernel_shape": [2, 2], "strides": [2, 2], "p": 2})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    want = np.sqrt((x ** 2).reshape(1, 2, 2, 2, 2, 2)
                   .transpose(0, 1, 2, 4, 3, 5).reshape(1, 2, 4, 4)
                   .reshape(1, 2, 4, 2, 2).sum(-1)
                   .reshape(1, 2, 2, 2, 2).sum(-1))
    assert got.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(np.sort(got.ravel()),
                               np.sort(want.ravel()), rtol=1e-4)
    (gl,) = _import_graph(
        tmp_path,
        [NodeProto("GlobalLpPool", "n", ["x"], ["out"], {"p": 2})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    np.testing.assert_allclose(
        gl.ravel(), np.sqrt((x ** 2).sum(axis=(2, 3))).ravel(), rtol=1e-4)
    # statistical check only for the random family
    (r,) = _import_graph(
        tmp_path,
        [NodeProto("RandomNormal", "n", [], ["out"],
                   {"shape": [2000], "mean": 1.0, "scale": 0.5})],
        {}, {}, ["out"], {})
    assert abs(r.mean() - 1.0) < 0.1 and abs(r.std() - 0.5) < 0.1
    (ru,) = _import_graph(
        tmp_path,
        [NodeProto("RandomUniformLike", "n", ["x"], ["out"],
                   {"low": 2.0, "high": 3.0})],
        {}, {"x": x.shape}, ["out"], {"x": x})
    assert ru.shape == x.shape and 2.0 <= ru.min() and ru.max() <= 3.0


def test_export_import_alexnet_zoo_roundtrip(tmp_path):
    """Second zoo family round-trip (the reference's onnx test zoo walks
    bvlc_alexnet etc.; with zero egress we round-trip our own zoo build)."""
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.alexnet(classes=10)
    net.initialize(mx.init.Xavier())
    x = np.random.RandomState(6).randn(1, 3, 224, 224).astype("float32")
    ref = net(mx.nd.array(x)).asnumpy()
    data = mx.sym.Variable("data")
    sym = net(data)
    params = {p.name: p.data() for p in net.collect_params().values()}
    path = str(tmp_path / "alexnet.onnx")
    export_model(sym, params, x.shape, np.float32, path)
    sym2, args2, aux2 = import_model(path)
    ex = sym2.bind(mx.cpu(), {**args2, **aux2, "data": mx.nd.array(x)})
    got = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-3)


def test_onnx_import_lp_normalization_last_axis(tmp_path):
    """axis=-1 (the ONNX default) must normalize over ONLY the last axis
    for ndim > 2 inputs."""
    rng = np.random.RandomState(8)
    x = rng.randn(2, 3, 4).astype("float32")
    (got,) = _import_graph(
        tmp_path, [NodeProto("LpNormalization", "n", ["x"], ["out"])],
        {}, {"x": x.shape}, ["out"], {"x": x})
    want = x / np.sqrt((x ** 2).sum(-1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
