"""Subgraph partitioning framework tests (reference
tests/python/unittest/test_subgraph_op.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import subgraph as sg


def _bind_forward(sym, feeds):
    ex = sym.bind(mx.cpu(), {k: mx.nd.array(v) for k, v in feeds.items()})
    return [o.asnumpy() for o in ex.forward(is_train=False)]


def _count_ops(sym):
    from collections import Counter
    return Counter(n.op for n in sym.topo_nodes() if n.op)


def test_partition_simple_chain(rng):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.relu((a + b) * a, name="r")
    part = sg.build_subgraph(out, ["elemwise_add", "elemwise_mul", "relu",
                                   "broadcast_add", "broadcast_mul", "_plus",
                                   "_mul"])
    ops = _count_ops(part)
    assert ops.get("_subgraph", 0) == 1
    assert sum(v for k, v in ops.items() if k != "_subgraph") == 0

    av = rng.randn(3, 4).astype("float32")
    bv = rng.randn(3, 4).astype("float32")
    ref = np.maximum((av + bv) * av, 0)
    got = _bind_forward(part, {"a": av, "b": bv})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_partition_partial_selection(rng):
    """Only FC ops grouped; activation stays outside."""
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="act")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")

    part = sg.build_subgraph(fc2, ["FullyConnected"])
    ops = _count_ops(part)
    assert ops["_subgraph"] == 2            # two disjoint FC regions
    assert ops["Activation"] == 1
    assert "FullyConnected" not in ops

    shapes, _, _ = fc2.infer_shape(data=(2, 5))
    feeds = {"data": rng.randn(2, 5).astype("float32")}
    for name, shp in zip(fc2.list_arguments(), shapes):
        if name != "data":
            feeds[name] = rng.randn(*shp).astype("float32") * 0.1
    ref = _bind_forward(fc2, feeds)[0]
    got = _bind_forward(part, feeds)[0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_partition_convexity(rng):
    """Diamond where one branch is unselectable: region must not swallow
    both ends (would create a cycle through the external branch)."""
    a = mx.sym.Variable("a")
    left = a * 2.0                      # selectable (_mul_scalar)
    right = mx.sym.sigmoid(a)           # NOT selectable
    out = left + right                  # selectable add consumes both

    part = sg.build_subgraph(out, ["_mul_scalar", "_plus_scalar",
                                   "broadcast_add", "elemwise_add"])
    av = rng.randn(4).astype("float32")
    ref = av * 2.0 + 1 / (1 + np.exp(-av))
    got = _bind_forward(part, {"a": av})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    ops = _count_ops(part)
    assert ops.get("sigmoid", 0) == 1   # external op survived


def test_partition_multi_output_region(rng):
    """A region output consumed both inside and outside the region."""
    a = mx.sym.Variable("a")
    h = mx.sym.relu(a, name="h")
    o1 = h * 3.0
    out = mx.sym.Group([h, o1])
    part = sg.build_subgraph(out, ["relu", "_mul_scalar"])
    av = rng.randn(5).astype("float32")
    got = _bind_forward(part, {"a": av})
    np.testing.assert_allclose(got[0], np.maximum(av, 0), rtol=1e-6)
    np.testing.assert_allclose(got[1], np.maximum(av, 0) * 3, rtol=1e-6)


def test_property_registry():
    prop = sg.SubgraphProperty(["relu"])
    sg.register_subgraph_property("test_backend", prop)
    assert sg.get_subgraph_property("test_backend") is prop
    with pytest.raises(mx.MXNetError):
        sg.get_subgraph_property("nope")


def test_custom_selector(rng):
    """Selector veto via filter(): regions smaller than 2 nodes dropped."""

    class MinSizeSelector(sg.ContainOpSelector):
        def filter(self, candidates):
            return candidates if len(candidates) >= 2 else []

    class Prop(sg.SubgraphProperty):
        def create_subgraph_selector(self):
            return MinSizeSelector(["relu", "tanh"])

    a = mx.sym.Variable("a")
    lone = mx.sym.relu(a)               # single-node region -> vetoed
    part1 = sg.partition_graph(lone, Prop())
    assert "_subgraph" not in _count_ops(part1)

    pair = mx.sym.tanh(mx.sym.relu(a))  # two-node region -> kept
    part2 = sg.partition_graph(pair, Prop())
    assert _count_ops(part2)["_subgraph"] == 1
