#!/usr/bin/env python
"""Benchmark driver: ResNet-50 training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus diagnostic fields (mfu, flops_per_step, device_kind, overlapped_img_s,
and "degraded" when a fallback path was taken).

Baseline: the reference's headline ResNet-50 ImageNet training number —
109 img/s on 1x K80 at batch 32 (reference example/image-classification/
README.md:149-156, recorded in BASELINE.md).

Robustness contract (the round-1 failure mode): the parent process NEVER
imports jax. The actual benchmark runs in a child process; if the TPU backend
fails to initialize (transient "UNAVAILABLE: TPU backend setup/compile error"
from the axon tunnel) the parent retries once, then falls back to a CPU child,
and in the worst case still emits a well-formed JSON line with a "degraded"
field. A wall-clock budget is split across attempts so the driver's own
timeout is never hit with nothing printed.

The training step is the fused SPMD path (parallel.DataParallelTrainer):
forward+backward+update in one jitted XLA computation, bfloat16 compute with
float32 params/accumulation on TPU.
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

BASELINE_IMG_S = 109.0  # reference ResNet-50, 1x K80, batch 32

# bf16 peak FLOP/s per chip by device_kind substring (public TPU specs).
_PEAK_FLOPS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


# --------------------------------------------------------------------------
# Child: the actual benchmark. Exits 3 quickly if no backend comes up so the
# parent can retry / fall back without burning its budget.
# --------------------------------------------------------------------------
def run_bench():
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the fused ResNet-50 train step takes minutes
    # to compile over the axon tunnel; cache it so retries (and the driver's
    # own bench run on this machine) skip the compile entirely.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("BENCH_CACHE_DIR",
                                         "/tmp/mxtpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        print("compile cache unavailable: %s" % e, file=sys.stderr)

    devices = None
    err = None
    for attempt in range(2):
        try:
            devices = jax.devices()
            break
        except Exception as e:  # backend init failure — retry once in-process
            err = e
            time.sleep(3)
    if devices is None:
        print("BENCH_CHILD_BACKEND_FAIL: %s" % err, file=sys.stderr)
        sys.exit(3)

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    on_accel = any(d.platform != "cpu" for d in devices)
    # batch 256 saturates the MXU far better than the reference's 32
    # (1356 -> 2127 img/s on v5e); per-image math is batch-invariant
    batch = int(os.environ.get("BENCH_BATCH", 256 if on_accel else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_accel else 64))
    steps = int(os.environ.get("BENCH_STEPS", 30 if on_accel else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 5 if on_accel else 1))

    np.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.DataParallelTrainer(
        net, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype="bfloat16" if on_accel else None)

    x = np.random.uniform(-1, 1, (batch, 3, image, image)).astype("float32")
    y = np.random.randint(0, 1000, (batch,)).astype("float32")

    # pre-stage the synthetic batch on device BEFORE warmup (reference
    # benchmark_score.py measures with synthetic device-resident data too);
    # the axon tunnel makes host->device uploads artificially slow and is
    # not what we measure — transfer exactly once.
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = NamedSharding(trainer.mesh, P("dp"))
    t_compile = time.perf_counter()
    loss = trainer.step(x, y)  # capture + lower + compile (first call)
    float(loss)
    print("first step (compile) took %.1fs" % (time.perf_counter() - t_compile),
          file=sys.stderr, flush=True)
    xd = jax.device_put(x, spec)
    yd = jax.device_put(y, spec)
    for _ in range(warmup):
        loss = trainer.step(xd, yd)
    float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(xd, yd)
    float(loss)  # sync
    dt = time.perf_counter() - t0
    img_per_sec = steps * batch / dt

    n_chips = max(1, len([d for d in devices if d.platform != "cpu"]))
    per_chip = img_per_sec / n_chips
    device_kind = devices[0].device_kind

    core = {
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_S, 3),
        "batch": batch, "image": image, "steps": steps,
        "n_chips": n_chips, "device_kind": device_kind,
        "platform": devices[0].platform,
    }
    if not on_accel:
        core["degraded"] = "cpu-only-backend"
    # Emit the measured number NOW — the diagnostics below (cost analysis,
    # overlapped variant) must not be able to cost us the result if they
    # hang; the parent takes the LAST metric line, so the enriched line
    # below supersedes this one when everything goes well.
    print(json.dumps(core), flush=True)

    # ---- MFU from the lowered step's own cost analysis --------------------
    flops_per_step = None
    flops_source = None
    mfu = None
    try:
        lowered = trainer._step_fn.lower(
            trainer._params, trainer._aux, trainer._opt_state,
            jax.random.PRNGKey(0), xd, yd)
        try:
            ca = lowered.cost_analysis()  # compile-free when supported
        except Exception:
            ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if ca:  # some PJRT backends (the axon tunnel) return None
            flops_per_step = float(ca.get("flops", 0.0)) or None
            flops_source = "xla_cost_analysis"
    except Exception as e:
        print("cost_analysis unavailable: %s" % e, file=sys.stderr)
    if flops_per_step is None:
        # analytic fallback: ResNet-50 fwd ~= 4.1 GFLOP/image at 224^2
        # (2 FLOPs per MAC), bwd ~= 2x fwd => ~12.3 GFLOP/image train,
        # scaled for non-default image sizes (conv FLOPs ~ HW)
        per_image = 12.3e9 * (image / 224.0) ** 2
        flops_per_step = per_image * batch
        flops_source = "analytic_2flops_per_mac"
    peak = _peak_flops(device_kind) if on_accel else None
    if flops_per_step and peak:
        achieved = flops_per_step * (steps / dt)
        mfu = achieved / (peak * n_chips)

    # ---- input-pipeline-overlapped variant: host batches, async dispatch --
    overlapped = None
    try:
        # a handful of steps suffices for the diagnostic — at large batch
        # each step ships the full host batch (tunnel-bound here)
        osteps = min(steps, 5)
        host_batches = [
            (np.random.uniform(-1, 1, x.shape).astype("float32"), y)
            for _ in range(3)]
        trainer.step(*host_batches[0])  # warm transfer path
        t0 = time.perf_counter()
        for i in range(osteps):
            hx, hy = host_batches[i % len(host_batches)]
            loss = trainer.step(hx, hy)  # async: upload i+1 overlaps step i
        float(loss)
        overlapped = round(osteps * batch / (time.perf_counter() - t0) /
                           n_chips, 2)
    except Exception as e:
        print("overlapped variant failed: %s" % e, file=sys.stderr)

    out = dict(core)
    if flops_per_step:
        out["flops_per_step"] = flops_per_step
        out["flops_source"] = flops_source
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
        out["peak_flops_assumed"] = peak
    if overlapped is not None:
        out["overlapped_img_s_per_chip"] = overlapped
        if overlapped < 0.5 * core["value"]:
            # per-step host->device transfer dominates (expected through the
            # remote axon tunnel; on a directly-attached chip the async
            # dispatch overlaps it)
            out["overlapped_note"] = "input-transfer bound"
    print(json.dumps(out), flush=True)


# --------------------------------------------------------------------------
# Parent: orchestrates child attempts under a wall-clock budget. No jax here.
# --------------------------------------------------------------------------
def _attempt(env_extra, timeout):
    env = dict(os.environ, **env_extra)
    def last_metric_line(stdout):
        line = None
        for ln in (stdout or "").splitlines():
            ln = ln.strip()
            if ln.startswith("{") and '"metric"' in ln:
                line = ln
        return line

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run"],
            env=env, capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired as exc:
        # the child may have printed a valid measurement before hanging in
        # post-measurement diagnostics — salvage it.
        stdout = exc.stdout.decode(errors="replace") if isinstance(
            exc.stdout, bytes) else (exc.stdout or "")
        stderr = exc.stderr.decode(errors="replace") if isinstance(
            exc.stderr, bytes) else (exc.stderr or "")
        line = last_metric_line(stdout)
        if line:
            try:
                return json.loads(line), None
            except ValueError:
                pass
        return None, "timeout after %ds %s" % (
            timeout, stderr[-400:].replace("\n", " | "))
    line = last_metric_line(proc.stdout)
    if proc.returncode == 0 and line:
        try:
            return json.loads(line), None
        except ValueError:
            pass
    tail = ((proc.stderr or "") + (proc.stdout or ""))[-800:]
    return None, "rc=%d %s" % (proc.returncode, tail.replace("\n", " | "))


def main():
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 2400))
    deadline = time.time() + budget
    errors = []

    # attempt 1 + one retry on the default (TPU) backend; reserve time for
    # the CPU fallback child. The retry hits the persistent compile cache,
    # so it needs far less time than attempt 1.
    reserve = 420.0
    for i in range(2):
        remaining = deadline - time.time() - reserve
        if remaining < 60:
            errors.append("no budget left for TPU attempt %d" % (i + 1))
            break
        # cap attempt 1: a wedged axon tunnel (single-client; a killed
        # handshake can jam it for minutes) must leave real budget for
        # attempt 2 after the tunnel recovers
        cap = 800.0 if i == 0 else 1500.0
        result, err = _attempt({}, timeout=min(cap, remaining))
        if result is not None:
            print(json.dumps(result))
            return
        errors.append("tpu attempt %d: %s" % (i + 1, err))
        time.sleep(5)

    # CPU fallback — hardcoded small shapes so it ALWAYS finishes fast,
    # regardless of any BENCH_* tuning aimed at the TPU attempt.
    remaining = max(60.0, deadline - time.time())
    result, err = _attempt(
        {"BENCH_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
         "BENCH_BATCH": "8", "BENCH_IMAGE": "64", "BENCH_STEPS": "3",
         "BENCH_WARMUP": "1"},
        timeout=min(remaining, reserve))
    if result is not None:
        result["degraded"] = "cpu-fallback: " + "; ".join(errors)[:400]
        print(json.dumps(result))
        return
    errors.append("cpu fallback: %s" % err)

    # worst case: still emit a well-formed line.
    print(json.dumps({
        "metric": "resnet50_train_throughput_per_chip",
        "value": 0.0, "unit": "img/s/chip", "vs_baseline": 0.0,
        "degraded": "all attempts failed: " + "; ".join(errors)[:800],
    }))


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_bench()
    else:
        main()
