#!/usr/bin/env python
"""Benchmark driver: ResNet-50 training throughput on the available device.

Prints JSON lines {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
(the LAST line is the official result) plus diagnostic fields (mfu,
flops_per_step, device_kind, provenance, and "degraded" when a fallback
path was taken).

Baseline: the reference's headline ResNet-50 ImageNet training number —
109 img/s on 1x K80 at batch 32 (reference example/image-classification/
README.md:149-156, recorded in BASELINE.md).

Robustness contract (hardened for round 3; the round-1/2 failure modes):

1. CACHED-FIRST. The parent immediately prints the last-good measurement
   from ``bench_cache.json`` (committed, seeded from the round-2 real-chip
   run) with ``"provenance": "cached"`` before touching any backend, so as
   long as the cache file exists even an instant SIGKILL leaves a parsable
   numeric line on stdout. Every successful live run rewrites the cache.
2. NEVER KILL A TPU CHILD. This machine's axon tunnel is single-client and
   a killed client wedges it for an hour+. The TPU child runs detached
   (its own session, output to files); if it outlives the parent's window
   the parent simply stops waiting — the child keeps running, finishes
   gracefully, and refreshes ``bench_cache.json`` for the next run.
3. BOUNDED LADDER. Default total budget is ~14 minutes: one TPU attempt
   (window ~10 min), then a tiny CPU fallback (~2 min, safe to kill —
   it never touches the tunnel). The parent also traps SIGTERM and emits
   the best-known line before exiting, so an external timeout still
   yields a result.
4. SELF-CLEANING WINDOW. Leftover tunnel clients from OUR OWN tooling
   (aot_warm/perf_lab register their pids via tools/tunnel_session.py)
   are killed by the preflight instead of skipping the live attempt —
   the exact BENCH_r05 failure. "Leftover" = alive past the lifetime the
   tool declared for itself at registration (expected_s: ~30 min for a
   warm, hours for a perf-lab ladder; BENCH_PREFLIGHT_KILL_AGE, default
   1800 s, for undeclared). Active owned clients and genuinely foreign
   processes still cause a skip, never a kill (BENCH_PREFLIGHT_KILL=0
   disables killing entirely). Kills are recorded as "preflight_killed"
   in the emitted row.

The training step is the fused SPMD path (parallel.DataParallelTrainer):
forward+backward+update in one jitted XLA computation, bfloat16 compute with
float32 params/accumulation on TPU.
"""
import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
# session-owned tunnel-client registry (pure stdlib — safe for the parent,
# which must never import jax). Absent in stripped-down copies of bench.py:
# degrade to the old skip-only behavior.
try:
    sys.path.insert(1, os.path.join(HERE, "tools"))
    import tunnel_session as _tunnel
except Exception:
    _tunnel = None

BASELINE_IMG_S = 109.0  # reference ResNet-50, 1x K80, batch 32
CACHE_PATH = os.path.join(HERE, "bench_cache.json")

def _peak_flops(device_kind: str):
    """Per-chip bf16 peak FLOP/s — single source of truth is the perf
    layer's device table (observability/xcost.py, shared with the live MFU
    gauge and the roofline classifier). Only called from the child, where
    mxnet_tpu is imported anyway; the parent never touches it."""
    try:
        from mxnet_tpu.observability.xcost import peak_flops
    except Exception:
        return None
    return peak_flops(device_kind)


def _read_cache():
    try:
        with open(CACHE_PATH) as f:
            data = json.load(f)
        if isinstance(data, dict) and "value" in data and "metric" in data:
            return data
    except Exception:
        pass
    return None


def _write_cache(result):
    """Atomic rewrite of the last-good cache (called from the live child)."""
    try:
        tmp = CACHE_PATH + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, CACHE_PATH)
    except Exception as e:
        print("cache write failed: %s" % e, file=sys.stderr)


# --------------------------------------------------------------------------
# Child: the actual benchmark. Exits 3 quickly if no backend comes up so the
# parent can fall back without burning its budget.
# --------------------------------------------------------------------------
def run_bench():
    import atexit

    def _cleanup_pidfile():
        try:
            with open("/tmp/mxtpu_bench_child.pid") as f:
                if int(f.read().strip()) == os.getpid():
                    os.unlink("/tmp/mxtpu_bench_child.pid")
        except Exception:
            pass

    atexit.register(_cleanup_pidfile)
    soft_deadline = float(os.environ.get("BENCH_CHILD_DEADLINE", 0)) or None

    def time_left():
        return (soft_deadline - time.time()) if soft_deadline else 1e9

    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the fused ResNet-50 train step takes minutes
    # to compile over the axon tunnel; cache it so retries (and the driver's
    # own bench run on this machine) skip the compile entirely.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("BENCH_CACHE_DIR",
                                         "/tmp/mxtpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        print("compile cache unavailable: %s" % e, file=sys.stderr)

    devices = None
    err = None
    for attempt in range(2):
        try:
            devices = jax.devices()
            break
        except Exception as e:  # backend init failure — retry once in-process
            err = e
            time.sleep(3)
    if devices is None:
        print("BENCH_CHILD_BACKEND_FAIL: %s" % err, file=sys.stderr)
        sys.exit(3)

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    on_accel = any(d.platform != "cpu" for d in devices)
    # batch 256 saturates the MXU far better than the reference's 32
    # (1356 -> 2127 img/s on v5e); per-image math is batch-invariant
    batch = int(os.environ.get("BENCH_BATCH", 256 if on_accel else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_accel else 64))
    steps = int(os.environ.get("BENCH_STEPS", 30 if on_accel else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 5 if on_accel else 1))

    # channel-last is the TPU-preferred layout (convs lower to the MXU
    # without layout transposes); overridable for A/B via BENCH_LAYOUT
    layout = os.environ.get("BENCH_LAYOUT", "NHWC" if on_accel else "NCHW")

    np.random.seed(0)
    mx.random.seed(0)   # initializers draw from the framework host stream
    # BENCH_S2D=1 enables the space-to-depth stem (exact 7x7/s2
    # reparameterization, tests/test_s2d_stem.py) — NHWC only
    s2d = os.environ.get("BENCH_S2D") == "1" and layout == "NHWC"
    # BENCH_PASSES=1 measures the graph-pass pipeline INSTEAD of the hand
    # flags: the net is built plain NCHW (like `mxtune --route passes`)
    # and the default pipeline applies layout/s2d as rewrites over the
    # channel-last feed — never both hand flags AND passes, so the row's
    # declared lever config always matches the measured program. Default
    # OFF so bench rows (and the AOT blob digests) stay comparable with
    # earlier rounds. Either way the emitted row stamps the provenance.
    bench_passes = os.environ.get("BENCH_PASSES") == "1"
    if bench_passes:
        from mxnet_tpu.passes import PassManager
        net = vision.resnet50_v1(classes=1000)
        trainer_passes = PassManager(None, input_layout="NHWC")
        layout, s2d = "NHWC", False   # the pipeline decides s2d; the
        #                               passes provenance field records it
    else:
        net = vision.resnet50_v1(classes=1000, layout=layout, stem_s2d=s2d)
        trainer_passes = False
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.DataParallelTrainer(
        net, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype="bfloat16" if on_accel else None, passes=trainer_passes)

    shape = (batch, image, image, 3) if layout == "NHWC" \
        else (batch, 3, image, image)
    x = np.random.uniform(-1, 1, shape).astype("float32")
    y = np.random.randint(0, 1000, (batch,)).astype("float32")

    # pre-stage the synthetic batch on device BEFORE warmup (reference
    # benchmark_score.py measures with synthetic device-resident data too);
    # the axon tunnel makes host->device uploads artificially slow and is
    # not what we measure — transfer exactly once.
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = NamedSharding(trainer.mesh, P("dp"))
    # AOT executable reuse: the fused step takes minutes to compile over a
    # remote-compile tunnel and the persistent HLO cache does NOT survive
    # across processes there — but a serialized executable does
    # (tools/aot_warm.py writes it outside the bench window). Exactly one
    # compile ever happens: aot_save IS the compile when the blob is cold.
    aot_path = os.environ.get(
        "BENCH_AOT", os.path.join(
            HERE, ".bench_aot",
            "resnet50_step_passes.pkl" if bench_passes
            else "resnet50_step_s2d.pkl" if s2d else "resnet50_step.pkl"))
    t_compile = time.perf_counter()
    loaded = False
    if on_accel:   # CPU-fallback compiles are fast; don't pollute the blob
        try:
            os.makedirs(os.path.dirname(aot_path), exist_ok=True)
            loaded = trainer.aot_load(aot_path, x, y)
        except Exception as e:
            print("aot_load failed (will compile): %s" % e, file=sys.stderr)
        if loaded:
            print("AOT executable loaded in %.1fs (compile skipped)"
                  % (time.perf_counter() - t_compile), file=sys.stderr,
                  flush=True)
        else:
            try:
                trainer.aot_save(aot_path, x, y)
            except Exception as e:
                print("aot_save failed (jit fallback): %s" % e,
                      file=sys.stderr)
    loss = trainer.step(x, y)  # AOT: runs the executable; else jit-compiles
    float(loss)
    print("first step (compile) took %.1fs" % (time.perf_counter() - t_compile),
          file=sys.stderr, flush=True)
    xd = jax.device_put(x, spec)
    yd = jax.device_put(y, spec)
    for _ in range(warmup):
        loss = trainer.step(xd, yd)
    float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(xd, yd)
    float(loss)  # sync
    dt = time.perf_counter() - t0
    img_per_sec = steps * batch / dt

    n_chips = max(1, len([d for d in devices if d.platform != "cpu"]))
    per_chip = img_per_sec / n_chips
    device_kind = devices[0].device_kind

    core = {
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_S, 3),
        "batch": batch, "image": image, "steps": steps,
        "layout": layout + ("+s2d" if s2d else ""),
        "n_chips": n_chips, "device_kind": device_kind,
        "platform": devices[0].platform,
        # graph-pass provenance: which rewrite passes (and rewrite counts)
        # produced this step — perfwatch baselines must be attributable to
        # their lever configuration, hand flags and passes alike
        "passes": trainer.passes_provenance(),
    }
    if not on_accel:
        core["degraded"] = "cpu-only-backend"
    # Emit the measured number NOW — the diagnostics below must not be able
    # to cost us the result if they hang; the parent takes the LAST metric
    # line, so the enriched line below supersedes this one when everything
    # goes well.
    print(json.dumps(core), flush=True)
    if on_accel:
        cached = dict(core)
        cached["provenance"] = "last-good live run at %s" % time.strftime(
            "%Y-%m-%dT%H:%MZ", time.gmtime())
        _write_cache(cached)

    # ---- MFU from the lowered step's own cost analysis --------------------
    # FLOPs unification (ISSUE 6): BOTH sources are always recorded — the
    # exact XLA count when the backend delivers one, and the analytic
    # ResNet-50 estimate (fwd ~= 4.1 GFLOP/image at 224^2, 2 FLOPs/MAC,
    # bwd ~= 2x fwd => ~12.3 GFLOP/image, conv FLOPs ~ HW) — and the XLA
    # count is preferred consistently, so MFU numbers stay comparable
    # across rounds whichever source a given window managed to reach.
    flops_analytic = 12.3e9 * (image / 224.0) ** 2 * batch
    flops_xla = None
    ca = None
    lowered = None
    mfu = None
    if time_left() > 60:
        try:
            lowered = trainer._step_fn.lower(
                trainer._params, trainer._aux, trainer._opt_state,
                trainer._guard_state, jax.random.PRNGKey(0), xd, yd)
            try:
                ca = lowered.cost_analysis()  # compile-free when supported
            except Exception:
                ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            if ca:  # some PJRT backends (the axon tunnel) return None
                flops_xla = float(ca.get("flops", 0.0)) or None
        except Exception as e:
            print("cost_analysis unavailable: %s" % e, file=sys.stderr)
            ca = None
    if flops_xla is not None:
        flops_per_step, flops_source = flops_xla, "xla_cost_analysis"
    else:
        flops_per_step, flops_source = flops_analytic, \
            "analytic_2flops_per_mac"
    # source disagreement is an explicit row field, never a silent
    # preference: a drifting analytic model (or an XLA count that stops
    # covering part of the step) shows up in the row, and MFU readers can
    # judge whether cross-round numbers are comparable
    flops_disagreement_pct = None
    if flops_xla is not None and flops_analytic:
        flops_disagreement_pct = round(
            (flops_xla - flops_analytic) / flops_analytic * 100.0, 1)
    peak = _peak_flops(device_kind) if on_accel else None
    if flops_per_step and peak:
        achieved = flops_per_step * (steps / dt)
        mfu = achieved / (peak * n_chips)

    out = dict(core)
    out["flops_per_step"] = flops_per_step
    out["flops_source"] = flops_source
    out["flops_per_step_analytic"] = flops_analytic
    if flops_xla is not None:
        out["flops_per_step_xla"] = flops_xla
    if flops_disagreement_pct is not None:
        out["flops_source_disagreement_pct"] = flops_disagreement_pct
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
        out["peak_flops_assumed"] = peak

    # ---- tuner provenance: when the autotuner cache holds a best measured
    # config for this device kind, stamp it into the row so BENCH_* history
    # records which levers produced the number (and whether this window ran
    # them). tools/mxtune.py writes the cache; missing/foreign = silent.
    try:
        from mxnet_tpu.tuner import best_cached
        # model- AND topology-filtered: a cache row from another model
        # (an mxtune --model tiny smoke) or another chip count must never
        # masquerade as provenance for THIS window's configuration
        tuned = best_cached(device_kind=device_kind, model="resnet50",
                            n_devices=n_chips)
    except Exception as e:
        print("tuner cache lookup failed: %s" % e, file=sys.stderr)
        tuned = None
    if tuned is not None:
        out["tuned_config"] = tuned.get("tuner_config")
        if tuned.get("throughput_img_s_per_chip"):
            out["tuned_img_s_per_chip"] = round(
                float(tuned["throughput_img_s_per_chip"]), 1)

    # ---- cost-ledger row: the bench window is also a compile-time cost
    # capture — the same append-only ledger the trainer's perf layer and
    # the ROADMAP-1 autotuner read (observability/xcost.py)
    if ca:
        try:
            from mxnet_tpu.observability import xcost
            row = xcost.analyze_cost(ca, device_kind=device_kind,
                                     n_devices=n_chips)
            row.update({
                "label": "bench.resnet50",
                "fingerprint": trainer._lowered_digest(lowered),
                "platform": devices[0].platform,
                "batch": batch, "image": image, "layout": core["layout"],
                "throughput_img_s_per_chip": per_chip,
                "measured_step_ms": 1e3 * dt / steps,
            })
            if mfu is not None:
                row["mfu"] = mfu
            ledger_path = os.environ.get("MXNET_PERF_LEDGER") or \
                os.path.join(HERE, "mxtpu_cost_ledger.jsonl")
            xcost.CostLedger(ledger_path).append(row)
            out["cost_ledger"] = ledger_path
        except Exception as e:
            print("cost ledger write failed: %s" % e, file=sys.stderr)

    # ---- input-overlap diagnostic: batches fed host->device DURING compute
    # via the async device feed (reference PrefetcherIter overlap,
    # src/io/iter_prefetcher.h:1; VERDICT r3 weak #2). uint8 on the wire +
    # on-device rescale = the reference's uint8-record pipeline (4x fewer
    # bytes than f32).
    if on_accel and time_left() > 150 and \
            os.environ.get("BENCH_OVERLAP", "1") == "1":
        try:
            import jax.numpy as jnp
            from mxnet_tpu.io import prefetch_to_device

            xu8 = np.random.randint(0, 256, shape).astype("uint8")

            @jax.jit
            def rescale(a):
                return a.astype(jnp.float32) * (2.0 / 255.0) - 1.0

            # pure-wire probe: one synchronous staged batch
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(xu8, spec))
            wire_s = time.perf_counter() - t0
            wire_mbs = xu8.nbytes / wire_s / 1e6
            # per-chip so it compares unit-for-unit with per_chip/ov below
            wire_limit = batch / wire_s / n_chips

            n_feed = max(4, min(10, int(time_left() / max(wire_s, 0.5) / 2)))

            def src():
                for _ in range(n_feed):
                    yield (xu8, y)

            it = prefetch_to_device(src(), sharding=spec, depth=2)
            xb, yb = next(it)           # pipeline fill
            loss = trainer.step(rescale(xb), yb)
            t0 = time.perf_counter()
            n_done = 0
            for xb, yb in it:
                loss = trainer.step(rescale(xb), yb)
                n_done += 1
            float(loss)
            dt = time.perf_counter() - t0
            ov = n_done * batch / dt / n_chips
            compute_limit = per_chip
            bound = min(compute_limit, wire_limit)
            out["overlapped_img_s_per_chip"] = round(ov, 2)
            out["overlap_wire_MBps"] = round(wire_mbs, 1)
            out["overlap_efficiency_vs_bound"] = round(ov / bound, 3)
            out["overlapped_note"] = (
                "wire-bound (uint8 wire %.0f MB/s caps feed at %.0f "
                "img/s/chip)" % (wire_mbs, wire_limit)
                if wire_limit < compute_limit else "compute-bound")
        except Exception as e:
            print("overlap diagnostic failed: %s" % e, file=sys.stderr)

    # ---- int8 inference diagnostic row (VERDICT r2 #7) --------------------
    if on_accel and time_left() > 90 and \
            os.environ.get("BENCH_INT8", "1") == "1":
        try:
            from mxnet_tpu.contrib.quantization import quantized_resnet_bench
            int8_row = quantized_resnet_bench(net, xd, steps=min(steps, 20))
            out.update(int8_row)
            # the same numbers as a label="quant" ledger row, so the tuner
            # cache / mxlint MXL-T215 / perfwatch see on-chip int8 evidence
            try:
                from mxnet_tpu.tuner import get_cache
                i8 = int8_row.get("int8_infer_img_s_per_chip")
                bf = int8_row.get("bf16_infer_img_s_per_chip")
                get_cache().append({
                    "label": "quant", "model": "resnet50",
                    "net_class": type(net).__name__, "batch": batch,
                    "int8_img_s_per_chip": i8, "bf16_img_s_per_chip": bf,
                    "int8_ms": round(batch / i8 * 1e3, 4) if i8 else None,
                    # the non-quantized baseline here is the bench's bf16
                    # run (what the f32 tier actually costs on-chip) —
                    # baseline_dtype says so, readers must not report the
                    # number as a true-f32 measurement
                    "f32_ms": round(batch / bf * 1e3, 4) if bf else None,
                    "baseline_dtype": "bf16",
                    "int8_vs_f32": int8_row.get("int8_vs_bf16"),
                    "device_kind": jax.devices()[0].device_kind,
                    "platform": jax.devices()[0].platform,
                    "provenance": "bench",
                })
            except Exception as e:
                print("int8 ledger row failed: %s" % e, file=sys.stderr)
        except Exception as e:
            print("int8 diagnostic failed: %s" % e, file=sys.stderr)

    print(json.dumps(out), flush=True)
    if on_accel:
        cached = dict(out)
        cached["provenance"] = "last-good live run at %s" % time.strftime(
            "%Y-%m-%dT%H:%MZ", time.gmtime())
        _write_cache(cached)


# --------------------------------------------------------------------------
# Multichip mode: a REAL scaling-efficiency row (img/s/chip at N devices vs
# 1) replacing the empty MULTICHIP_* dryrun tail (ROADMAP item 5). The
# measurement itself lives in mxnet_tpu/parallel/collbench.py (scaling_row)
# so the dryrun harness and tests share it; this mode is the bench-window
# driver around it, plus a collectives bandwidth mini-sweep for the row's
# context. Knobs: BENCH_MC_MODEL=tiny|resnet50, BENCH_MC_BATCH (per chip),
# BENCH_MC_IMAGE, BENCH_MC_STEPS, BENCH_GRAD_REDUCE, BENCH_REDUCE_DTYPE.
# --------------------------------------------------------------------------
def run_multichip():
    import jax
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:
        devices = jax.devices()
    except Exception as e:
        print("BENCH_MC_BACKEND_FAIL: %s" % e, file=sys.stderr)
        return 3
    on_accel = any(d.platform != "cpu" for d in devices)
    from mxnet_tpu.parallel import collbench

    model = os.environ.get("BENCH_MC_MODEL",
                           "resnet50" if on_accel else "tiny")
    if model not in ("tiny", "resnet50"):
        # an unknown knob value must not stamp false model provenance into
        # the row while silently measuring the tiny default net
        print("BENCH_MC_MODEL must be tiny|resnet50, got %r" % model,
              file=sys.stderr)
        return 2
    batch = int(os.environ.get("BENCH_MC_BATCH", 32 if on_accel else 8))
    image = int(os.environ.get("BENCH_MC_IMAGE", 224 if on_accel else 16))
    steps = int(os.environ.get("BENCH_MC_STEPS", 10 if on_accel else 4))
    grad_reduce = os.environ.get("BENCH_GRAD_REDUCE", "reduce_scatter")
    reduce_dtype = os.environ.get("BENCH_REDUCE_DTYPE") or None

    builder = None
    if model == "resnet50":
        def builder(prefix, classes):
            import mxnet_tpu as mx
            from mxnet_tpu import gluon
            from mxnet_tpu.gluon.model_zoo import vision
            mx.random.seed(0)
            net = vision.resnet50_v1(classes=classes, prefix=prefix)
            net.initialize(mx.init.Xavier())
            return net, gluon.loss.SoftmaxCrossEntropyLoss()

    # provenance decided BEFORE the measurement so the ledger-persisted
    # row and the printed row are identical (model-filtered readers must
    # never see a ledger row missing the identity fields)
    extra = {"model": model,
             "provenance": "live multichip run at %s" % time.strftime(
                 "%Y-%m-%dT%H:%MZ", time.gmtime())}
    if not on_accel:
        extra["degraded"] = "cpu-only-backend (virtual-device scaling: " \
            "collective cost is real, chip compute is not)"
    try:
        row = collbench.scaling_row(
            batch_per_chip=batch, image=image, steps=steps,
            grad_reduce=grad_reduce, grad_reduce_dtype=reduce_dtype,
            builder=builder, extra=extra)
    except Exception as e:
        print(json.dumps({"metric": "multichip_scaling_efficiency",
                          "value": 0.0, "unit": "ratio",
                          "degraded": "scaling run failed: %r" % e}),
              flush=True)
        return 1
    print(json.dumps(row), flush=True)
    # context: a small collectives sweep at the same device count, so the
    # efficiency number ships next to the bytes/sec curve explaining it
    if os.environ.get("BENCH_MC_COLLECTIVES", "1") == "1":
        try:
            collbench.run(device_counts=(len(devices),),
                          payload_sizes=(1 << 20,),
                          steps=max(3, steps // 2), warmup=1,
                          compression=0.5,
                          emit=lambda r: print(json.dumps(r), flush=True))
        except Exception as e:
            print("collectives sweep failed: %r" % e, file=sys.stderr)
    return 0


# --------------------------------------------------------------------------
# Parent: orchestrates under a wall-clock budget. No jax is imported here.
# --------------------------------------------------------------------------
def _metric_lines(text):
    out = []
    for ln in (text or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{") and '"metric"' in ln:
            try:
                out.append(json.loads(ln))
            except ValueError:
                pass
    return out


def _foreign_tunnel_clients():
    """OTHER processes that may hold the single-client tunnel (perf_lab /
    aot_warm / tpu session leftovers), as {"name", "pid"} dicts. A second
    concurrent client hangs behind them, so each must either be killed
    (session-owned leftovers, see ``_preflight_clear_tunnel``) or the live
    attempt skipped (genuinely foreign processes)."""
    # ONE source of truth for the marker list: the registry's own MARKERS
    # (every self-registering tool extends it there); the literal fallback
    # only covers stripped-down bench.py copies shipped without tools/
    markers = (_tunnel.MARKERS if _tunnel is not None else
               ("aot_warm.py", "perf_lab.py", "mxtune.py", "collbench.py",
                "mxserve.py", "loadgen.py", "mxquant.py", "mxtrace.py",
                "mxfleet.py", "mxmem.py", "mxrollout.py", "tpu_session"))
    found = []
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == os.getpid():
                continue
            try:
                with open("/proc/%s/cmdline" % pid, "rb") as f:
                    cmd = f.read().decode(errors="replace")
            except OSError:
                continue
            if "python" not in cmd:
                continue      # an editor/tail/grep naming the file is not
                              # a tunnel client; only python processes are
            for m in markers:
                if m in cmd:
                    found.append({"name": m, "pid": int(pid)})
                    break
    except OSError:
        pass
    return found


def _preflight_clear_tunnel(clients):
    """Self-cleaning bench window (the exact BENCH_r05 failure: our own
    leftover aot_warm.py clients made three straight windows skip the live
    attempt). Clients registered in the session registry
    (tools/tunnel_session.py) are OURS — SIGTERM→SIGKILL them and take the
    window; unregistered ones stay untouchable and still skip the live
    attempt. Ownership alone is not leftover-ness: a warm/perf-lab run the
    operator started minutes ago is ACTIVE, and killing it mid-compile
    would be worse than skipping — so a client is only a leftover once its
    registration is older than the lifetime its tool declared for itself
    (``expected_s`` in the registry doc: ~30 min for an aot warm, hours
    for a perf-lab ladder; BENCH_PREFLIGHT_KILL_AGE is the default for
    registrations that declare nothing). Younger owned clients block the
    window like foreign ones. Returns
    (still_blocking, killed_descriptions)."""
    killed = []
    if not clients or _tunnel is None \
            or os.environ.get("BENCH_PREFLIGHT_KILL", "1") != "1":
        return clients, killed
    try:
        owned = _tunnel.owned_pids()
    except Exception:
        return clients, killed
    default_age = float(os.environ.get("BENCH_PREFLIGHT_KILL_AGE", 1800))
    remaining = []
    for c in clients:
        doc = owned.get(c["pid"])
        # a registration without a start stamp is from a torn write —
        # nothing alive refreshes it, so it counts as ancient
        age = (time.time() - float(doc["start"])) if doc and doc.get("start") \
            else float("inf")
        min_age = (float(doc.get("expected_s") or default_age)
                   if doc else default_age)
        if doc is not None and age >= min_age:
            try:
                res = _tunnel.kill(c["pid"])
            except Exception as e:
                res = "error: %s" % e
            killed.append("%s(pid %d): %s" % (c["name"], c["pid"], res))
            if res.startswith("error"):
                remaining.append(c)
        else:
            remaining.append(c)
    return remaining, killed


def _tunnel_preflight(timeout_s):
    """Classify the accelerator backend fast: 'ok' (devices() returned a
    non-cpu platform), 'down' (init raised), 'hung' (no answer within
    timeout_s — the probe is ABANDONED, never killed, because a client
    killed mid-handshake wedges the tunnel for everyone)."""
    out = "/tmp/mxtpu_bench_preflight_%d.out" % os.getpid()
    code = ("import jax\n"
            "ds = jax.devices()\n"
            "print('PREFLIGHT_OK' if any(d.platform != 'cpu' for d in ds)"
            " else 'PREFLIGHT_CPU', flush=True)\n")
    try:
        with open(out, "w") as fo:
            proc = subprocess.Popen([sys.executable, "-c", code], stdout=fo,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
    except Exception:
        return "down"
    cutoff = time.time() + timeout_s
    while time.time() < cutoff:
        if proc.poll() is not None:
            try:
                with open(out) as f:
                    txt = f.read()
            except OSError:
                txt = ""
            if "PREFLIGHT_OK" in txt:
                return "ok"
            if "PREFLIGHT_CPU" in txt:
                return "down"       # only the cpu backend answered
            return "down"
        time.sleep(2)
    return "hung"


def main():
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 840))
    deadline = time.time() + budget
    best = None          # the line we will print LAST (official result)
    printed_final = []   # guard so the SIGTERM handler prints at most once
    live_measurements = []  # any live line (even cpu fallback) this run

    errors = []
    preflight_killed = []   # session-owned leftovers we cleared pre-window

    def emit_final():
        if printed_final:
            return
        printed_final.append(True)
        if best is not None:
            if preflight_killed and "preflight_killed" not in best:
                best["preflight_killed"] = list(preflight_killed)
            # machine-consumer honesty: a cache re-print must be flagged as
            # degraded, not just in the free-form provenance string
            if (str(best.get("provenance", "")).startswith("cached")
                    and "degraded" not in best):
                best["degraded"] = (
                    "cached-official: live run was only a cpu fallback"
                    if live_measurements else
                    "cached-only: no live measurement this run")
            print(json.dumps(best), flush=True)
        else:
            print(json.dumps({
                "metric": "resnet50_train_throughput_per_chip",
                "value": 0.0, "unit": "img/s/chip", "vs_baseline": 0.0,
                "degraded": ("no cache and all live attempts failed: " +
                             "; ".join(errors))[:800],
            }), flush=True)

    def on_term(signum, frame):
        emit_final()
        os._exit(0)

    signal.signal(signal.SIGTERM, on_term)

    # 1. cached-first: a numeric line is on stdout within milliseconds.
    cached = _read_cache()
    if cached is not None:
        line = dict(cached)
        line["provenance"] = "cached: " + str(
            cached.get("provenance", "previous run"))
        print(json.dumps(line), flush=True)
        best = line

    # 2. one detached TPU attempt. NEVER killed — if it outlives the window
    #    we stop waiting and it refreshes bench_cache.json on its own.
    cpu_reserve = float(os.environ.get("BENCH_CPU_RESERVE", 150))
    tpu_window = deadline - time.time() - cpu_reserve
    child_out = os.path.join("/tmp", "mxtpu_bench_child_%d.out" % os.getpid())
    child_err = os.path.join("/tmp", "mxtpu_bench_child_%d.err" % os.getpid())
    pidfile = "/tmp/mxtpu_bench_child.pid"
    orphan = None
    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
        # guard against PID recycling: only a live process whose cmdline is
        # actually this script's --run child counts as an orphan
        with open("/proc/%d/cmdline" % pid, "rb") as f:
            cmd = f.read().decode(errors="replace")
        if "bench.py" in cmd and "--run" in cmd:
            orphan = pid
        else:
            os.unlink(pidfile)
    except Exception:
        try:
            os.unlink(pidfile)
        except OSError:
            pass
    live = None
    foreign, killed = _preflight_clear_tunnel(_foreign_tunnel_clients())
    preflight_killed.extend(killed)
    if killed:
        # recorded in the bench row provenance (emit_final/live rows) AND
        # on stderr for the window log
        print("preflight killed session-owned tunnel client(s): %s"
              % ", ".join(killed), file=sys.stderr)
    preflight = None
    if orphan is None and not foreign \
            and os.environ.get("BENCH_SKIP_TPU") != "1" and tpu_window > 90:
        # health-check the tunnel BEFORE committing the window to a child:
        # the observed failure mode is an init that hangs 25+ minutes and
        # then raises UNAVAILABLE — a child stuck there burns the whole
        # window. A short detached probe classifies the backend fast; a
        # hung probe is abandoned (never killed: a mid-handshake kill
        # wedges the tunnel) and the live attempt skipped.
        preflight = _tunnel_preflight(min(
            float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", 240)),
            tpu_window / 3))
    if orphan is not None:
        # a previous run's TPU child still holds the single-client tunnel;
        # spawning a second client would wedge it — rely on the cache.
        errors.append("previous bench child pid=%d still alive; "
                      "skipping live TPU attempt" % orphan)
    elif foreign:
        # a genuinely foreign tool (not in our session registry) holds the
        # single-client tunnel; a second client would hang behind it, and
        # killing a process we do not own is off the table
        errors.append("foreign tunnel client(s) alive: %s; "
                      "skipping live TPU attempt" % ", ".join(
                          "%s(pid %d)" % (c["name"], c["pid"])
                          for c in foreign))
    elif preflight in ("down", "hung"):
        errors.append("tunnel preflight: backend %s; skipping live TPU "
                      "attempt (cached row stands)" % preflight)
    elif os.environ.get("BENCH_SKIP_TPU") != "1" and tpu_window > 90:
        # preflight consumed part of the window: rebase on the absolute
        # deadline so the child's budget stays honest, and re-check the
        # same 90s floor that gated the attempt in the first place
        tpu_window = deadline - time.time() - cpu_reserve
        if tpu_window <= 90:
            errors.append("window too small after preflight "
                          "(%.0fs); skipping live TPU attempt" % tpu_window)
            tpu_window = 0
    if live is None and orphan is None and not foreign \
            and preflight == "ok" and tpu_window > 90:
        env = dict(os.environ)
        env["BENCH_CHILD_DEADLINE"] = str(time.time() + tpu_window)
        with open(child_out, "w") as fo, open(child_err, "w") as fe:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--run"],
                env=env, stdout=fo, stderr=fe, start_new_session=True)
        with open(pidfile, "w") as f:
            f.write(str(proc.pid))
        cutoff = time.time() + tpu_window
        exited = False
        while time.time() < cutoff:
            if proc.poll() is not None:
                exited = True
                break
            time.sleep(2)
        if exited:
            try:
                os.unlink(pidfile)
            except OSError:
                pass
        try:
            with open(child_out) as f:
                lines = _metric_lines(f.read())
        except Exception:
            lines = []
        if lines:
            live = lines[-1]
            live_measurements.append(live)
            if not exited:
                live["provenance"] = "live (partial: diagnostics still running)"
            else:
                live["provenance"] = "live driver run"
            if preflight_killed:
                live["preflight_killed"] = list(preflight_killed)
        elif exited:
            try:
                with open(child_err) as f:
                    tail = f.read()[-400:].replace("\n", " | ")
            except Exception:
                tail = ""
            errors.append("tpu child rc=%s %s" % (proc.returncode, tail))
        else:
            # still running with no output: tunnel slow/wedged. Do NOT kill —
            # it holds the single-client tunnel; orphan it and move on.
            errors.append("tpu child still initializing at window end "
                          "(left running; it will refresh the cache)")
    elif tpu_window <= 90:
        errors.append("budget too small for a TPU attempt")

    if live is not None and live.get("platform") != "cpu":
        best = live
        emit_final()
        return
    if live is not None:
        # the default-backend child silently came up CPU-only: the TPU
        # backend is down. Reuse its measurement as the CPU sanity check
        # instead of re-running a near-identical CPU child.
        errors.append("default-backend child came up cpu-only (TPU down?)")
        live["degraded"] = "cpu-fallback: " + "; ".join(errors)[:400]
        live["provenance"] = "live cpu (default backend fell back)"
        if best is None:
            best = live
        else:
            print(json.dumps(live), flush=True)
        emit_final()
        return

    # 3. CPU fallback — tiny shapes, safe to kill BECAUSE the axon plugin
    #    is stripped from its environment: JAX_PLATFORMS=cpu alone does NOT
    #    stop the plugin (loaded via PYTHONPATH) from opening the tunnel.
    remaining = deadline - time.time()
    if remaining > 30:
        cpu_env = dict(os.environ, BENCH_FORCE_CPU="1", JAX_PLATFORMS="cpu",
                       BENCH_BATCH="8", BENCH_IMAGE="64", BENCH_STEPS="3",
                       BENCH_WARMUP="1", BENCH_INT8="0")
        cpu_env["PYTHONPATH"] = os.pathsep.join(
            p for p in cpu_env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run"],
                env=cpu_env, capture_output=True, text=True,
                timeout=max(30.0, remaining - 10))
            lines = _metric_lines(proc.stdout)
            if lines:
                cpu_line = lines[-1]
                live_measurements.append(cpu_line)
                cpu_line["degraded"] = ("cpu-fallback: " +
                                        "; ".join(errors)[:400])
                cpu_line["provenance"] = "live cpu fallback"
                # a cached TPU number beats a live CPU number as the official
                # result; surface the CPU sanity check as a diagnostic print.
                if best is None:
                    best = cpu_line
                else:
                    print(json.dumps(cpu_line), flush=True)
            else:
                errors.append("cpu fallback rc=%s %s" % (
                    proc.returncode, (proc.stderr or "")[-300:].replace(
                        "\n", " | ")))
        except subprocess.TimeoutExpired:
            errors.append("cpu fallback timed out")
        except Exception as e:
            errors.append("cpu fallback: %s" % e)

    if best is not None and errors and "degraded" not in best:
        best = dict(best)
        best["live_attempt_errors"] = "; ".join(errors)[:400]
    emit_final()


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_bench()
    elif "--multichip" in sys.argv:
        sys.exit(run_multichip())
    else:
        main()
