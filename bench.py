#!/usr/bin/env python
"""Benchmark driver: ResNet-50 training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Baseline: the reference's headline ResNet-50 ImageNet training number —
109 img/s on 1x K80 at batch 32 (reference example/image-classification/
README.md:149-156, recorded in BASELINE.md).

The training step is the fused SPMD path (parallel.DataParallelTrainer):
forward+backward+update in one jitted XLA computation, bfloat16 compute with
float32 params/accumulation on TPU.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    on_accel = any(d.platform != "cpu" for d in jax.devices())
    batch = int(os.environ.get("BENCH_BATCH", 32 if on_accel else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_accel else 64))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_accel else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 5 if on_accel else 1))

    np.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.DataParallelTrainer(
        net, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype="bfloat16" if on_accel else None)

    x = np.random.uniform(-1, 1, (batch, 3, image, image)).astype("float32")
    y = np.random.randint(0, 1000, (batch,)).astype("float32")

    # pre-stage the synthetic batch on device (reference benchmark_score.py
    # measures with synthetic device-resident data too); the axon tunnel makes
    # host->device uploads artificially slow and is not what we measure.
    from jax.sharding import NamedSharding, PartitionSpec as P
    for _ in range(warmup):
        loss = trainer.step(x, y)
    float(loss)  # sync
    spec = NamedSharding(trainer.mesh, P("dp"))
    xd = jax.device_put(x, spec)
    yd = jax.device_put(y, spec)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(xd, yd)
    float(loss)  # sync
    dt = time.perf_counter() - t0

    img_per_sec = steps * batch / dt
    baseline = 109.0  # img/s, reference 1xK80 batch 32
    n_chips = max(1, len([d for d in jax.devices() if d.platform != "cpu"]))
    print(json.dumps({
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(img_per_sec / n_chips, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(img_per_sec / n_chips / baseline, 3),
    }))


if __name__ == "__main__":
    main()
