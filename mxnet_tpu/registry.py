"""Generic named-class registry (``mx.registry``).

Reference parity: ``python/mxnet/registry.py`` — factory helpers used by
optimizer/metric/initializer registries: register a class under a (lowercase)
name, create an instance from ``name``, ``(name, kwargs)``, a JSON string
``'["name", {...}]'``, or pass through an existing instance.
"""
from __future__ import annotations

import json
import warnings
from typing import Any, Dict, Type

from .base import MXNetError

_REGISTRIES: Dict[type, Dict[str, type]] = {}


def get_registry(base_class: type) -> Dict[str, type]:
    """The (name -> class) dict for a base class (copy-safe view)."""
    return dict(_REGISTRIES.setdefault(base_class, {}))


def get_register_func(base_class: type, nickname: str):
    """Build a ``register(klass, name=None)`` function for ``base_class``."""
    registry = _REGISTRIES.setdefault(base_class, {})

    def register(klass: type, name: str = None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry and registry[name] is not klass:
            warnings.warn(
                "New %s %s.%s registered with name %s is overriding existing "
                "%s %s.%s" % (nickname, klass.__module__, klass.__name__, name,
                              nickname, registry[name].__module__,
                              registry[name].__name__), UserWarning)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname, nickname)
    return register


def get_alias_func(base_class: type, nickname: str):
    """Build an ``alias(*names)`` decorator for ``base_class``."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class: type, nickname: str):
    """Build a ``create(spec, **kwargs)`` factory for ``base_class``."""
    registry = _REGISTRIES.setdefault(base_class, {})

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)

        if isinstance(name, base_class):
            assert len(args) == 0 and len(kwargs) == 0, \
                "%s is already an instance. Additional arguments are invalid" % nickname
            return name

        if isinstance(name, dict):
            return create(**name)

        assert isinstance(name, str), "%s must be of string type" % nickname
        if name.startswith('['):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)

        name = name.lower()
        if name not in registry:
            raise MXNetError("%s is not registered. Please register with "
                             "register.%s first" % (name, nickname))
        return registry[name](*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
