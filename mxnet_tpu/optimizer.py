"""Optimizers.

Reference parity: ``python/mxnet/optimizer/optimizer.py`` (registry, Updater,
SGD/NAG/Adam/AdaGrad/AdaDelta/RMSProp/Ftrl/Signum/FTML/DCASGD/Adamax/Nadam,
multi-precision fp16 master weights) + the fused C++ kernels in
``src/operator/optimizer_op.cc``.

TPU-first: every update rule is a pure jax function jitted per (rule,
hyperparam-signature); scalar hyperparameters that change per step (lr, wd,
rescale) are traced *arguments* so no retrace happens when they change. The
whole update fuses into one XLA kernel per weight — the analogue of the
reference's fused sgd_mom_update kernels — and multi-tensor batches can ride
``jax.jit`` over stacked pytrees in the Trainer fast path.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.ndarray import _unwrap, _wrap

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta", "RMSProp",
           "Ftrl", "Signum", "FTML", "DCASGD", "Adamax", "Nadam", "LBSGD",
           "Test", "create", "register", "Updater", "get_updater"]

_OPT_REGISTRY: Dict[str, type] = {}


def register(klass):
    _OPT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    key = str(name).lower()
    if key not in _OPT_REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}")
    return _OPT_REGISTRY[key](**kwargs)


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}

    # ------------------------------------------------------------- config
    def set_learning_rate(self, lr: float) -> None:
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is active; set lr on the scheduler")
        self.lr = lr

    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult: Dict[Any, float]) -> None:
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[Any, float]) -> None:
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index) -> None:
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            name = self.idx2name[index]
            wd *= self.wd_mult.get(name, 1.0)
            if name.endswith(("_gamma", "_beta", "_bias")):
                pass  # reference applies wd_mult from param attrs; default 1
        return wd

    # ------------------------------------------------------------- state
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == np.float16:
            master, base_state = state
            grad32 = grad.astype("float32")
            self.update(index, master, grad32, base_state)
            weight._set_data(master._data.astype(jnp.float16))
        else:
            self.update(index, weight, grad, state)

    # serialization for kvstore server-side optimizer (reference
    # kvstore_dist_server.h set_optimizer)
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_jit", None)
        return d


def _clipped(grad, rescale, clip):
    grad = grad * rescale
    if clip is not None:
        grad = jnp.clip(grad, -clip, clip)
    return grad


@register
class SGD(Optimizer):
    """SGD with momentum + weight decay (reference optimizer.py:SGD,
    fused kernel src/operator/optimizer_op.cc sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _wrap(jnp.zeros_like(_unwrap(weight)))

    @staticmethod
    @jax.jit
    def _step(w, g, mom, lr, wd, has_clip, clip, rescale, momentum):
        g = g * rescale
        g = jnp.where(has_clip, jnp.clip(g, -clip, clip), g)
        g = g + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = momentum * mom - lr * g
        return w + new_mom, new_mom

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient
        w, g = _unwrap(weight), _unwrap(grad)
        mom = _unwrap(state) if state is not None else None
        new_w, new_mom = self._step(
            w, g, mom, jnp.float32(lr), jnp.float32(wd),
            jnp.bool_(clip is not None), jnp.float32(clip or 1e30),
            jnp.float32(self.rescale_grad), float(self.momentum))
        weight._set_data(new_w)
        if state is not None:
            state._set_data(new_mom)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py:NAG)."""

    @staticmethod
    @jax.jit
    def _step(w, g, mom, lr, wd, has_clip, clip, rescale, momentum):
        g = g * rescale
        g = jnp.where(has_clip, jnp.clip(g, -clip, clip), g)
        g = g + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = momentum * mom + g
        return w - lr * (g + momentum * new_mom), new_mom


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z))

    @staticmethod
    @jax.jit
    def _step(w, g, m, v, lr_t, wd, clip, rescale, beta1, beta2, eps):
        g = g * rescale
        g = jnp.where(jnp.isfinite(clip), jnp.clip(g, -clip, clip), g)
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        return w - lr_t * m / (jnp.sqrt(v) + eps), m, v

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        lr_t = lr * math.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        m, v = state
        new_w, new_m, new_v = self._step(
            _unwrap(weight), _unwrap(grad), _unwrap(m), _unwrap(v),
            jnp.float32(lr_t), jnp.float32(wd),
            jnp.float32(self.clip_gradient if self.clip_gradient else np.inf),
            jnp.float32(self.rescale_grad), self.beta1, self.beta2,
            jnp.float32(self.epsilon))
        weight._set_data(new_w)
        m._set_data(new_m)
        v._set_data(new_v)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _wrap(jnp.zeros_like(_unwrap(weight)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        g = g + wd * _unwrap(weight)
        hist = _unwrap(state) + g * g
        state._set_data(hist)
        weight._set_data(_unwrap(weight) - lr * g / (jnp.sqrt(hist) + self.float_stable_eps))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        acc_g, acc_delta = state
        ag = self.rho * _unwrap(acc_g) + (1 - self.rho) * g * g
        delta = jnp.sqrt(_unwrap(acc_delta) + self.epsilon) / jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * _unwrap(acc_delta) + (1 - self.rho) * delta * delta
        acc_g._set_data(ag)
        acc_delta._set_data(ad)
        weight._set_data(_unwrap(weight) - delta - wd * _unwrap(weight))


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        if self.centered:
            return (_wrap(z), _wrap(z), _wrap(z))
        return _wrap(z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        g = g + wd * _unwrap(weight)
        if self.centered:
            n, gbar, delta = state
            nn = self.gamma1 * _unwrap(n) + (1 - self.gamma1) * g * g
            gb = self.gamma1 * _unwrap(gbar) + (1 - self.gamma1) * g
            d = self.gamma2 * _unwrap(delta) - lr * g / jnp.sqrt(
                nn - gb * gb + self.epsilon)
            n._set_data(nn); gbar._set_data(gb); delta._set_data(d)
            new_w = _unwrap(weight) + d
        else:
            n = state
            nn = (1 - self.gamma1) * g * g + self.gamma1 * _unwrap(n)
            n._set_data(nn)
            new_w = _unwrap(weight) - lr * g / jnp.sqrt(nn + self.epsilon)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        weight._set_data(new_w)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z))  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        z, n = state
        w = _unwrap(weight)
        nn = _unwrap(n) + g * g
        sigma = (jnp.sqrt(nn) - jnp.sqrt(_unwrap(n))) / lr
        zz = _unwrap(z) + g - sigma * w
        z._set_data(zz); n._set_data(nn)
        new_w = jnp.where(
            jnp.abs(zz) > self.lamda1,
            -(zz - jnp.sign(zz) * self.lamda1) /
            ((self.beta + jnp.sqrt(nn)) / lr + wd), 0.0)
        weight._set_data(new_w.astype(w.dtype))


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _wrap(jnp.zeros_like(_unwrap(weight)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        w = _unwrap(weight)
        if state is not None:
            mom = self.momentum * _unwrap(state) - (1 - self.momentum) * (g + wd * w)
            state._set_data(mom)
            new_w = (1 - lr * self.wd_lh) * w + lr * jnp.sign(mom)
        else:
            new_w = (1 - lr * (wd + self.wd_lh)) * w - lr * jnp.sign(g)
        weight._set_data(new_w)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999, epsilon=1e-8,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z), _wrap(z))  # d, v, z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        g = g + wd * _unwrap(weight)
        d, v, z = state
        vv = self.beta2 * _unwrap(v) + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(vv / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * _unwrap(d)
        zz = self.beta1 * _unwrap(z) + (1 - self.beta1) * g - sigma * _unwrap(weight)
        d._set_data(d_t); v._set_data(vv); z._set_data(zz)
        weight._set_data(-zz / d_t)


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z) if self.momentum != 0 else None, _wrap(_unwrap(weight)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        mom, prev = state
        w = _unwrap(weight)
        comp = g + wd * w + self.lamda * g * g * (w - _unwrap(prev))
        if mom is not None:
            m = self.momentum * _unwrap(mom) - lr * comp
            mom._set_data(m)
            new_w = w + m
        else:
            new_w = w - lr * comp
        prev._set_data(w)
        weight._set_data(new_w)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        g = g + wd * _unwrap(weight)
        m, u = state
        mm = self.beta1 * _unwrap(m) + (1 - self.beta1) * g
        uu = jnp.maximum(self.beta2 * _unwrap(u), jnp.abs(g))
        m._set_data(mm); u._set_data(uu)
        weight._set_data(_unwrap(weight) - lr * mm / (uu + 1e-8))


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        z = jnp.zeros_like(_unwrap(weight))
        return (_wrap(z), _wrap(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _clipped(_unwrap(grad), self.rescale_grad, self.clip_gradient)
        g = g + wd * _unwrap(weight)
        mom_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mom_t1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= mom_t
        m_sched_next = self.m_schedule * mom_t1
        m, v = state
        mm = self.beta1 * _unwrap(m) + (1 - self.beta1) * g
        vv = self.beta2 * _unwrap(v) + (1 - self.beta2) * g * g
        g_prime = g / (1 - self.m_schedule)
        m_prime = mm / (1 - m_sched_next)
        v_prime = vv / (1 - self.beta2 ** t)
        m_bar = (1 - mom_t) * g_prime + mom_t1 * m_prime
        m._set_data(mm); v._set_data(vv)
        weight._set_data(_unwrap(weight) - lr * m_bar /
                         (jnp.sqrt(v_prime) + self.epsilon))


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (reference optimizer.py:LBSGD)."""

    def __init__(self, momentum=0.0, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(momentum=momentum, **kwargs)

    def update(self, index, weight, grad, state):
        w = _unwrap(weight)
        g = _unwrap(grad)
        wnorm = jnp.linalg.norm(w)
        gnorm = jnp.linalg.norm(g * self.rescale_grad)
        lars = jnp.where(gnorm > 0, wnorm / (gnorm + 1e-9), 1.0)
        lr_save = self.lr
        try:
            self.lr = float(self.lr * jnp.clip(lars, 0.0, 10.0))
            super().update(index, weight, grad, state)
        finally:
            self.lr = lr_save


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return _wrap(jnp.zeros_like(_unwrap(weight)))

    def update(self, index, weight, grad, state):
        weight._set_data(_unwrap(weight) - self.lr * _unwrap(grad) * self.rescale_grad)


class Updater:
    """Closure applying an optimizer with per-index states (reference
    optimizer.py:Updater; serialized to KVStore servers via get_states)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def __call__(self, index, grad, weight):
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if self._lazy_row_sparse_update(index, grad, weight):
                return
            grad = grad.todense()   # stateful optimizers: standard update
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def _lazy_row_sparse_update(self, index, grad, weight) -> bool:
        """Row-sparse lazy update: touch ONLY the rows present in the
        gradient (reference sparse SGD kernel, optimizer_op.cc SGDUpdateEx
        row_sparse path / optimizer.py lazy_update=True). Supported for
        momentum-free SGD, where untouched rows are genuinely unchanged;
        stateful optimizers fall back to a dense update because their
        per-row state must decay every step."""
        opt = self.optimizer
        # plain lazy SGD only: momentum/delay-compensation/master-copy state
        # must evolve every step, which a touched-rows-only update cannot
        # honor; lazy_update=False requests reference std_update semantics
        # (weight decay applied to EVERY row each step)
        if not (type(opt).__name__ == "SGD"
                and getattr(opt, "momentum", 0) == 0
                and getattr(opt, "lazy_update", True)
                and not getattr(opt, "multi_precision", False)):
            return False
        import jax.numpy as jnp
        opt._update_count(index)
        lr = opt._get_lr(index)
        wd = opt._get_wd(index)
        # merge duplicate indices first — the raw (values, indices) ctor
        # permits them, and todense() sums them, so the lazy path must too
        idx = jnp.asarray(grad._indices).astype(jnp.int32)
        vals = jnp.asarray(grad._values)
        uniq, inv = jnp.unique(idx, return_inverse=True)
        g = jnp.zeros((uniq.shape[0],) + vals.shape[1:],
                      vals.dtype).at[inv].add(vals)
        g = g * opt.rescale_grad
        if getattr(opt, "clip_gradient", None):
            g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
        w = weight._data
        rows = w[uniq]
        weight._set_data(w.at[uniq].set(rows - lr * (g + wd * rows)))
        return True

    def get_states(self, dump_optimizer=False):
        import pickle
        if dump_optimizer:
            return pickle.dumps((self.states, self.optimizer))
        return pickle.dumps(self.states)

    def set_states(self, states):
        import pickle
        obj = pickle.loads(states)
        if isinstance(obj, tuple):
            self.states, self.optimizer = obj
        else:
            self.states = obj


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
