"""``mx.nd`` — imperative array namespace.

Every registered operator is exposed here as a function (generated lazily via
module ``__getattr__``, the analogue of the reference's import-time codegen in
``python/mxnet/ndarray/register.py``). Convention: NDArray positional args are
op inputs; keyword args are attrs; ``out=`` writes into an existing array.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .ndarray import NDArray, array, _wrap, _unwrap
from .utils import (zeros, ones, full, empty, arange, save, load,
                    load_frombuffer, concat, stack, split, one_hot,
                    concatenate, moveaxis)
from . import sparse
from .. import random as _random
from .._imperative import invoke
from ..base import MXNetError
from ..context import Context, current_context
from ..ops.registry import get_op, list_ops, _REGISTRY

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "save", "load", "load_frombuffer", "concat", "stack", "split",
           "one_hot", "waitall", "onehot_encode", "imdecode",
           "from_dlpack", "to_dlpack_for_read", "to_dlpack_for_write",
           "add", "subtract", "multiply", "divide", "true_divide", "modulo",
           "maximum", "minimum", "power", "equal", "not_equal", "greater",
           "greater_equal", "lesser", "lesser_equal", "logical_and",
           "logical_or", "logical_xor", "concatenate", "moveaxis"]


def _scalar_or_elemwise(elem_op, scalar_op, rscalar_op=None):
    """Reference-style top-level binary convenience (ndarray.py:2938-3100
    power/maximum/minimum): dispatch on scalar vs array operands."""
    def fn(lhs, rhs):
        l_scalar = np.isscalar(lhs)
        r_scalar = np.isscalar(rhs)
        if l_scalar and r_scalar:
            raise ValueError("at least one operand must be an NDArray")
        if r_scalar:
            return invoke(scalar_op, [lhs], {"scalar": float(rhs)})
        if l_scalar:
            op = rscalar_op or scalar_op
            return invoke(op, [rhs], {"scalar": float(lhs)})
        return invoke(elem_op, [lhs, rhs], {})
    return fn


maximum = _scalar_or_elemwise("broadcast_maximum", "_maximum_scalar")
minimum = _scalar_or_elemwise("broadcast_minimum", "_minimum_scalar")
power = _scalar_or_elemwise("broadcast_power", "_power_scalar",
                            "_rpower_scalar")
add = _scalar_or_elemwise("broadcast_add", "_plus_scalar")
subtract = _scalar_or_elemwise("broadcast_sub", "_minus_scalar",
                               "_rminus_scalar")
multiply = _scalar_or_elemwise("broadcast_mul", "_mul_scalar")
divide = _scalar_or_elemwise("broadcast_div", "_div_scalar", "_rdiv_scalar")
true_divide = divide
modulo = _scalar_or_elemwise("broadcast_mod", "_mod_scalar", "_rmod_scalar")
equal = _scalar_or_elemwise("broadcast_equal", "_equal_scalar")
not_equal = _scalar_or_elemwise("broadcast_not_equal", "_not_equal_scalar")
greater = _scalar_or_elemwise("broadcast_greater", "_greater_scalar")
greater_equal = _scalar_or_elemwise("broadcast_greater_equal",
                                    "_greater_equal_scalar")
lesser = _scalar_or_elemwise("broadcast_lesser", "_lesser_scalar")
lesser_equal = _scalar_or_elemwise("broadcast_lesser_equal",
                                   "_lesser_equal_scalar")
logical_and = _scalar_or_elemwise("broadcast_logical_and",
                                  "_logical_and_scalar")
logical_or = _scalar_or_elemwise("broadcast_logical_or",
                                 "_logical_or_scalar")
logical_xor = _scalar_or_elemwise("broadcast_logical_xor",
                                  "_logical_xor_scalar")


def onehot_encode(indices, out):
    """Legacy one-hot fill (reference ndarray.py onehot_encode): writes the
    one-hot expansion of ``indices`` into ``out`` and returns it."""
    depth = out.shape[1]
    hot = invoke("one_hot", [indices], {"depth": int(depth)})
    if tuple(hot.shape) != tuple(out.shape):
        raise MXNetError(
            "onehot_encode: output shape %s does not match the one-hot "
            "expansion %s of the given indices" %
            (tuple(out.shape), tuple(hot.shape)))
    out._set_data(hot._data.astype(out.dtype))
    return out


def from_dlpack(ext_tensor) -> NDArray:
    """Zero-copy import of a DLPack tensor (reference from_dlpack).

    Takes a modern DLPack PROVIDER (any object with ``__dlpack__`` /
    ``__dlpack_device__`` — a torch tensor, numpy array, jax array, or the
    view :func:`to_dlpack_for_read` returns). Raw legacy PyCapsules are
    rejected with guidance — the 2018-era capsule protocol predates the
    standardized one every current framework speaks."""
    if type(ext_tensor).__name__ == "PyCapsule":
        raise MXNetError(
            "from_dlpack takes a DLPack provider object (torch tensor, "
            "numpy array, ...), not a raw capsule; pass the tensor itself")
    return NDArray(jnp.from_dlpack(ext_tensor))


def to_dlpack_for_read(arr: NDArray):
    """Export as a DLPack provider; the array is synced first (reference
    to_dlpack_for_read). jax arrays are immutable, so the read/write
    variants coincide; consumers call ``torch.from_dlpack(view)`` /
    ``np.from_dlpack(view)`` on the result."""
    arr.wait_to_read()
    return _unwrap(arr)


def to_dlpack_for_write(arr: NDArray):
    """Export a WRITABLE DLPack provider (reference to_dlpack_for_write).

    jax buffers are immutable, so sharing the live buffer (as the read
    variant does) would let a writable consumer — ``torch.from_dlpack``
    tensors are writable — mutate memory XLA assumes constant. Instead a
    fresh host copy is exported: writes land in the copy, never in the
    source array, and the caller re-imports via :func:`from_dlpack` /
    ``nd.array`` to see them (a divergence from the reference's in-place
    semantics, forced by the functional buffer model)."""
    arr.wait_to_read()
    return np.array(_unwrap(arr))


def imdecode(buf, **kwargs) -> NDArray:
    """Decode an image buffer (reference nd.imdecode; delegates to the
    image module's decoder)."""
    from .. import image as _image
    return _image.imdecode(buf, **kwargs)


def waitall() -> None:
    """Block until all launched work completes (reference Engine::WaitForAll:
    device XLA queues + host task engine, surfacing deferred errors)."""
    from ..engine import wait_all
    wait_all()


def _make_op_func(name: str):
    opdef = get_op(name)

    def fn(*args, out=None, **kwargs):
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif isinstance(a, (np.ndarray, jax.Array)):
                inputs.append(array(a))
            else:
                # positional scalar attr (rare; ops like clip(x, a, b))
                inputs.append(a)
        nds = [x for x in inputs if isinstance(x, NDArray)]
        pos_scalars = [x for x in inputs if not isinstance(x, NDArray)]
        # reference calling convention: tensor arguments may be passed by
        # KEYWORD (`SequenceMask(x, sequence_length=lens)`); lift any
        # array-valued kwarg whose name is a declared tensor arg into the
        # input list at its declared position
        try:
            arg_names = tuple(opdef.arg_names() or ())
        except Exception:
            arg_names = ()
        named = {}
        for k in list(kwargs):
            if k in arg_names and isinstance(kwargs[k],
                                             (NDArray, np.ndarray, jax.Array)):
                v = kwargs.pop(k)
                named[k] = v if isinstance(v, NDArray) else array(v)
        if named:
            slots = {n: named.get(n) for n in arg_names}
            queue = list(nds)
            for n in arg_names:
                if slots[n] is None and queue:
                    slots[n] = queue.pop(0)
            ordered = [slots[n] for n in arg_names]
            # an unfilled slot BEFORE a named one must stay as an explicit
            # None placeholder (e.g. op(data, c=c) with optional middle b),
            # or c would silently shift into b's position
            while ordered and ordered[-1] is None:
                ordered.pop()
            nds = ordered + queue
        if pos_scalars:
            kwargs.setdefault("_pos", tuple(pos_scalars))
            # clip is the only common positional-scalar op
            if name == "clip" and len(pos_scalars) == 2:
                kwargs.pop("_pos")
                kwargs.setdefault("a_min", pos_scalars[0])
                kwargs.setdefault("a_max", pos_scalars[1])
            else:
                kwargs.pop("_pos")
        kwargs.pop("name", None)
        kwargs.pop("ctx", None)
        return invoke(name, nds, kwargs, out=out)

    fn.__name__ = name
    fn.__doc__ = opdef.doc
    return fn


_func_cache = {}


def __getattr__(name: str):
    if name == "contrib":
        import importlib
        return importlib.import_module(__name__ + ".contrib")
    if name == "Custom":
        # frontend-defined op: eager python callback path (mx.operator)
        from ..operator import Custom
        return Custom
    if name not in _REGISTRY and not name.startswith("__"):
        # ops registered by modules outside ops/ resolve lazily (registry
        # _LAZY_PROVIDERS) — mirror the reference where every op name is
        # importable the moment the package loads
        try:
            from ..ops.registry import get_op
            get_op(name)
        except Exception:
            pass
    if name in _REGISTRY:
        if name not in _func_cache:
            _func_cache[name] = _make_op_func(name)
        return _func_cache[name]
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list_ops()))
