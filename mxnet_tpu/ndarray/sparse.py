"""Sparse NDArray: row_sparse and CSR storage.

Reference parity: ``include/mxnet/ndarray.h:61-66`` storage types +
``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray, CSRNDArray,
row_sparse_array/csr_matrix constructors, retain, sparse dot).

TPU-first (SURVEY.md hard part #3): XLA has no sparse HLOs, so
- storage is faithful (values+indices / data+indices+indptr on device),
- CSR matmul lowers through ``jax.experimental.sparse.BCOO`` (XLA
  gather/scatter + segment-sum emulation — the documented strategy),
- row_sparse exists chiefly for the KVStore ``row_sparse_pull`` /
  sparse-gradient pattern: ops that need dense math densify explicitly
  (``tostype('default')``), never silently.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context
from .ndarray import NDArray, array as nd_array, _unwrap, _wrap

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "empty", "retain", "dot"]


class BaseSparseNDArray:
    @property
    def shape(self):
        return self._shape

    def norm(self) -> NDArray:
        """Frobenius norm over stored values (valid because indices are
        duplicate-free by construction)."""
        return _wrap(jnp.sqrt(jnp.sum(self._values.astype(jnp.float32)
                                      ** 2)))

    @property
    def dtype(self):
        return np.dtype(self._values.dtype)

    @property
    def context(self):
        return _wrap(self._values).context

    def asnumpy(self):
        return np.asarray(self.todense()._data)

    def __repr__(self):
        return f"<{type(self).__name__} {'x'.join(map(str, self.shape))}>"


class RowSparseNDArray(BaseSparseNDArray):
    """Rows `indices` hold `values`; all other rows are zero
    (reference ndarray.h kRowSparseStorage)."""

    stype = "row_sparse"

    def __init__(self, values, indices, shape):
        self._values = _unwrap(values) if not isinstance(values, np.ndarray) \
            else jnp.asarray(values)
        self._indices = jnp.asarray(_unwrap(indices)).astype(jnp.int64)
        self._shape = tuple(shape)

    @property
    def data(self) -> NDArray:
        return _wrap(self._values)

    @property
    def indices(self) -> NDArray:
        return _wrap(self._indices)

    def copy(self):
        return RowSparseNDArray(jnp.copy(self._values),
                                jnp.copy(self._indices), self._shape)

    def todense(self) -> NDArray:
        out = jnp.zeros(self._shape, dtype=self._values.dtype)
        return _wrap(out.at[self._indices].add(self._values))

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only rows in row_ids (reference sparse_retain op).

        Sorts the stored index vector then gathers only the requested rows —
        never densifies (a (10M, 512) embedding gradient with a few thousand
        nnz rows stays a few MB)."""
        rid = jnp.asarray(_unwrap(row_ids)).astype(jnp.int64)
        tail = self._values.shape[1:]
        if self._indices.shape[0] == 0:
            vals = jnp.zeros((rid.shape[0],) + tail, dtype=self._values.dtype)
            return RowSparseNDArray(vals, rid, self._shape)
        # stored indices may arrive unsorted from the (values, indices)
        # constructor — sort them (with values) so the searchsorted gather
        # below is valid, then zero-fill requested rows that are absent
        order = jnp.argsort(self._indices)
        sorted_idx = self._indices[order]
        pos = jnp.searchsorted(sorted_idx, rid)
        pos = jnp.clip(pos, 0, sorted_idx.shape[0] - 1)
        present = sorted_idx[pos] == rid
        mask = present.reshape((-1,) + (1,) * len(tail))
        # gather only the |row_ids| requested rows, never a sorted full copy
        vals = jnp.where(mask, self._values[order[pos]],
                         jnp.zeros((), dtype=self._values.dtype))
        return RowSparseNDArray(vals, rid, self._shape)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            # sparse + sparse stays sparse. Rows present in BOTH operands
            # must be summed into one stored row — a raw concat would leave
            # duplicate indices that break every non-linear consumer
            # (square/norm/retain) even though todense() would still be
            # right (reference FComputeEx elemwise_add kRowSparseStorage).
            if other._shape != self._shape:
                raise MXNetError(f"shape mismatch {self._shape} vs "
                                 f"{other._shape}")
            idx = jnp.concatenate([self._indices, other._indices])
            vals = jnp.concatenate([self._values, other._values], axis=0)
            uniq, inv = jnp.unique(idx, return_inverse=True)
            tail = vals.shape[1:]
            merged = jnp.zeros((uniq.shape[0],) + tail,
                               dtype=vals.dtype).at[inv].add(vals)
            return RowSparseNDArray(merged, uniq, self._shape)
        return self.todense() + other

    def __sub__(self, other):
        if isinstance(other, RowSparseNDArray):
            return self + RowSparseNDArray(-other._values, other._indices,
                                           other._shape)
        return self.todense() - other

    def __mul__(self, other):
        if np.isscalar(other):
            return RowSparseNDArray(self._values * other, self._indices,
                                    self._shape)
        return self.todense() * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        if np.isscalar(other):
            return RowSparseNDArray(self._values / other, self._indices,
                                    self._shape)
        return self.todense() / other

    def _unary(self, fn) -> "RowSparseNDArray":
        """Apply a zero-preserving elementwise fn to stored values only
        (reference FComputeEx unary kRowSparseStorage dispatch)."""
        return RowSparseNDArray(fn(self._values), self._indices, self._shape)

    def square(self):
        return self._unary(jnp.square)

    def sqrt(self):
        return self._unary(jnp.sqrt)

    def abs(self):
        return self._unary(jnp.abs)

    def sign(self):
        return self._unary(jnp.sign)

    def clip(self, a_min, a_max):
        if a_min > 0 or a_max < 0:
            raise MXNetError("clip range excluding 0 would densify a "
                             "row_sparse array; convert with "
                             "tostype('default') first")
        return self._unary(lambda v: jnp.clip(v, a_min, a_max))

    def wait_to_read(self):
        self._values.block_until_ready()


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference ndarray.h kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._values = jnp.asarray(_unwrap(data))
        self._indices = jnp.asarray(_unwrap(indices)).astype(jnp.int32)
        self._indptr = jnp.asarray(_unwrap(indptr)).astype(jnp.int32)
        self._shape = tuple(shape)

    @property
    def data(self) -> NDArray:
        return _wrap(self._values)

    @property
    def indices(self) -> NDArray:
        return _wrap(self._indices)

    @property
    def indptr(self) -> NDArray:
        return _wrap(self._indptr)

    def _row_ids(self):
        counts = self._indptr[1:] - self._indptr[:-1]
        return jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self._values.shape[0])

    def _bcoo(self):
        from jax.experimental import sparse as jsparse
        rows = self._row_ids()
        idx = jnp.stack([rows, self._indices.astype(jnp.int64)], axis=1)
        return jsparse.BCOO((self._values, idx), shape=self._shape)

    def todense(self) -> NDArray:
        rows = self._row_ids()
        out = jnp.zeros(self._shape, dtype=self._values.dtype)
        return _wrap(out.at[rows, self._indices].add(self._values))

    def tostype(self, stype: str):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        raise MXNetError(f"cannot convert csr to {stype}")

    def dot(self, rhs, transpose_a=False) -> NDArray:
        """CSR × dense via BCOO matmul (XLA gather/segment-sum lowering)."""
        b = self._bcoo()
        if transpose_a:
            b = b.T
        return _wrap(b @ _unwrap(rhs))

    def wait_to_read(self):
        self._values.block_until_ready()

    def __getitem__(self, i):
        if isinstance(i, slice):
            # row-range slice stays CSR without densifying (reference CSR
            # slice op, matrix_op FComputeEx kCSRStorage)
            start, stop, step = i.indices(self._shape[0])
            if step != 1:
                raise MXNetError("CSR slicing supports step 1 only")
            if stop <= start:  # empty (or inverted) row range
                return CSRNDArray(self._values[:0], self._indices[:0],
                                  jnp.zeros((1,), self._indptr.dtype),
                                  (0, self._shape[1]))
            ptr = self._indptr[start:stop + 1]
            lo, hi = int(ptr[0]), int(ptr[-1])
            return CSRNDArray(self._values[lo:hi], self._indices[lo:hi],
                              ptr - lo, (stop - start, self._shape[1]))
        return self.todense()[i]

    # -------------------------------------------------- sparse arithmetic
    def _coo(self):
        """Host (rows, cols, vals) view — CSR structure manipulation is
        metadata work the reference also runs on CPU kernels."""
        indptr = np.asarray(self._indptr)
        rows = np.repeat(np.arange(self._shape[0], dtype=np.int64),
                         np.diff(indptr))
        return rows, np.asarray(self._indices, np.int64), \
            np.asarray(self._values)

    @staticmethod
    def _merge_coo(rows, cols, vals):
        """Canonicalize: sort by (row, col) and sum duplicate entries (the
        raw csr_matrix ctor performs no canonicalization)."""
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
        if len(rows):
            boundary = np.ones(len(rows), bool)
            boundary[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
            starts = np.flatnonzero(boundary)
            vals = np.add.reduceat(vals, starts)
            rows, cols = rows[starts], cols[starts]
        return rows, cols, vals

    @staticmethod
    def _from_coo(rows, cols, vals, shape, prune_zeros=True):
        rows, cols, vals = CSRNDArray._merge_coo(rows, cols, vals)
        if prune_zeros and len(rows):
            keep = vals != 0
            rows, cols, vals = rows[keep], cols[keep], vals[keep]
        indptr = np.zeros(shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        return CSRNDArray(vals, cols, np.cumsum(indptr), shape)

    def __add__(self, other):
        """csr + csr stays csr (reference ElemwiseBinaryOp csr,csr->csr
        FComputeEx); anything else densifies."""
        if isinstance(other, CSRNDArray):
            if other._shape != self._shape:
                raise MXNetError(f"shape mismatch {self._shape} vs "
                                 f"{other._shape}")
            r1, c1, v1 = self._coo()
            r2, c2, v2 = other._coo()
            return self._from_coo(np.concatenate([r1, r2]),
                                  np.concatenate([c1, c2]),
                                  np.concatenate([v1, v2]), self._shape)
        return self.todense() + other

    def __sub__(self, other):
        if isinstance(other, CSRNDArray):
            return self + CSRNDArray(-other._values, other._indices,
                                     other._indptr, other._shape)
        return self.todense() - other

    def __mul__(self, other):
        """Scalar scaling and csr*csr intersection stay csr; csr * dense
        keeps the sparsity pattern, scaling each stored value by the dense
        element at its position (reference elemwise_mul csr,dense->csr)."""
        if np.isscalar(other):
            return CSRNDArray(self._values * other, self._indices,
                              self._indptr, self._shape)
        if isinstance(other, CSRNDArray):
            if other._shape != self._shape:
                raise MXNetError(f"shape mismatch {self._shape} vs "
                                 f"{other._shape}")
            # sparse intersection on linearized keys — never densifies;
            # canonicalize first so duplicate entries sum before multiplying
            r1, c1, v1 = self._merge_coo(*self._coo())
            r2, c2, v2 = self._merge_coo(*other._coo())
            ncols = self._shape[1]
            k1 = r1 * ncols + c1
            k2 = r2 * ncols + c2
            common, i1, i2 = np.intersect1d(k1, k2, assume_unique=True,
                                            return_indices=True)
            return self._from_coo(common // ncols, common % ncols,
                                  v1[i1] * v2[i2], self._shape)
        dense = np.asarray(other.asnumpy() if hasattr(other, "asnumpy")
                           else other)
        if tuple(dense.shape) != tuple(self._shape):
            raise MXNetError(f"shape mismatch {self._shape} vs "
                             f"{tuple(dense.shape)} (csr * dense requires "
                             "identical shapes)")
        rows, cols, vals = self._coo()
        return self._from_coo(rows, cols, vals * dense[rows, cols],
                              self._shape, prune_zeros=False)

    __rmul__ = __mul__

    def sum(self, axis=None):
        """Reductions without densifying (reference sum FComputeEx csr)."""
        from .ndarray import _wrap
        rows, cols, vals = self._coo()
        if axis is None:
            return _wrap(jnp.asarray(np.asarray(vals).sum()))
        if axis in (0, -2):
            out = np.zeros(self._shape[1], vals.dtype)
            np.add.at(out, cols, vals)
            return _wrap(jnp.asarray(out))
        if axis in (1, -1):
            out = np.zeros(self._shape[0], vals.dtype)
            np.add.at(out, rows, vals)
            return _wrap(jnp.asarray(out))
        raise MXNetError(f"bad axis {axis} for 2-D CSR")

    def mean(self, axis=None):
        n = (np.prod(self._shape) if axis is None
             else self._shape[0] if axis in (0, -2) else self._shape[1])
        return self.sum(axis=axis) / float(n)

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])


def add_n(*arrays):
    """ElementwiseSum over a mixed sparse/dense list (reference
    ElementwiseSum FComputeEx: all-row_sparse stays row_sparse, all-csr
    stays csr, any dense densifies)."""
    if not arrays:
        raise MXNetError("add_n needs at least one array")
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    out = arrays[0]
    for a in arrays[1:]:
        if isinstance(a, BaseSparseNDArray) and type(a) is not type(out):
            a = a.todense()   # dense accumulator or MIXED sparse storage
                              # types: neither +-path can consume the rhs
        out = out + a
    return out


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        if dtype is None:
            # preserve the source dtype (reference: default_dtype = source);
            # bare python lists still default to float32
            dtype = getattr(values, "dtype", "float32")
        values = np.asarray(values, dtype=dtype)
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        return RowSparseNDArray(jnp.asarray(values), jnp.asarray(indices), shape)
    dense = np.asarray(arg1, dtype=dtype or "float32")
    nz_rows = np.where(np.abs(dense).sum(axis=tuple(range(1, dense.ndim))) > 0)[0]
    return RowSparseNDArray(jnp.asarray(dense[nz_rows]), jnp.asarray(nz_rows),
                            dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) needs shape")
        return CSRNDArray(np.asarray(data, dtype=dtype or "float32"),
                          np.asarray(indices), np.asarray(indptr), shape)
    dense = np.asarray(arg1, dtype=dtype or "float32")
    try:
        import scipy.sparse as sp
        m = sp.csr_matrix(dense)
        return CSRNDArray(m.data.astype(dense.dtype), m.indices, m.indptr,
                          dense.shape)
    except ImportError:
        indptr = [0]
        data, indices = [], []
        for row in dense:
            nz = np.nonzero(row)[0]
            indices.extend(nz.tolist())
            data.extend(row[nz].tolist())
            indptr.append(len(indices))
        return CSRNDArray(np.asarray(data, dtype=dense.dtype),
                          np.asarray(indices), np.asarray(indptr), dense.shape)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), jnp.dtype(dtype)),
                                jnp.zeros((0,), jnp.int64), shape)
    if stype == "csr":
        return CSRNDArray(np.zeros(0, dtype), np.zeros(0, "int32"),
                          np.zeros(shape[0] + 1, "int32"), shape)
    from .utils import zeros as dense_zeros
    return dense_zeros(shape, ctx=ctx, dtype=dtype)


empty = zeros


def retain(data: RowSparseNDArray, indices) -> RowSparseNDArray:
    return data.retain(indices)


def dot(lhs, rhs, transpose_a=False, transpose_b=False) -> NDArray:
    if isinstance(lhs, CSRNDArray):
        if transpose_b:
            rhs = rhs.T
        return lhs.dot(rhs, transpose_a=transpose_a)
    from .._imperative import invoke
    return invoke("dot", [lhs, rhs], {"transpose_a": transpose_a,
                                      "transpose_b": transpose_b})
