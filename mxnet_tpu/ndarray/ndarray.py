"""NDArray — the imperative array with dataflow semantics.

Reference parity: ``include/mxnet/ndarray.h:82`` / ``src/ndarray/ndarray.cc``
and the Python frontend ``python/mxnet/ndarray/ndarray.py``.

TPU-first design: the reference's Chunk = {storage handle + engine variable}
becomes simply a ``jax.Array`` — XLA's async dispatch provides the same
observable semantics the C++ dependency engine provides (ops return
immediately; ``wait_to_read`` blocks on the underlying buffer future;
asynchronous errors surface at the next sync point). Mutation (`a[:] = x`,
`a += b`) rebinds the underlying buffer and bumps a version counter, which is
exactly the ThreadedVar version story (threaded_engine.h:115-220) minus the
need for any locks: the old buffer stays alive for whoever recorded it.
"""
from __future__ import annotations

import threading
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .._imperative import invoke, invoke_raw
from ..base import MXNetError
from ..context import Context, current_context

__all__ = ["NDArray", "array", "_wrap", "_unwrap"]

_tls = threading.local()   # set by engine tasks (see operator.Custom)


def _unwrap(x):
    if isinstance(x, NDArray):
        if x._pending is not None:
            x._sync()
        return x._data
    return x


def _wrap(data) -> "NDArray":
    return NDArray(data)


def _to_jax(source_array, ctx: Optional[Context], dtype) -> jax.Array:
    if isinstance(source_array, NDArray):
        data = _unwrap(source_array)
    elif isinstance(source_array, jax.Array):
        data = source_array
    else:
        data = np.asarray(source_array, dtype=dtype if dtype else None)
        if data.dtype == np.float64 and dtype is None:
            data = data.astype(np.float32)  # MXNet default dtype
    dev = (ctx or current_context()).jax_device()
    out = jax.device_put(data, dev)
    if dtype is not None and out.dtype != jnp.dtype(dtype):
        out = out.astype(jnp.dtype(dtype))
    return out


class NDArray:
    """An n-dimensional array on a device, with async execution semantics."""

    __slots__ = ("_data", "_grad", "_ag_node", "_ag_slot", "_version",
                 "_pending", "__weakref__")

    # make numpy defer to our reflected operators (np_array + NDArray etc.)
    __array_priority__ = 100.0

    def __init__(self, data):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._grad: Optional[NDArray] = None
        self._ag_node = None
        self._ag_slot = 0
        self._version = 0
        self._pending = None    # host-engine var an async writer will signal

    # ------------------------------------------------------------- properties
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def stype(self) -> str:
        return "default"

    @property
    def context(self) -> Context:
        dev = list(self._data.devices())[0]
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    # ------------------------------------------------------------- sync / host
    def _sync(self) -> None:
        """Wait for an async host-engine writer (e.g. a CustomOp dispatched
        on the engine pool) to finish filling this array; deferred errors
        re-raise here. Shape/dtype are known before the write completes, so
        only VALUE reads pay this. Inside an engine task the engine's var
        deps already order every access — and a task writing its own output
        must not wait on itself — so the guard is skipped there."""
        pending = self._pending
        if pending is None or getattr(_tls, "in_engine_task", False):
            return
        self._pending = None
        from .. import engine as _engine
        try:
            _engine.wait_var(pending)
        except Exception as e:
            raise MXNetError(
                "async custom-op failure surfaced at read: %s" % e) from e
        finally:
            _engine.free_var(pending)

    def wait_to_read(self) -> None:
        """Block until all pending writes finish (reference
        NDArray::WaitToRead). Async errors raise here."""
        if self._pending is not None:
            self._sync()
        try:
            self._data.block_until_ready()
        except Exception as e:  # surface XLA async errors as MXNetError
            raise MXNetError(str(e)) from e

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        self.wait_to_read()
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("the array is not scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype else a

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size != 1:
            raise MXNetError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of 0-d array")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} " \
               f"@{self.context}>"

    # ------------------------------------------------------------- mutation
    def _set_data(self, data) -> None:
        self._data = data
        self._version += 1

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if self._pending is not None:
            self._sync()
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()))
        other._set_data(jax.device_put(self._data, list(other._data.devices())[0]))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device()))

    as_in_ctx = as_in_context

    def copy(self) -> "NDArray":
        return NDArray(jnp.copy(_unwrap(self)))

    def astype(self, dtype, copy=True) -> "NDArray":
        if not copy and self.dtype == np.dtype(dtype):
            return self
        return NDArray(_unwrap(self).astype(jnp.dtype(dtype)))

    def detach(self) -> "NDArray":
        out = NDArray(_unwrap(self))
        return out

    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        """Allocate a gradient buffer and mark this array as a tape leaf
        (reference MXAutogradMarkVariables)."""
        self._grad = NDArray(jnp.zeros_like(self._data))
        self._ag_node = autograd._Leaf(self, grad_req)
        self._ag_slot = 0
        autograd._register_leaf(self)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True) -> None:
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- indexing
    def __getitem__(self, key) -> "NDArray":
        if isinstance(key, NDArray):
            key = _unwrap(key)
            if jnp.issubdtype(key.dtype, jnp.floating):
                key = key.astype(jnp.int32)
        return NDArray(_unwrap(self)[key])

    def __setitem__(self, key, value) -> None:
        if self._pending is not None:
            self._sync()    # writes must order AFTER the async fill
        if isinstance(key, NDArray):
            key = _unwrap(key).astype(jnp.int32)
        if isinstance(value, NDArray):
            value = _unwrap(value)
        if isinstance(key, slice) and key == slice(None) and not np.isscalar(value):
            value = jnp.asarray(value, dtype=self._data.dtype)
            self._set_data(jnp.broadcast_to(value, self.shape).astype(self._data.dtype))
            return
        self._set_data(self._data.at[key].set(jnp.asarray(value)))

    def slice_assign(self, rhs, begin, end, step=None):
        from ..ops.matrix import _canon_slice
        sl = _canon_slice(self.shape, begin, end, step)
        self._set_data(self._data.at[sl].set(_unwrap(rhs)))
        return self

    # ------------------------------------------------------------- arithmetic
    def _binop(self, op, other, scalar_op=None, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(op, [a, b], {})
        if np.isscalar(other):
            return invoke(scalar_op, [self], {"scalar": float(other)})
        other = NDArray(_to_jax(other, self.context, None))
        a, b = (other, self) if reverse else (self, other)
        return invoke(op, [a, b], {})

    def __add__(self, o): return self._binop("broadcast_add", o, "_plus_scalar")
    def __radd__(self, o): return self._binop("broadcast_add", o, "_plus_scalar")
    def __sub__(self, o): return self._binop("broadcast_sub", o, "_minus_scalar")
    def __rsub__(self, o): return self._binop("broadcast_sub", o, "_rminus_scalar", reverse=True)
    def __mul__(self, o): return self._binop("broadcast_mul", o, "_mul_scalar")
    def __rmul__(self, o): return self._binop("broadcast_mul", o, "_mul_scalar")
    def __truediv__(self, o): return self._binop("broadcast_div", o, "_div_scalar")
    def __rtruediv__(self, o): return self._binop("broadcast_div", o, "_rdiv_scalar", reverse=True)
    def __mod__(self, o): return self._binop("broadcast_mod", o, "_mod_scalar")
    def __rmod__(self, o): return self._binop("broadcast_mod", o, "_rmod_scalar", reverse=True)
    def __pow__(self, o): return self._binop("broadcast_power", o, "_power_scalar")
    def __rpow__(self, o): return self._binop("broadcast_power", o, "_rpower_scalar", reverse=True)
    def __neg__(self): return invoke("negative", [self], {})
    def __abs__(self): return invoke("abs", [self], {})
    def __matmul__(self, o): return invoke("dot", [self, o], {})

    def __eq__(self, o): return self._binop("broadcast_equal", o, "_equal_scalar")
    def __ne__(self, o): return self._binop("broadcast_not_equal", o, "_not_equal_scalar")
    def __gt__(self, o): return self._binop("broadcast_greater", o, "_greater_scalar")
    def __ge__(self, o): return self._binop("broadcast_greater_equal", o, "_greater_equal_scalar")
    def __lt__(self, o): return self._binop("broadcast_lesser", o, "_lesser_scalar")
    def __le__(self, o): return self._binop("broadcast_lesser_equal", o, "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def _inplace(self, op, other, scalar_op):
        res = self._binop(op, other, scalar_op)
        self._set_data(res._data)
        return self

    def __iadd__(self, o): return self._inplace("broadcast_add", o, "_plus_scalar")
    def __isub__(self, o): return self._inplace("broadcast_sub", o, "_minus_scalar")
    def __imul__(self, o): return self._inplace("broadcast_mul", o, "_mul_scalar")
    def __itruediv__(self, o): return self._inplace("broadcast_div", o, "_div_scalar")

    # ------------------------------------------------------------- op methods
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.pop("shape", shape)
        reverse = kwargs.pop("reverse", False)
        return invoke("Reshape", [self], {"shape": tuple(shape), "reverse": reverse})

    def reshape_like(self, other):
        return invoke("reshape_like", [self, other], {})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other], {})

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes or None})

    def flatten(self):
        return invoke("Flatten", [self], {})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def __getattr__(self, name):
        # dynamic method fallback: any registered op becomes a method taking
        # self as first input — mirrors the reference's generated methods.
        from ..ops.registry import _REGISTRY
        if name.startswith("_") or name not in _REGISTRY:
            raise AttributeError(f"NDArray has no attribute {name!r}")
        me = self

        def method(*args, **kwargs):
            ins = [me] + [a for a in args if isinstance(a, NDArray)]
            attrs = {k: v for k, v in kwargs.items()}
            scalars = [a for a in args if not isinstance(a, NDArray)]
            if scalars:
                # positional non-array args are op-specific; only axis-like
                # single values are supported positionally
                if len(scalars) == 1 and "axis" not in attrs:
                    attrs["axis"] = scalars[0]
            out = attrs.pop("out", None)
            return invoke(name, ins, attrs, out=out)

        return method


def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference mx.nd.array)."""
    return NDArray(_to_jax(source_array, ctx, dtype))
