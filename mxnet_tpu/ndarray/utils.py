"""Array creation helpers and serialization.

Reference parity: ``python/mxnet/ndarray/utils.py`` (zeros/ones/save/load) and
the binary list format of ``NDArray::Save/Load``
(``src/ndarray/ndarray.cc:1562-1769``). The on-disk format here is a
self-describing container (magic + dtype/shape header + raw little-endian
buffers); ``mxnet_tpu.util.load_reference_params`` handles the reference's
format for zoo interop.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .ndarray import NDArray, array, _unwrap
from ..context import Context, current_context
from ..base import MXNetError

__all__ = ["zeros", "ones", "full", "empty", "arange", "save", "load",
           "concat", "stack", "split", "one_hot", "concatenate", "moveaxis"]

_MAGIC = b"MXTPU001"


def _shape(shape):
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def zeros(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    dtype = dtype or "float32"
    return array(np.zeros(_shape(shape), dtype=dtype), ctx=ctx)


def ones(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    dtype = dtype or "float32"
    return array(np.ones(_shape(shape), dtype=dtype), ctx=ctx)


def full(shape, val, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    dtype = dtype or "float32"
    return array(np.full(_shape(shape), val, dtype=dtype), ctx=ctx)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    dtype = dtype or "float32"
    out = np.arange(start, stop, step, dtype=dtype)
    if repeat > 1:
        out = np.repeat(out, repeat)
    return array(out, ctx=ctx)


def concat(*arrays, dim=1):
    from .._imperative import invoke
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("Concat", list(arrays), {"dim": dim})


def concatenate(arrays, axis=0):
    return concat(*arrays, dim=axis)


def stack(*arrays, axis=0):
    from .._imperative import invoke
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("stack", list(arrays), {"axis": axis})


def split(ary, indices_or_sections, axis=0):
    from .._imperative import invoke
    if isinstance(indices_or_sections, int):
        return invoke("SliceChannel", [ary],
                      {"num_outputs": indices_or_sections, "axis": axis})
    return invoke("split_v2", [ary],
                  {"indices": tuple(indices_or_sections), "axis": axis})


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    from .._imperative import invoke
    return invoke("one_hot", [indices],
                  {"depth": depth, "on_value": on_value, "off_value": off_value,
                   "dtype": dtype})


def moveaxis(tensor, source, destination):
    ax = list(range(tensor.ndim))
    ax.remove(source % tensor.ndim)
    ax.insert(destination % tensor.ndim, source % tensor.ndim)
    return tensor.transpose(*ax)


# ---------------------------------------------------------------- save / load
def save(fname: str, data) -> None:
    """Save a list or str-keyed dict of NDArrays (reference mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        keys = None
        arrays = list(data)
    metas = []
    blobs = []
    for a in arrays:
        np_a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
        blobs.append(np_a.tobytes())
        metas.append({"shape": list(np_a.shape), "dtype": str(np_a.dtype)})
    header = json.dumps({"keys": keys, "metas": metas}).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for b in blobs:
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _load_stream(f, label):
    """Shared body of :func:`load`/:func:`load_frombuffer`: parse either
    this framework's container or the reference's (via interop)."""
    magic = f.read(8)
    if magic != _MAGIC:
        import tempfile
        from .. import interop
        f.seek(0)
        data = f.read()
        with tempfile.NamedTemporaryFile(suffix=".params") as tmp:
            tmp.write(data)
            tmp.flush()
            if interop.is_reference_params_file(tmp.name):
                arrays, names = interop.load_reference_ndarrays(tmp.name)
                return dict(zip(names, arrays)) if names else arrays
        raise MXNetError(f"{label}: not a mxnet_tpu NDArray file "
                         f"(bad magic {magic!r}) and not a reference "
                         f".params file either")
    (hlen,) = struct.unpack("<Q", f.read(8))
    header = json.loads(f.read(hlen).decode())
    arrays = []
    for meta in header["metas"]:
        (blen,) = struct.unpack("<Q", f.read(8))
        buf = f.read(blen)
        np_a = np.frombuffer(buf, dtype=meta["dtype"]).reshape(meta["shape"])
        arrays.append(array(np_a))
    if header["keys"] is None:
        return arrays
    return dict(zip(header["keys"], arrays))


def load(fname: str):
    """Load NDArrays saved by :func:`save` — or by the reference's
    ``mx.nd.save`` (the dmlc ``0x112`` list container, auto-detected and
    routed through :mod:`mxnet_tpu.interop`); returns list or dict."""
    with open(fname, "rb") as f:
        return _load_stream(f, fname)


def load_frombuffer(buf: bytes):
    """Load NDArrays from an in-memory file image (reference
    ``nd.load_frombuffer``) — same container auto-detection as
    :func:`load`."""
    import io
    return _load_stream(io.BytesIO(buf), "<buffer>")
