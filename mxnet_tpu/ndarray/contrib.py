"""``mx.nd.contrib`` — experimental-op namespace.

Mirrors the reference's generated ``mxnet.ndarray.contrib`` module
(``python/mxnet/ndarray/register.py`` puts every ``_contrib_*`` registration
under the ``contrib`` namespace): ``mx.nd.contrib.MultiBoxPrior(...)`` calls
the op registered as ``_contrib_MultiBoxPrior``.
"""
from __future__ import annotations

from ..ops.registry import _REGISTRY


def __getattr__(name: str):
    if name in ("foreach", "while_loop", "cond"):
        from ..contrib import control_flow as _cf
        return getattr(_cf, name)
    if name.startswith("dgl_"):
        # graph-sampling ops take/return CSRNDArrays — host functions, not
        # registry ops (reference: CPU-only FComputeEx, dgl_graph.cc)
        from ..contrib import dgl as _dgl
        if hasattr(_dgl, name):
            return getattr(_dgl, name)
    from . import __getattr__ as _nd_getattr
    for cand in (f"_contrib_{name}", f"contrib_{name}"):
        try:   # the nd getattr handles lazy-provider resolution itself
            return _nd_getattr(cand)
        except AttributeError:
            continue
    raise AttributeError(
        f"module 'mxnet_tpu.ndarray.contrib' has no attribute {name!r}")


def __dir__():
    return sorted(n[len("_contrib_"):] for n in _REGISTRY
                  if n.startswith("_contrib_"))
