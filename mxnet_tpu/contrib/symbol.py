"""``mx.contrib.symbol`` — contrib symbolic namespace alias (see
``mx.sym.contrib``)."""
from ..symbol.contrib import __getattr__, __dir__  # noqa: F401
