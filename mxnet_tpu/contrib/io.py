"""Contrib IO (reference ``python/mxnet/contrib/io.py``:
DataLoaderIter — wraps a gluon DataLoader in the DataIter interface so
Module-based training loops can consume Dataset/DataLoader pipelines)."""
from __future__ import annotations

from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """DataIter view over a ``gluon.data.DataLoader`` (reference io.py:30)."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super().__init__(batch_size=getattr(loader, "_batch_size", 0))
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        self._first = None
        try:
            self._first = next(self._iter)
        except StopIteration:
            raise ValueError("empty DataLoader")

    def _descs(self, sample, name):
        return [DataDesc(name, tuple(sample.shape))]

    @property
    def provide_data(self):
        return self._descs(self._first[0], self._data_name)

    @property
    def provide_label(self):
        return self._descs(self._first[1], self._label_name)

    def reset(self):
        self._iter = iter(self._loader)

    def next(self):
        if self._first is not None:
            data, label = self._first
            self._first = None
        else:
            try:
                data, label = next(self._iter)
            except StopIteration:
                raise StopIteration
        return DataBatch(data=[data], label=[label], pad=0)
