"""Contrib IO (reference ``python/mxnet/contrib/io.py``:
DataLoaderIter — wraps a gluon DataLoader in the DataIter interface so
Module-based training loops can consume Dataset/DataLoader pipelines)."""
from __future__ import annotations

from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """DataIter view over a ``gluon.data.DataLoader`` (reference io.py:30)."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        # peek one batch ONLY for shape metadata; iteration always restarts
        # from a fresh loader iterator, so nothing is duplicated or skipped
        try:
            first = next(iter(loader))
        except StopIteration:
            raise ValueError("empty DataLoader")
        super().__init__(batch_size=int(first[0].shape[0]))
        self._loader = loader
        self._data_descs = [DataDesc(data_name, tuple(first[0].shape))]
        self._label_descs = [DataDesc(label_name, tuple(first[1].shape))]
        self._iter = iter(loader)

    @property
    def provide_data(self):
        return self._data_descs

    @property
    def provide_label(self):
        return self._label_descs

    def reset(self):
        self._iter = iter(self._loader)

    def next(self):
        try:
            data, label = next(self._iter)
        except StopIteration:
            raise StopIteration
        return DataBatch(data=[data], label=[label], pad=0)
