"""``mx.contrib.ndarray`` — contrib op namespace alias (reference generates
``mxnet.contrib.ndarray`` from the ``_contrib_*`` registrations; here it is
the same lazy module as ``mx.nd.contrib``)."""
from ..ndarray.contrib import __getattr__, __dir__  # noqa: F401
