"""Automatic mixed precision (reference: the amp_cast/amp_multicast ops in
``src/operator/tensor/amp_cast.cc`` + python/mxnet/contrib/amp of later
branches).

TPU-first: the low-precision type is bfloat16 (MXU-native). bf16's exponent
range matches fp32, so loss scaling is rarely REQUIRED — but the reference
AMP API ships a dynamic loss scaler and some models still want one (tiny
gradients underflow bf16's short-mantissa paths), so ``init_trainer`` +
``scale_loss`` implement the real thing: scale the loss up, unscale inside
``Trainer.step``, skip the update and halve the scale on overflow, double it
after ``growth_interval`` clean steps.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from ..base import MXNetError

__all__ = ["init", "is_enabled", "convert_hybrid_block", "init_trainer",
           "scale_loss", "LossScaler"]

_state = {"enabled": False, "dtype": "bfloat16"}


def init(target_dtype: str = "bfloat16") -> None:
    """Enable AMP: gluon nets can then be converted with convert_hybrid_block,
    and DataParallelTrainer(compute_dtype=...) gives the fused-loop variant."""
    _state["enabled"] = True
    _state["dtype"] = target_dtype


def is_enabled() -> bool:
    return _state["enabled"]


class LossScaler:
    """Dynamic loss scaling (reference amp/loss_scaler.py): multiply the
    loss by ``loss_scale``; on non-finite grads skip the step and halve,
    after ``growth_interval`` good steps double (capped at 2**24)."""

    def __init__(self, init_scale: float = 2.0 ** 10,
                 growth_interval: int = 200):
        self.loss_scale = float(init_scale)
        self.growth_interval = growth_interval
        self._good_steps = 0

    def has_overflow(self, params) -> bool:
        """Device-side finiteness check: one reduced scalar crosses to the
        host (the reference's multi_all_finite), never the gradients."""
        import jax.numpy as jnp
        from ..ndarray.ndarray import _unwrap
        bad = None
        for p in params:
            if p.grad_req == "null":
                continue
            g = p.grad
            if g is None:
                continue
            cnt = jnp.sum(~jnp.isfinite(_unwrap(g)))
            bad = cnt if bad is None else bad + cnt
        return bool(bad) if bad is not None else False

    def update(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(self.loss_scale / 2.0, 1.0)
            self._good_steps = 0
        else:
            self._good_steps += 1
            if self._good_steps >= self.growth_interval:
                self.loss_scale = min(self.loss_scale * 2.0, 2.0 ** 24)
                self._good_steps = 0


def init_trainer(trainer, loss_scaler: Optional[LossScaler] = None) -> None:
    """Attach a dynamic loss scaler to a gluon Trainer and wrap its step:
    grads are unscaled via the trainer's rescale machinery; overflowed steps
    are SKIPPED (the reference amp trainer hook)."""
    scaler = loss_scaler or LossScaler()
    trainer._amp_loss_scaler = scaler
    orig_step = trainer.step

    def step(batch_size, ignore_stale_grad=False):
        overflow = scaler.has_overflow(trainer._params)
        if not overflow:
            # fold the unscale into the optimizer's rescale_grad
            orig_step(batch_size * scaler.loss_scale,
                      ignore_stale_grad=ignore_stale_grad)
        scaler.update(overflow)

    trainer.step = step


@contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()`` —
    multiplies the loss by the current dynamic scale; the wrapped
    trainer.step unscales and handles overflow."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) before scale_loss")
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def convert_hybrid_block(net, target_dtype: Optional[str] = None):
    """Cast a HybridBlock's parameters for low-precision inference; BN stats
    stay float32 (the multi-precision split of the reference optimizer)."""
    target_dtype = target_dtype or _state["dtype"]
    for p in net.collect_params().values():
        if p.grad_req == "null" or p.name.endswith(("running_mean",
                                                    "running_var",
                                                    "moving_mean",
                                                    "moving_var",
                                                    "gamma", "beta")):
            continue
        p.cast(target_dtype)
    return net
