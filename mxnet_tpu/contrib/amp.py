"""Automatic mixed precision (reference: the amp_cast/amp_multicast ops in
``src/operator/tensor/amp_cast.cc`` + python/mxnet/contrib/amp of later
branches).

TPU-first: the low-precision type is bfloat16 (MXU-native). bf16's exponent
range matches fp32, so loss scaling is rarely REQUIRED — but the reference
AMP API ships a dynamic loss scaler and some models still want one (tiny
gradients underflow bf16's short-mantissa paths), so ``init_trainer`` +
``scale_loss`` implement the real thing: scale the loss up, unscale inside
``Trainer.step``, skip the update and halve the scale on overflow, double it
after ``growth_interval`` clean steps.
"""
from __future__ import annotations

import pickle
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from ..base import MXNetError

__all__ = ["init", "is_enabled", "convert_hybrid_block", "init_trainer",
           "scale_loss", "LossScaler", "pack_states", "unpack_states"]

_state = {"enabled": False, "dtype": "bfloat16"}

# one fused jitted reduction over ALL gradients -> a single non-finite
# count on device (the reference's multi_all_finite as one XLA program).
# jit caches one executable per distinct (shapes, dtypes) pytree — i.e.
# one compile per model, not per step. The scalar it returns is ASYNC:
# nothing blocks until the caller actually needs the boolean.
_nonfinite_count_fn = None


def _nonfinite_count(grads: Tuple) -> Any:
    global _nonfinite_count_fn
    if _nonfinite_count_fn is None:
        import jax
        import jax.numpy as jnp

        def count(gs):
            total = jnp.zeros((), jnp.int32)
            for g in jax.tree_util.tree_leaves(gs):
                total = total + jnp.sum(
                    ~jnp.isfinite(g)).astype(jnp.int32)
            return total

        _nonfinite_count_fn = jax.jit(count)
    return _nonfinite_count_fn(grads)


def init(target_dtype: str = "bfloat16") -> None:
    """Enable AMP: gluon nets can then be converted with convert_hybrid_block,
    and DataParallelTrainer(compute_dtype=...) gives the fused-loop variant."""
    _state["enabled"] = True
    _state["dtype"] = target_dtype


def is_enabled() -> bool:
    return _state["enabled"]


class LossScaler:
    """Dynamic loss scaling (reference amp/loss_scaler.py): multiply the
    loss by ``loss_scale``; on non-finite grads skip the step and halve,
    after ``growth_interval`` good steps double (capped at 2**24)."""

    def __init__(self, init_scale: float = 2.0 ** 10,
                 growth_interval: int = 200):
        self.loss_scale = float(init_scale)
        self._init_scale = float(init_scale)
        self.growth_interval = growth_interval
        self._good_steps = 0

    def reset(self) -> None:
        """Back to construction state: loading a states file from a
        lineage that never had a scaler must not keep another run's earned
        scale alive."""
        self.loss_scale = self._init_scale
        self._good_steps = 0

    def overflow_scalar(self, params):
        """Non-finite-gradient count as a LAZY device scalar: ONE fused
        jitted reduction over every gradient (one dispatch, no host sync
        here — the reference's multi_all_finite). ``None`` when no
        parameter has a gradient. Resolve with ``bool(...)`` only at the
        point the skip decision is actually made; until then training
        dispatch keeps flowing. The same reduction serves the gluon path
        (``init_trainer``) and diagnostics."""
        from ..ndarray.ndarray import _unwrap
        grads = tuple(_unwrap(p.grad) for p in params
                      if p.grad_req != "null" and p.grad is not None)
        if not grads:
            return None
        return _nonfinite_count(grads)

    def has_overflow(self, params) -> bool:
        """Blocking form of :meth:`overflow_scalar` (back-compat): the one
        reduced scalar crosses to the host, never the gradients."""
        cnt = self.overflow_scalar(params)
        return bool(cnt) if cnt is not None else False

    # scaler state round-trips through gluon Trainer.save_states /
    # Module.save_checkpoint(save_optimizer_states=True) so an AMP run
    # resumes with the scale it had earned, not init_scale
    def state_dict(self) -> Dict[str, Any]:
        return {"loss_scale": float(self.loss_scale),
                "good_steps": int(self._good_steps),
                "growth_interval": int(self.growth_interval)}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.loss_scale = float(state["loss_scale"])
        self._good_steps = int(state.get("good_steps", 0))
        if "growth_interval" in state:
            self.growth_interval = int(state["growth_interval"])

    def update(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(self.loss_scale / 2.0, 1.0)
            self._good_steps = 0
        else:
            self._good_steps += 1
            if self._good_steps >= self.growth_interval:
                self.loss_scale = min(self.loss_scale * 2.0, 2.0 ** 24)
                self._good_steps = 0


def init_trainer(trainer, loss_scaler: Optional[LossScaler] = None) -> None:
    """Attach a dynamic loss scaler to a gluon Trainer and wrap its step:
    grads are unscaled via the trainer's rescale machinery; overflowed steps
    are SKIPPED (the reference amp trainer hook). The finiteness check is
    ONE fused jitted reduction (``LossScaler.overflow_scalar``), not a
    dispatch per parameter; its single scalar is resolved at the branch
    point — the only host read the imperative gluon path fundamentally
    needs. A scaler state loaded by ``Trainer.load_states`` BEFORE this
    call is applied here."""
    scaler = loss_scaler or LossScaler()
    pending = getattr(trainer, "_pending_amp_state", None)
    if pending is not None:
        scaler.load_state_dict(pending)
        trainer._pending_amp_state = None
    trainer._amp_loss_scaler = scaler
    orig_step = trainer.step

    def step(batch_size, ignore_stale_grad=False):
        overflow = scaler.has_overflow(trainer._params)
        if not overflow:
            # fold the unscale into the optimizer's rescale_grad
            orig_step(batch_size * scaler.loss_scale,
                      ignore_stale_grad=ignore_stale_grad)
        scaler.update(overflow)

    trainer.step = step


# ------------------------------------------------- state-file envelope
# gluon Trainer.save_states / Module's optimizer .states files are opaque
# updater bytes; when a LossScaler is attached its state must ride along
# or a resumed AMP run silently restarts from init_scale. The envelope is
# a magic byte prefix + pickled wrapper around the original payload: the
# sniff on load is an O(1) startswith, never a speculative unpickle of a
# potentially-large plain updater payload. Readers without a scaler (or
# old files without an envelope) keep working.
_STATES_MAGIC = b"\x93MXTPU_AMP_STATES_V1\n"


def pack_states(payload: bytes, scaler) -> bytes:
    """Wrap opaque optimizer-state bytes with the scaler state — a
    :class:`LossScaler` or an already-materialized state dict (the
    load-before-init_trainer stash). No-op passthrough when ``scaler`` is
    None."""
    if scaler is None:
        return payload
    state = scaler.state_dict() if isinstance(scaler, LossScaler) \
        else dict(scaler)
    return _STATES_MAGIC + pickle.dumps(
        {"updater": payload, "amp_scaler": state})


def unpack_states(data: bytes) -> Tuple[bytes, Optional[Dict[str, Any]]]:
    """Inverse of :func:`pack_states`: returns ``(updater_bytes,
    scaler_state_or_None)``. Non-envelope bytes pass through untouched."""
    if not data.startswith(_STATES_MAGIC):
        return data, None
    obj = pickle.loads(data[len(_STATES_MAGIC):])
    return obj["updater"], obj.get("amp_scaler")


@contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()`` —
    multiplies the loss by the current dynamic scale; the wrapped
    trainer.step unscales and handles overflow."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) before scale_loss")
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def convert_hybrid_block(net, target_dtype: Optional[str] = None):
    """Cast a HybridBlock's parameters for low-precision inference; BN stats
    stay float32 (the multi-precision split of the reference optimizer)."""
    target_dtype = target_dtype or _state["dtype"]
    for p in net.collect_params().values():
        if p.grad_req == "null" or p.name.endswith(("running_mean",
                                                    "running_var",
                                                    "moving_mean",
                                                    "moving_var",
                                                    "gamma", "beta")):
            continue
        p.cast(target_dtype)
    return net
