"""Automatic mixed precision (reference: the amp_cast/amp_multicast ops in
``src/operator/tensor/amp_cast.cc`` + python/mxnet/contrib/amp of later
branches). On TPU the low-precision type is bfloat16 (MXU-native), not fp16.
"""
from __future__ import annotations

from typing import Optional

from ..base import MXNetError

_state = {"enabled": False, "dtype": "bfloat16"}


def init(target_dtype: str = "bfloat16") -> None:
    """Enable AMP: gluon nets can then be converted with convert_hybrid_block,
    and DataParallelTrainer(compute_dtype=...) gives the fused-loop variant."""
    _state["enabled"] = True
    _state["dtype"] = target_dtype


def is_enabled() -> bool:
    return _state["enabled"]


def convert_hybrid_block(net, target_dtype: Optional[str] = None):
    """Cast a HybridBlock's parameters for low-precision inference; BN stats
    stay float32 (the multi-precision split of the reference optimizer)."""
    target_dtype = target_dtype or _state["dtype"]
    for p in net.collect_params().values():
        if p.grad_req == "null" or p.name.endswith(("running_mean",
                                                    "running_var",
                                                    "moving_mean",
                                                    "moving_var",
                                                    "gamma", "beta")):
            continue
        p.cast(target_dtype)
    return net
