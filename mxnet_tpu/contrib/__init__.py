"""``mx.contrib`` (reference: ``python/mxnet/contrib`` + the contrib op
directory ``src/operator/contrib``)."""
from . import control_flow
from .control_flow import foreach, while_loop, cond
from . import quantization
from . import amp
from . import onnx
from . import text
from . import svrg_optimization
from . import tensorboard
from . import tensorrt
from . import autograd
from . import dgl
from . import io
from . import ndarray
from . import symbol
