"""TensorBoard logging callback (reference
``python/mxnet/contrib/tensorboard.py``: LogMetricsCallback writing scalar
summaries per batch). Gated on an installed summary writer
(``tensorboardX``/``torch.utils.tensorboard``) — absent here, the callback
degrades to logging so training scripts keep running unchanged."""
from __future__ import annotations

import logging

__all__ = ["LogMetricsCallback"]


def _find_writer(logging_dir):
    try:
        from tensorboardX import SummaryWriter  # type: ignore
        return SummaryWriter(logging_dir)
    except Exception:   # missing package OR failing constructor — fall back
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter  # type: ignore
        return SummaryWriter(logging_dir)
    except Exception:
        return None


class LogMetricsCallback:
    """Per-batch metric scalars → TensorBoard event file (reference
    tensorboard.py:25). Use as a ``batch_end_callback``."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _find_writer(logging_dir)
        if self.summary_writer is None:
            logging.warning("no tensorboard writer available; "
                            "LogMetricsCallback falls back to logging")

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            if self.summary_writer is not None:
                self.summary_writer.add_scalar(name, value, self.step)
            else:
                logging.info("tb[%d] %s=%s", self.step, name, value)
