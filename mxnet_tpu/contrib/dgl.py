"""DGL graph-sampling operators over CSR adjacency matrices.

Reference parity: ``src/operator/contrib/dgl_graph.cc`` —
``_contrib_dgl_csr_neighbor_uniform_sample`` (SampleSubgraph :544-727),
``_contrib_dgl_csr_neighbor_non_uniform_sample`` (ArrayHeap weighted
sampling :495-542), ``_contrib_dgl_subgraph`` (:1129), ``_contrib_dgl_adjacency``
(:1390), ``_contrib_dgl_graph_compact`` (:1565).

These are host operators in the reference too (CPU-only FComputeEx — graph
traversal with hash sets has no fixed-shape device lowering), so the
TPU-native design keeps them on host: numpy BFS/sampling over the CSR
buffers, fixed-size padded outputs exactly like the reference so downstream
device code sees static shapes. Exposed through ``mx.nd.contrib.*`` like
every other ``_contrib_`` op.

Output contract of the neighbor samplers (per seed array):
1. ``sampled_vertices`` int64[max_num_vertices+1] — sorted unique vertex
   ids, padded; LAST element = actual count.
2. ``sub_csr`` CSR (max_num_vertices, graph_cols) — row i = i-th sampled
   vertex's sampled edges; values are the ORIGINAL edge ids.
3. (non-uniform only) ``sub_prob`` float32[max_num_vertices] — each sampled
   vertex's probability.
4. ``sub_layer`` int64[max_num_vertices] — BFS layer per sampled vertex
   (0 = seed), padded with -1.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array
from ..ndarray.sparse import CSRNDArray, csr_matrix

__all__ = ["dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample",
           "dgl_subgraph", "dgl_adjacency", "dgl_graph_compact"]


def _csr_parts(csr: CSRNDArray):
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("graph must be a CSRNDArray (stype 'csr')")
    data = np.asarray(csr.data.asnumpy()).astype(np.int64)
    indices = np.asarray(csr.indices.asnumpy()).astype(np.int64)
    indptr = np.asarray(csr.indptr.asnumpy()).astype(np.int64)
    return data, indices, indptr


def _as_np_ids(x) -> np.ndarray:
    if isinstance(x, NDArray):
        x = x.asnumpy()
    return np.asarray(x).astype(np.int64).ravel()


def _sample_row(vals, cols, num_neighbor, rs, prob=None):
    """Sample up to num_neighbor of a vertex's edges (GetUniformSample /
    GetNonUniformSample: degree <= k keeps everything, in order)."""
    deg = len(cols)
    if deg <= num_neighbor:
        return cols, vals
    if prob is None:
        idx = np.sort(rs.choice(deg, size=num_neighbor, replace=False))
        return cols[idx], vals[idx]
    p = prob[cols]
    s = p.sum()
    if s <= 0:
        raise MXNetError("non-uniform sample: zero total probability")
    idx = rs.choice(deg, size=num_neighbor, replace=False, p=p / s)
    # the reference sorts the sampled vertex and edge lists INDEPENDENTLY
    # (GetNonUniformSample, dgl_graph.cc:533-534), which scrambles the
    # (neighbor, edge-id) pairing; we sort by column carrying the edge id
    # along so edge-feature lookups stay correct — deliberate fix, not a
    # transcription
    order = np.argsort(cols[idx], kind="stable")
    return cols[idx][order], vals[idx][order]


def _sample_subgraph(csr, seeds, num_hops, num_neighbor, max_num_vertices,
                     prob=None, rs=None):
    """SampleSubgraph (dgl_graph.cc:544): BFS from the seeds, sampling
    ``num_neighbor`` edges per expanded vertex, capped at
    ``max_num_vertices`` vertices."""
    data, indices, indptr = _csr_parts(csr)
    seeds = _as_np_ids(seeds)
    if max_num_vertices < len(seeds):
        raise MXNetError("max_num_vertices must cover the seeds")
    n_rows = csr.shape[0]
    if len(seeds) and (seeds.min() < 0 or seeds.max() >= n_rows):
        raise MXNetError(
            f"seed vertex ids must be in [0, {n_rows}); got "
            f"[{seeds.min()}, {seeds.max()}]")
    rs = rs or np.random.RandomState()

    layer_of = {}
    order: List[Tuple[int, int]] = []   # (vertex, layer) in discovery order
    for s in seeds:
        if int(s) not in layer_of:
            layer_of[int(s)] = 0
            order.append((int(s), 0))
    edges = {}                          # expanded vertex -> (cols, vals)
    idx = 0
    while idx < len(order) and len(layer_of) < max_num_vertices:
        v, lvl = order[idx]
        idx += 1
        if lvl >= num_hops:
            continue
        lo, hi = indptr[v], indptr[v + 1]
        cols, vals = _sample_row(data[lo:hi], indices[lo:hi], num_neighbor,
                                 rs, prob)
        # keep deterministic (col, val) pairing: _sample_row may have sorted
        edges[v] = (cols, vals)
        for u in cols:
            if len(layer_of) >= max_num_vertices:
                break
            if int(u) not in layer_of:
                layer_of[int(u)] = lvl + 1
                order.append((int(u), lvl + 1))

    verts = np.sort(np.fromiter(layer_of, np.int64, len(layer_of)))
    n = len(verts)

    sampled = np.zeros((max_num_vertices + 1,), np.int64)
    sampled[:n] = verts
    sampled[max_num_vertices] = n       # last element = actual count
    layers = np.full((max_num_vertices,), -1, np.int64)
    layers[:n] = [layer_of[int(v)] for v in verts]

    # sub-CSR: row i = i-th sampled vertex, columns keep ORIGINAL ids
    out_data, out_cols, out_ptr = [], [], [0]
    for v in verts:
        cols, vals = edges.get(int(v), (np.empty(0, np.int64),) * 2)
        out_cols.extend(int(c) for c in cols)
        out_data.extend(int(x) for x in vals)
        out_ptr.append(len(out_cols))
    while len(out_ptr) < max_num_vertices + 1:
        out_ptr.append(out_ptr[-1])
    sub = csr_matrix((np.asarray(out_data, np.int64),
                      np.asarray(out_cols, np.int64),
                      np.asarray(out_ptr, np.int64)),
                     shape=(max_num_vertices, csr.shape[1]))
    if prob is not None:
        sub_prob = np.zeros((max_num_vertices,), np.float32)
        sub_prob[:n] = prob[verts]
        return nd_array(sampled), sub, nd_array(sub_prob), nd_array(layers)
    return nd_array(sampled), sub, nd_array(layers)


def dgl_csr_neighbor_uniform_sample(csr, *seeds, num_args=None, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100,
                                    seed=None):
    """Uniform neighbor sampling; returns the 3 output sets flattened in
    reference order: all sampled_vertices, then all sub_csrs, then all
    layers (one of each per seed array)."""
    rs = np.random.RandomState(seed)
    results = [_sample_subgraph(csr, s, num_hops, num_neighbor,
                                max_num_vertices, rs=rs) for s in seeds]
    return [r[i] for i in range(3) for r in results] if len(results) > 1 \
        else list(results[0])


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seeds,
                                        num_args=None, num_hops=1,
                                        num_neighbor=2, max_num_vertices=100,
                                        seed=None):
    """Probability-weighted sampling; outputs gain a per-vertex probability
    set (4 sets total, dgl_graph.cc:852+)."""
    prob = np.asarray(probability.asnumpy() if isinstance(probability, NDArray)
                      else probability, np.float32).ravel()
    rs = np.random.RandomState(seed)
    results = [_sample_subgraph(csr, s, num_hops, num_neighbor,
                                max_num_vertices, prob=prob, rs=rs)
               for s in seeds]
    return [r[i] for i in range(4) for r in results] if len(results) > 1 \
        else list(results[0])


def dgl_subgraph(graph, *vertex_sets, return_mapping=False, num_args=None):
    """Induced subgraph per (sorted) vertex set: rows/cols restricted and
    relabelled to the set's order. The first output's edge values are NEW
    edge ids — 0-based row-major positions, exactly the reference kernel
    (GetSubgraph ``sub_eids[i] = i``; its docstring example shows 1-based
    but the implementation is 0-based). The mapping output (if requested)
    carries the original edge ids."""
    data, indices, indptr = _csr_parts(graph)
    news, olds = [], []
    for vset in vertex_sets:
        v = _as_np_ids(vset)
        if not np.all(v[:-1] <= v[1:]):
            raise MXNetError("the input vertex list has to be sorted")
        if len(v) and (v.min() < 0 or v.max() >= graph.shape[0]):
            raise MXNetError(
                f"vertex ids must be in [0, {graph.shape[0]}); got "
                f"[{v.min()}, {v.max()}]")
        pos = {int(x): i for i, x in enumerate(v)}
        n = len(v)
        nd_, nc, np_ = [], [], [0]
        od = []
        for dst in v:
            lo, hi = indptr[dst], indptr[dst + 1]
            for c, val in zip(indices[lo:hi], data[lo:hi]):
                j = pos.get(int(c))
                if j is None:
                    continue
                nc.append(j)
                nd_.append(len(nd_))
                od.append(int(val))
            np_.append(len(nc))
        mk = lambda vals: csr_matrix((np.asarray(vals, np.int64),
                                      np.asarray(nc, np.int64),
                                      np.asarray(np_, np.int64)),
                                     shape=(n, n))
        news.append(mk(nd_))
        olds.append(mk(od))
    out = news + olds if return_mapping else news
    return out if len(out) > 1 else out[0]


def dgl_adjacency(graph):
    """Edge-id CSR -> float32 adjacency-of-ones CSR (dgl_graph.cc:1390)."""
    data, indices, indptr = _csr_parts(graph)
    return csr_matrix((np.ones(len(data), np.float32), indices, indptr),
                      shape=tuple(graph.shape))


def dgl_graph_compact(*args, graph_sizes=None, return_mapping=False,
                      num_args=None):
    """Strip the padding the neighbor samplers add: keep the first
    ``graph_size`` rows, relabel columns to subgraph-local ids, emit a
    (size, size) CSR whose values are new 0-based sequential edge ids
    (CompactSubgraph ``sub_eids[i] = i``). The mapping output carries the
    original edge ids. Inputs alternate: N sub_csrs then N vertex-id arrays
    (reference SubgraphCompactParam layout); a trailing count element on
    the vertex array (as the samplers emit) is ignored via ``graph_sizes``.
    Edges to vertices outside the kept set are dropped (the reference hard-
    CHECK-fails there; that only happens on truncated samples)."""
    n_graphs = len(args) // 2
    if len(args) != 2 * n_graphs or n_graphs == 0:
        raise MXNetError("expected csr1..csrN, vertices1..vertexN")
    if graph_sizes is None:
        raise MXNetError(
            "dgl_graph_compact requires graph_sizes (the actual vertex "
            "count per subgraph — the samplers report it in the last "
            "element of their sampled_vertices output)")
    sizes = ([int(graph_sizes)] * n_graphs if np.isscalar(graph_sizes)
             else [int(s) for s in graph_sizes])
    news, olds = [], []
    for g in range(n_graphs):
        sub, vids = args[g], args[n_graphs + g]
        size = sizes[g]
        data, indices, indptr = _csr_parts(sub)
        v = _as_np_ids(vids)[:size]
        pos = {int(x): i for i, x in enumerate(v)}
        nd_, nc, np_ = [], [], [0]
        od = []
        for r in range(size):
            lo, hi = indptr[r], indptr[r + 1]
            for c, val in zip(indices[lo:hi], data[lo:hi]):
                j = pos.get(int(c))
                if j is None:
                    continue
                nc.append(j)
                od.append(int(val))
                nd_.append(len(nd_))
            np_.append(len(nc))
        mk = lambda vals: csr_matrix((np.asarray(vals, np.int64),
                                      np.asarray(nc, np.int64),
                                      np.asarray(np_, np.int64)),
                                     shape=(size, size))
        news.append(mk(nd_))
        olds.append(mk(od))
    out = news + olds if return_mapping else news
    return out if len(out) > 1 else out[0]
