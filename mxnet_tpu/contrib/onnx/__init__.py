"""``mx.contrib.onnx`` — ONNX interop (reference
``python/mxnet/contrib/onnx``: ``import_model``/``export_model`` over the
mx2onnx + onnx2mx translator registries). Self-contained: serialization uses
the in-repo protobuf wire codec (proto.py), no ``onnx`` package required.
"""
from .mx2onnx import export_model
from .onnx2mx import import_model

__all__ = ["export_model", "import_model"]
