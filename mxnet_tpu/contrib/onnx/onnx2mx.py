"""ONNX → Symbol importer.

Reference parity: ``python/mxnet/contrib/onnx/onnx2mx/import_onnx.py``
(GraphProto walk building mx symbols + arg/aux param dicts, one translator
per ONNX op — ``_op_translations.py``). Returns ``(sym, arg_params,
aux_params)`` exactly like ``onnx_mxnet.import_model``.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ...base import MXNetError
from .proto import ModelProto, ONNX_TO_DTYPE

__all__ = ["import_model", "ONNX2MX_TRANSLATORS"]

ONNX2MX_TRANSLATORS = {}


def register(op_type):
    def deco(fn):
        ONNX2MX_TRANSLATORS[op_type] = fn
        return fn
    return deco


def _sym():
    from ... import symbol
    return symbol


def _sym_pads(attrs, nd):
    """ONNX pads are [begin_0..begin_nd, end_0..end_nd]; our ops take one
    symmetric pad per spatial dim, so asymmetric padding must be rejected,
    not silently truncated."""
    pads = tuple(int(p) for p in attrs.get("pads", ()))
    if not pads:
        return ()
    begin, end = pads[:nd], pads[nd:]
    if tuple(begin) != tuple(end):
        raise MXNetError(
            f"ONNX import: asymmetric padding {pads} is not supported; "
            "only symmetric begin/end pads map onto the pad= attribute")
    return begin


@register("Conv")
def _conv(name, ins, attrs, st):
    kw = dict(kernel=tuple(attrs["kernel_shape"]),
              stride=tuple(attrs.get("strides", ())) or None,
              pad=_sym_pads(attrs, len(attrs["kernel_shape"])),
              dilate=tuple(attrs.get("dilations", ())) or None,
              num_group=int(attrs.get("group", 1)),
              num_filter=st["shapes"][ins[1].name][0],
              no_bias=len(ins) == 2)
    kw = {k: v for k, v in kw.items() if v is not None}
    return _sym().Convolution(*ins, name=name, **kw)


@register("ConvTranspose")
def _deconv(name, ins, attrs, st):
    kw = dict(kernel=tuple(attrs["kernel_shape"]),
              stride=tuple(attrs.get("strides", ())) or None,
              pad=_sym_pads(attrs, len(attrs["kernel_shape"])),
              num_group=int(attrs.get("group", 1)),
              num_filter=st["shapes"][ins[1].name][1],
              no_bias=len(ins) == 2)
    kw = {k: v for k, v in kw.items() if v is not None}
    return _sym().Deconvolution(*ins, name=name, **kw)


@register("Gemm")
def _gemm(name, ins, attrs, st):
    """All four transA/transB forms with alpha/beta scaling. The
    FC-shaped case (transA=0, transB=1, alpha=beta=1) lowers to
    FullyConnected; the rest compose transpose/dot/broadcast_add —
    matching the reference's general Gemm lowering."""
    transA = int(attrs.get("transA", 0))
    transB = int(attrs.get("transB", 0))
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    if (transA, transB, alpha, beta) == (0, 1, 1.0, 1.0) and len(ins) == 3:
        num_hidden = st["shapes"][ins[1].name][0]
        return _sym().FullyConnected(ins[0], ins[1], ins[2], name=name,
                                     num_hidden=num_hidden, flatten=False)
    a, b = ins[0], ins[1]
    out = _sym().dot(a, b, transpose_a=bool(transA),
                     transpose_b=bool(transB))
    if alpha != 1.0:
        out = _sym()._mul_scalar(out, scalar=alpha)
    if len(ins) == 3:
        c = ins[2]
        if beta != 1.0:
            c = _sym()._mul_scalar(c, scalar=beta)
        out = _sym().broadcast_add(out, c, name=name)
    return out


@register("MatMul")
def _matmul(name, ins, attrs, st):
    return _sym().dot(ins[0], ins[1], name=name)


@register("SpatialBN")
@register("BatchNormalization")
def _bn(name, ins, attrs, st):
    return _sym().BatchNorm(*ins, name=name,
                            eps=float(attrs.get("epsilon", 1e-5)),
                            momentum=float(attrs.get("momentum", 0.9)),
                            fix_gamma=False, use_global_stats=False)


def _pool_kw(attrs):
    kernel = tuple(attrs["kernel_shape"])
    return dict(kernel=kernel,
                stride=tuple(attrs.get("strides", ())) or (1,) * len(kernel),
                pad=_sym_pads(attrs, len(kernel)))


@register("MaxPool")
def _maxpool(name, ins, attrs, st):
    return _sym().Pooling(ins[0], name=name, pool_type="max", **_pool_kw(attrs))


@register("AveragePool")
def _avgpool(name, ins, attrs, st):
    return _sym().Pooling(
        ins[0], name=name, pool_type="avg",
        count_include_pad=bool(attrs.get("count_include_pad", 0)),  # spec default 0
        **_pool_kw(attrs))


@register("GlobalMaxPool")
def _gmaxpool(name, ins, attrs, st):
    return _sym().Pooling(ins[0], name=name, pool_type="max", global_pool=True)


@register("GlobalAveragePool")
def _gavgpool(name, ins, attrs, st):
    return _sym().Pooling(ins[0], name=name, pool_type="avg", global_pool=True)


@register("Softmax")
def _softmax(name, ins, attrs, st):
    return _sym().softmax(ins[0], name=name, axis=int(attrs.get("axis", -1)))


@register("Flatten")
def _flatten(name, ins, attrs, st):
    return _sym().Flatten(ins[0], name=name)


@register("Concat")
def _concat(name, ins, attrs, st):
    return _sym().Concat(*ins, name=name, dim=int(attrs.get("axis", 1)))


@register("Dropout")
def _dropout(name, ins, attrs, st):
    return _sym().Dropout(ins[0], name=name,
                          p=float(attrs.get("ratio", 0.5)))


@register("Reshape")
def _reshape(name, ins, attrs, st):
    if "shape" in attrs:                     # opset-1 attr form
        shape = tuple(int(x) for x in attrs["shape"])
    else:                                    # opset-5 tensor input form
        shp_name = st["raw_inputs"][name][1]
        if shp_name not in st["consts"]:
            raise MXNetError("ONNX import: dynamic Reshape shape")
        shape = tuple(int(x) for x in st["consts"][shp_name])
    return _sym().Reshape(ins[0], name=name, shape=shape)


@register("Transpose")
def _transpose(name, ins, attrs, st):
    if "perm" in attrs:
        return _sym().transpose(ins[0], name=name,
                                axes=tuple(int(x) for x in attrs["perm"]))
    return _sym().transpose(ins[0], name=name)


@register("Gather")
def _gather(name, ins, attrs, st):
    if int(attrs.get("axis", 0)) != 0:
        raise MXNetError("ONNX import: Gather only with axis=0")
    # Gather(weight, indices) -> take(weight, indices)
    return _sym().take(ins[0], ins[1], name=name)


@register("Clip")
def _clip(name, ins, attrs, st):
    return _sym().clip(ins[0], name=name,
                       a_min=float(attrs.get("min", -3.4e38)),
                       a_max=float(attrs.get("max", 3.4e38)))


@register("ReduceMean")
def _reduce_mean(name, ins, attrs, st):
    kw = dict(keepdims=bool(attrs.get("keepdims", 1)))
    if "axes" in attrs:
        kw["axis"] = tuple(int(a) for a in attrs["axes"])
    return _sym().mean(ins[0], name=name, **kw)


@register("Cast")
def _cast(name, ins, attrs, st):
    dtype = ONNX_TO_DTYPE[int(attrs["to"])]
    return _sym().Cast(ins[0], name=name, dtype=str(dtype))


@register("LeakyRelu")
def _leaky(name, ins, attrs, st):
    return _sym().LeakyReLU(ins[0], name=name, act_type="leaky",
                            slope=float(attrs.get("alpha", 0.01)))


@register("Elu")
def _elu(name, ins, attrs, st):
    return _sym().LeakyReLU(ins[0], name=name, act_type="elu",
                            slope=float(attrs.get("alpha", 1.0)))


@register("PRelu")
def _prelu(name, ins, attrs, st):
    return _sym().LeakyReLU(ins[0], ins[1], name=name, act_type="prelu")


@register("Sum")
def _sum(name, ins, attrs, st):
    return _sym().add_n(*ins, name=name)


@register("Pad")
def _pad(name, ins, attrs, st):
    pads = [int(x) for x in attrs.get("pads", ())]
    nd = len(pads) // 2
    width = []
    for i in range(nd):
        width += [pads[i], pads[nd + i]]
    return _sym().pad(ins[0], name=name,
                      mode=attrs.get("mode", "constant"),
                      pad_width=tuple(width),
                      constant_value=float(attrs.get("value", 0.0)))


@register("Squeeze")
def _squeeze(name, ins, attrs, st):
    if len(ins) > 1:        # opset >= 13 axes-as-input form
        raise MXNetError("ONNX import: Squeeze with axes as an input "
                         "(opset >= 13) is not supported; use opset 11")
    axes = [int(a) for a in attrs.get("axes", ())]
    return _sym().squeeze(ins[0], name=name,
                          axis=tuple(axes) if axes else None)


@register("Unsqueeze")
def _unsqueeze(name, ins, attrs, st):
    if len(ins) > 1:        # opset >= 13 axes-as-input form
        raise MXNetError("ONNX import: Unsqueeze with axes as an input "
                         "(opset >= 13) is not supported; use opset 11")
    axes = [int(a) for a in attrs.get("axes", ())]
    # ONNX axes index the OUTPUT rank; insertion order matters. Positive
    # axes insert ascending; all-negative axes insert descending (the one
    # closest to the end first), so e.g. axes=[-2,-1] on (3,) correctly
    # yields (3,1,1). Mixed signs would need the (unknown) input rank.
    out = ins[0]
    if all(a >= 0 for a in axes):
        order = sorted(axes)
    elif all(a < 0 for a in axes):
        order = sorted(axes, reverse=True)
    else:
        raise MXNetError("ONNX import: Unsqueeze with mixed-sign axes "
                         f"{axes} needs a static input rank")
    for a in order:
        out = _sym().expand_dims(out, axis=a)
    return out


@register("Slice")
def _slice(name, ins, attrs, st):
    starts = [int(a) for a in attrs.get("starts", ())]
    ends = [int(a) for a in attrs.get("ends", ())]
    steps = []
    raw = st["raw_inputs"].get(name, ())
    if not starts and len(raw) >= 3:
        # opset >= 10 input form: starts/ends/axes/steps are tensors. The
        # overwhelmingly common exported case has them as initializers —
        # fold them; truly dynamic slicing is rejected, never silently
        # mis-sliced.
        def _const(i):
            if i < len(raw) and raw[i] in st["consts"]:
                return [int(v) for v in np.ravel(st["consts"][raw[i]])]
            return None
        starts, ends = _const(1), _const(2)
        axes = _const(3)
        steps = _const(4)
        if starts is None or ends is None \
                or (len(raw) >= 4 and axes is None) \
                or (len(raw) >= 5 and steps is None):
            raise MXNetError(
                "ONNX import: Slice with dynamic (non-initializer) "
                "starts/ends/axes/steps is not supported")
        if axes is None:
            axes = list(range(len(starts)))
        steps = steps or []
    else:
        axes = [int(a) for a in attrs.get("axes", range(len(starts)))]
    if not starts or len(ends) != len(starts) or len(axes) != len(starts):
        raise MXNetError(
            "ONNX import: Slice starts/ends/axes lengths disagree "
            f"({len(starts)}/{len(ends) if ends else 0}/{len(axes)})")
    if any(int(st_) != 1 for st_ in steps):
        raise MXNetError("ONNX import: Slice steps != 1 not supported")
    out = ins[0]
    for ax, b, e in zip(axes, starts, ends):
        out = _sym().slice_axis(out, axis=ax, begin=b,
                                end=None if e >= 2 ** 31 - 1 else e)
    return out


@register("Split")
def _split(name, ins, attrs, st):
    axis = int(attrs.get("axis", 0))
    sizes = [int(s) for s in attrs.get("split", ())]
    if sizes and len(set(sizes)) > 1:
        raise MXNetError(
            f"ONNX import: uneven Split sizes {sizes} are not supported "
            "(SliceChannel is equal-section)")
    n = len(sizes) or int(st.get("n_outputs", 0))
    if n < 1:
        raise MXNetError("ONNX import: Split with no output count")
    return _sym().SliceChannel(ins[0], name=name, num_outputs=n, axis=axis)


@register("LRN")
def _lrn(name, ins, attrs, st):
    return _sym().LRN(ins[0], name=name,
                      alpha=float(attrs.get("alpha", 1e-4)),
                      beta=float(attrs.get("beta", 0.75)),
                      knorm=float(attrs.get("bias", 1.0)),
                      nsize=int(attrs.get("size", 5)))


def _binary(mx_op):
    def fn(name, ins, attrs, st):
        return getattr(_sym(), mx_op)(ins[0], ins[1], name=name)
    return fn


def _unary(mx_op):
    def fn(name, ins, attrs, st):
        return getattr(_sym(), mx_op)(ins[0], name=name)
    return fn


for _onnx, _mx in [("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                   ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                   ("Max", "broadcast_maximum"), ("Min", "broadcast_minimum"),
                   ("Pow", "broadcast_power")]:
    register(_onnx)(_binary(_mx))

for _onnx, _mx in [("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
                   ("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                   ("Abs", "abs"), ("Neg", "negative"), ("Floor", "floor"),
                   ("Ceil", "ceil"), ("Identity", "identity")]:
    register(_onnx)(_unary(_mx))


@register("Softplus")
def _softplus(name, ins, attrs, st):
    return _sym().Activation(ins[0], name=name, act_type="softrelu")


# ---------------------------------------------------------------------------
# round-5 breadth: the rest of the reference import table
# (python/mxnet/contrib/onnx/onnx2mx/_import_helper.py:1 — ~92 ops)
# ---------------------------------------------------------------------------

for _onnx, _mx in [("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
                   ("Asin", "arcsin"), ("Acos", "arccos"),
                   ("Atan", "arctan"), ("Reciprocal", "reciprocal"),
                   ("Softsign", "softsign"), ("Not", "logical_not")]:
    register(_onnx)(_unary(_mx))

for _onnx, _mx in [("And", "broadcast_logical_and"),
                   ("Or", "broadcast_logical_or"),
                   ("Xor", "broadcast_logical_xor"),
                   ("Equal", "broadcast_equal"),
                   ("Greater", "broadcast_greater"),
                   ("Less", "broadcast_lesser")]:
    register(_onnx)(_binary(_mx))


@register("Selu")
def _selu(name, ins, attrs, st):
    a = float(attrs.get("alpha", 1.6732632423543772))
    g = float(attrs.get("gamma", 1.0507009873554805))
    if abs(a - 1.6732632423543772) > 1e-6 or \
            abs(g - 1.0507009873554805) > 1e-6:
        raise MXNetError(
            "ONNX import: Selu with non-default alpha/gamma "
            f"({a}, {g}) has no counterpart (selu constants are fixed)")
    return _sym().LeakyReLU(ins[0], name=name, act_type="selu")


@register("HardSigmoid")
def _hard_sigmoid(name, ins, attrs, st):
    return _sym().hard_sigmoid(ins[0], name=name,
                               alpha=float(attrs.get("alpha", 0.2)),
                               beta=float(attrs.get("beta", 0.5)))


@register("LogSoftmax")
def _log_softmax(name, ins, attrs, st):
    return _sym().log_softmax(ins[0], name=name,
                              axis=int(attrs.get("axis", 1)))


def _arg_reduce(mx_op):
    def fn(name, ins, attrs, st):
        out = getattr(_sym(), mx_op)(ins[0], name=name,
                                     axis=int(attrs.get("axis", 0)),
                                     keepdims=bool(attrs.get("keepdims", 1)))
        return out
    return fn


register("ArgMax")(_arg_reduce("argmax"))
register("ArgMin")(_arg_reduce("argmin"))


def _reduce(mx_op, post=None, pre=None):
    """ONNX Reduce* -> mx reduce with axis/keepdims; pre/post wrap the
    composed forms (ReduceLogSum = log(sum), ReduceSumSquare =
    sum(square), ReduceLogSumExp = log(sum(exp)) — the reference composes
    them the same way)."""
    def fn(name, ins, attrs, st):
        x = ins[0]
        if pre is not None:
            x = getattr(_sym(), pre)(x)
        axes = attrs.get("axes")
        kw = dict(keepdims=bool(attrs.get("keepdims", 1)))
        if axes is not None:
            kw["axis"] = tuple(int(a) for a in axes)
        out = getattr(_sym(), mx_op)(x, **kw)
        if post is not None:
            out = getattr(_sym(), post)(out, name=name)
        return out
    return fn


register("ReduceSum")(_reduce("sum"))
register("ReduceMax")(_reduce("max"))
register("ReduceMin")(_reduce("min"))
register("ReduceProd")(_reduce("prod"))
register("ReduceLogSum")(_reduce("sum", post="log"))
register("ReduceLogSumExp")(_reduce("sum", post="log", pre="exp"))
register("ReduceSumSquare")(_reduce("sum", pre="square"))


@register("Shape")
def _shape(name, ins, attrs, st):
    return _sym().shape_array(ins[0], name=name)


@register("Size")
def _size(name, ins, attrs, st):
    return _sym().size_array(ins[0], name=name)


@register("Constant")
def _constant(name, ins, attrs, st):
    """Materialize the tensor as an initializer: the output Variable binds
    to it through arg_params like any other weight."""
    t = attrs.get("value")
    if t is None:
        raise MXNetError("ONNX import: Constant node without a value attr")
    arr = t.to_array() if hasattr(t, "to_array") else np.asarray(t)
    out_name = st["node_outputs"][0]
    st["consts"][out_name] = arr
    st["shapes"][out_name] = arr.shape
    from ... import symbol as sym_mod
    return sym_mod.Variable(out_name)


@register("InstanceNormalization")
def _instance_norm(name, ins, attrs, st):
    return _sym().InstanceNorm(ins[0], ins[1], ins[2], name=name,
                               eps=float(attrs.get("epsilon", 1e-5)))


@register("DepthToSpace")
def _depth_to_space(name, ins, attrs, st):
    return _sym().depth_to_space(ins[0], name=name,
                                 block_size=int(attrs["blocksize"]))


@register("SpaceToDepth")
def _space_to_depth(name, ins, attrs, st):
    return _sym().space_to_depth(ins[0], name=name,
                                 block_size=int(attrs["blocksize"]))


@register("LpPool")
def _lp_pool(name, ins, attrs, st):
    return _sym().Pooling(ins[0], name=name, pool_type="lp",
                          kernel=tuple(attrs["kernel_shape"]),
                          stride=tuple(attrs.get("strides", ())) or None,
                          pad=_sym_pads(attrs, len(attrs["kernel_shape"])),
                          p_value=int(attrs.get("p", 2)))


@register("GlobalLpPool")
def _global_lp_pool(name, ins, attrs, st):
    return _sym().Pooling(ins[0], name=name, pool_type="lp",
                          global_pool=True, kernel=(1, 1),
                          p_value=int(attrs.get("p", 2)))


@register("MaxRoiPool")
def _max_roi_pool(name, ins, attrs, st):
    return _sym().ROIPooling(ins[0], ins[1], name=name,
                             pooled_size=tuple(attrs["pooled_shape"]),
                             spatial_scale=float(attrs.get("spatial_scale",
                                                           1.0)))


@register("Mean")
def _mean_nary(name, ins, attrs, st):
    out = ins[0]
    for other in ins[1:]:
        out = _sym().broadcast_add(out, other)
    return _sym()._mul_scalar(out, scalar=1.0 / len(ins), name=name)


@register("Multinomial")
def _multinomial(name, ins, attrs, st):
    # ONNX feeds unnormalized LOG probabilities; sample_multinomial takes
    # probabilities — normalize through a softmax first
    probs = _sym().softmax(ins[0], axis=-1)
    return _sym().sample_multinomial(
        probs, name=name, shape=int(attrs.get("sample_size", 1)))


@register("RandomNormal")
def _random_normal(name, ins, attrs, st):
    return _sym().random_normal(loc=float(attrs.get("mean", 0.0)),
                                scale=float(attrs.get("scale", 1.0)),
                                shape=tuple(attrs["shape"]), name=name)


@register("RandomUniform")
def _random_uniform(name, ins, attrs, st):
    return _sym().random_uniform(low=float(attrs.get("low", 0.0)),
                                 high=float(attrs.get("high", 1.0)),
                                 shape=tuple(attrs["shape"]), name=name)


@register("RandomNormalLike")
def _random_normal_like(name, ins, attrs, st):
    return _sym()._random_normal_like(ins[0], name=name,
                                      loc=float(attrs.get("mean", 0.0)),
                                      scale=float(attrs.get("scale", 1.0)))


@register("RandomUniformLike")
def _random_uniform_like(name, ins, attrs, st):
    return _sym()._random_uniform_like(ins[0], name=name,
                                       low=float(attrs.get("low", 0.0)),
                                       high=float(attrs.get("high", 1.0)))


@register("FC")
def _fc(name, ins, attrs, st):
    """The reference exporter's own FullyConnected passthrough op."""
    num_hidden = st["shapes"][ins[1].name][0]
    return _sym().FullyConnected(*ins, name=name, num_hidden=num_hidden,
                                 no_bias=len(ins) == 2)


@register("LpNormalization")
def _lp_normalization(name, ins, attrs, st):
    if int(attrs.get("p", 2)) != 2:
        raise MXNetError("ONNX import: LpNormalization supports p=2 only")
    ax = int(attrs.get("axis", -1))
    # exact single-axis L2 normalization for ANY axis (ONNX semantics);
    # L2Normalization's instance/channel modes cover different axis SETS
    # and would be silently wrong for ndim > 2
    norm = _sym().sqrt(_sym().sum(_sym().square(ins[0]), axis=ax,
                                  keepdims=True))
    return _sym().broadcast_div(
        ins[0], _sym()._plus_scalar(norm, scalar=1e-10), name=name)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def import_model(model_file: str):
    """Load an ONNX file → ``(sym, arg_params, aux_params)``.

    Matches the reference entry ``onnx_mxnet.import_model`` — aux params are
    the BatchNorm running stats (inputs 3/4 of BatchNormalization); all other
    initializers are args.
    """
    from ... import ndarray as nd_mod
    from ... import symbol as sym_mod

    model = ModelProto.load(model_file)
    g = model.graph

    consts: Dict[str, np.ndarray] = {
        t.name: t.to_array() for t in g.initializers}
    shapes = {n: a.shape for n, a in consts.items()}

    aux_names = set()
    for node in g.nodes:
        if node.op_type == "BatchNormalization":
            aux_names.update(node.inputs[3:5])

    st = {"consts": consts, "shapes": shapes,
          "raw_inputs": {n.name or (n.outputs[0] if n.outputs else ""): n.inputs
                         for n in g.nodes}}

    env: Dict[str, "object"] = {}
    consumed_consts = set()  # attr-like tensors (e.g. Reshape shapes)
    # one pass index: name -> [(node, input position)] for the
    # "is this initializer read as data anywhere else" checks below
    consumers: Dict[str, list] = {}
    for _n in g.nodes:
        for _k, _inp in enumerate(_n.inputs):
            consumers.setdefault(_inp, []).append((_n, _k))

    def used_elsewhere(tensor_name, at_node, at_pos):
        return any(not (n2 is at_node and k2 == at_pos)
                   for (n2, k2) in consumers.get(tensor_name, ()))
    for vi in g.inputs:
        if vi.name not in consts:
            env[vi.name] = sym_mod.Variable(vi.name)
    for name in consts:
        env[name] = sym_mod.Variable(name)

    for node in g.nodes:
        fn = ONNX2MX_TRANSLATORS.get(node.op_type)
        if fn is None:
            raise MXNetError(
                f"ONNX import: op {node.op_type} not supported")
        name = node.name or node.outputs[0]
        st["raw_inputs"][name] = node.inputs
        st["n_outputs"] = len(node.outputs)
        st["node_outputs"] = list(node.outputs)
        ins = [env[i] for i in node.inputs if i in env]
        if node.op_type == "Slice" and len(node.inputs) >= 3:
            ins = ins[:1]       # starts/ends/axes/steps folded from consts
            for k1, pname in enumerate(node.inputs[1:], start=1):
                if pname in consts and \
                        not used_elsewhere(pname, node, k1):
                    consumed_consts.add(pname)
        if node.op_type == "Reshape" and len(ins) == 2:
            ins = ins[:1]  # shape tensor consumed via st["consts"] instead
            shp = node.inputs[1]
            # drop from params only if no OTHER node reads it as data
            if not used_elsewhere(shp, node, 1):
                consumed_consts.add(shp)
        out = fn(name, ins, node.attrs, st)
        outs = [out[j] for j in range(len(out))] if len(out) > 1 else [out]
        for out_name, s in zip(node.outputs, outs):
            env[out_name] = s

    out_syms = [env[o.name] for o in g.outputs]
    sym = out_syms[0] if len(out_syms) == 1 else sym_mod.Group(out_syms)

    # remap initializer names onto the composed graph's arg names: our symbol
    # ops auto-bind inputs by position, so Variables carry the onnx names
    arg_params = {k: nd_mod.array(v) for k, v in consts.items()
                  if k not in aux_names and k not in consumed_consts}
    aux_params = {k: nd_mod.array(v) for k, v in consts.items()
                  if k in aux_names}
    return sym, arg_params, aux_params
