"""Minimal self-contained ONNX protobuf codec (no ``onnx``/``protobuf`` dep).

The reference's converters (``python/mxnet/contrib/onnx/mx2onnx/export_onnx.py``,
``onnx2mx/import_onnx.py``) lean on the installed ``onnx`` package; this
environment has none, so the subset of ``onnx.proto3`` the converters need —
Model/Graph/Node/Attribute/Tensor/ValueInfo — is implemented directly against
the protobuf wire format (varint + length-delimited fields). Field numbers
and enums follow the public ONNX spec, so files written here load in stock
``onnx``/onnxruntime and vice versa.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["TensorProto", "ValueInfoProto", "AttributeProto", "NodeProto",
           "GraphProto", "ModelProto", "OperatorSetIdProto",
           "DTYPE_TO_ONNX", "ONNX_TO_DTYPE"]

# onnx TensorProto.DataType
DTYPE_TO_ONNX = {
    np.dtype("float32"): 1, np.dtype("uint8"): 2, np.dtype("int8"): 3,
    np.dtype("uint16"): 4, np.dtype("int16"): 5, np.dtype("int32"): 6,
    np.dtype("int64"): 7, np.dtype("bool"): 9, np.dtype("float16"): 10,
    np.dtype("float64"): 11, np.dtype("uint32"): 12, np.dtype("uint64"): 13,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

def _enc_varint(x: int) -> bytes:
    if x < 0:
        x += 1 << 64  # two's complement, 64-bit
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf: bytes, pos: int):
    x = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return x, pos


def _sint(x: int) -> int:
    """Interpret a decoded varint as a signed 64-bit int."""
    return x - (1 << 64) if x >= (1 << 63) else x


def _tag(field: int, wire: int) -> bytes:
    return _enc_varint((field << 3) | wire)


def _enc_len(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _enc_varint(len(payload)) + payload


def _enc_int(field: int, value: int) -> bytes:
    return _tag(field, 0) + _enc_varint(int(value))


def _enc_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


def _enc_str(field: int, value) -> bytes:
    if isinstance(value, str):
        value = value.encode()
    return _enc_len(field, value)


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value, next_pos) over a message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _dec_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _dec_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _dec_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _dec_packed_varints(val, wire) -> List[int]:
    if wire == 0:
        return [_sint(val)]
    out = []
    pos = 0
    while pos < len(val):
        x, pos = _dec_varint(val, pos)
        out.append(_sint(x))
    return out


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

class TensorProto:
    """onnx.TensorProto: dims=1, data_type=2, float_data=4, int32_data=5,
    string_data=6, int64_data=7, name=8, raw_data=9."""

    def __init__(self, name="", dims=(), data_type=1, raw_data=b""):
        self.name = name
        self.dims = list(dims)
        self.data_type = data_type
        self.raw_data = raw_data
        self._typed_data: List = []

    @classmethod
    def from_array(cls, arr: np.ndarray, name: str) -> "TensorProto":
        arr = np.ascontiguousarray(arr)
        dt = DTYPE_TO_ONNX[arr.dtype]
        return cls(name=name, dims=arr.shape, data_type=dt,
                   raw_data=arr.tobytes())

    def to_array(self) -> np.ndarray:
        dtype = ONNX_TO_DTYPE[self.data_type]
        if self.raw_data:
            arr = np.frombuffer(self.raw_data, dtype=dtype)
        elif self.data_type == 10:
            # float16 typed storage holds raw uint16 bit patterns in
            # int32_data, not numeric values
            arr = np.asarray(self._typed_data, dtype=np.uint16).view(np.float16)
        else:
            arr = np.asarray(self._typed_data, dtype=dtype)
        return arr.reshape(self.dims)

    def encode(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            out += _enc_int(1, d)
        out += _enc_int(2, self.data_type)
        if self.name:
            out += _enc_str(8, self.name)
        raw = self.raw_data
        if not raw and self._typed_data:
            # decoded from typed fields (float_data/int64_data…): re-encode
            # canonically as raw bytes so save→load round-trips the data
            raw = self.to_array().tobytes()
        out += _enc_len(9, raw)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "TensorProto":
        t = cls()
        t._typed_data = []
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                t.dims.extend(_dec_packed_varints(val, wire))
            elif field == 2:
                t.data_type = val
            elif field == 4 and wire == 2:   # packed floats
                t._typed_data.extend(
                    struct.unpack(f"<{len(val)//4}f", val))
            elif field == 4 and wire == 5:
                t._typed_data.append(struct.unpack("<f", val)[0])
            elif field in (5, 7):
                t._typed_data.extend(_dec_packed_varints(val, wire))
            elif field == 8:
                t.name = val.decode()
            elif field == 9:
                t.raw_data = val
        return t


class ValueInfoProto:
    """onnx.ValueInfoProto: name=1, type=2 {tensor_type=1 {elem_type=1,
    shape=2 {dim=1 {dim_value=1 | dim_param=2}}}}."""

    def __init__(self, name="", elem_type=1, shape=()):
        self.name = name
        self.elem_type = elem_type
        self.shape = list(shape)   # ints or strings (symbolic dims)

    def encode(self) -> bytes:
        dims = bytearray()
        for d in self.shape:
            if isinstance(d, str):
                dims += _enc_len(1, _enc_str(2, d))
            else:
                dims += _enc_len(1, _enc_int(1, d))
        shape_msg = bytes(dims)
        tensor_type = _enc_int(1, self.elem_type) + _enc_len(2, shape_msg)
        type_msg = _enc_len(1, tensor_type)
        return _enc_str(1, self.name) + _enc_len(2, type_msg)

    @classmethod
    def decode(cls, buf: bytes) -> "ValueInfoProto":
        v = cls()
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                v.name = val.decode()
            elif field == 2:
                for f2, w2, v2 in _iter_fields(val):
                    if f2 != 1:
                        continue
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1:
                            v.elem_type = v3
                        elif f3 == 2:
                            for f4, w4, v4 in _iter_fields(v3):
                                if f4 != 1:
                                    continue
                                dim_val = 0
                                for f5, w5, v5 in _iter_fields(v4):
                                    if f5 == 1:
                                        dim_val = _sint(v5)
                                    elif f5 == 2:
                                        dim_val = v5.decode()
                                v.shape.append(dim_val)
        return v


class AttributeProto:
    """onnx.AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    strings=9, type=20 (FLOAT=1 INT=2 STRING=3 TENSOR=4 FLOATS=6 INTS=7
    STRINGS=8)."""

    def __init__(self, name="", value=None, attr_type=None):
        self.name = name
        self.value = value
        self.attr_type = attr_type

    @classmethod
    def make(cls, name: str, value) -> "AttributeProto":
        if isinstance(value, bool):
            return cls(name, int(value), 2)
        if isinstance(value, (int, np.integer)):
            return cls(name, int(value), 2)
        if isinstance(value, (float, np.floating)):
            return cls(name, float(value), 1)
        if isinstance(value, (str, bytes)):
            return cls(name, value, 3)
        if isinstance(value, TensorProto):
            return cls(name, value, 4)
        if isinstance(value, (list, tuple)):
            if all(isinstance(x, (int, np.integer)) for x in value):
                return cls(name, [int(x) for x in value], 7)
            if all(isinstance(x, (str, bytes)) for x in value):
                return cls(name, list(value), 8)
            return cls(name, [float(x) for x in value], 6)
        raise TypeError(f"unsupported attribute {name}={value!r}")

    def encode(self) -> bytes:
        out = bytearray(_enc_str(1, self.name))
        t = self.attr_type
        if t == 1:
            out += _enc_float(2, self.value)
        elif t == 2:
            out += _enc_int(3, self.value)
        elif t == 3:
            out += _enc_str(4, self.value)
        elif t == 4:
            out += _enc_len(5, self.value.encode())
        elif t == 6:
            for x in self.value:
                out += _enc_float(7, x)
        elif t == 7:
            for x in self.value:
                out += _enc_int(8, x)
        elif t == 8:
            for x in self.value:
                out += _enc_str(9, x)
        else:
            raise TypeError(f"unsupported attr type {t}")
        out += _enc_int(20, t)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "AttributeProto":
        a = cls()
        floats: List[float] = []
        ints: List[int] = []
        strings: List[bytes] = []
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                a.name = val.decode()
            elif field == 2:
                a.value = struct.unpack("<f", val)[0]
                a.attr_type = a.attr_type or 1
            elif field == 3:
                a.value = _sint(val)
                a.attr_type = a.attr_type or 2
            elif field == 4:
                a.value = val.decode()
                a.attr_type = a.attr_type or 3
            elif field == 5:
                a.value = TensorProto.decode(val)
                a.attr_type = a.attr_type or 4
            elif field == 7:
                if wire == 5:
                    floats.append(struct.unpack("<f", val)[0])
                else:  # packed (proto3 default for repeated floats)
                    floats.extend(struct.unpack(f"<{len(val)//4}f", val))
                a.attr_type = 6
            elif field == 8:
                ints.extend(_dec_packed_varints(val, wire))
                a.attr_type = 7
            elif field == 9:
                strings.append(val.decode())
                a.attr_type = 8
            elif field == 20:
                a.attr_type = val
        if a.attr_type == 6:
            a.value = floats
        elif a.attr_type == 7:
            a.value = ints
        elif a.attr_type == 8:
            a.value = strings
        return a


class NodeProto:
    """onnx.NodeProto: input=1, output=2, name=3, op_type=4, attribute=5,
    domain=7."""

    def __init__(self, op_type="", name="", inputs=(), outputs=(), attrs=None):
        self.op_type = op_type
        self.name = name
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.attrs: Dict[str, Any] = dict(attrs or {})

    def encode(self) -> bytes:
        out = bytearray()
        for s in self.inputs:
            out += _enc_str(1, s)
        for s in self.outputs:
            out += _enc_str(2, s)
        out += _enc_str(3, self.name)
        out += _enc_str(4, self.op_type)
        for k in sorted(self.attrs):
            out += _enc_len(5, AttributeProto.make(k, self.attrs[k]).encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "NodeProto":
        n = cls()
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                n.inputs.append(val.decode())
            elif field == 2:
                n.outputs.append(val.decode())
            elif field == 3:
                n.name = val.decode()
            elif field == 4:
                n.op_type = val.decode()
            elif field == 5:
                a = AttributeProto.decode(val)
                n.attrs[a.name] = a.value
        return n


class GraphProto:
    """onnx.GraphProto: node=1, name=2, initializer=5, input=11, output=12."""

    def __init__(self, name="graph"):
        self.name = name
        self.nodes: List[NodeProto] = []
        self.initializers: List[TensorProto] = []
        self.inputs: List[ValueInfoProto] = []
        self.outputs: List[ValueInfoProto] = []

    def encode(self) -> bytes:
        out = bytearray()
        for n in self.nodes:
            out += _enc_len(1, n.encode())
        out += _enc_str(2, self.name)
        for t in self.initializers:
            out += _enc_len(5, t.encode())
        for v in self.inputs:
            out += _enc_len(11, v.encode())
        for v in self.outputs:
            out += _enc_len(12, v.encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "GraphProto":
        g = cls()
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                g.nodes.append(NodeProto.decode(val))
            elif field == 2:
                g.name = val.decode()
            elif field == 5:
                g.initializers.append(TensorProto.decode(val))
            elif field == 11:
                g.inputs.append(ValueInfoProto.decode(val))
            elif field == 12:
                g.outputs.append(ValueInfoProto.decode(val))
        return g


class OperatorSetIdProto:
    """onnx.OperatorSetIdProto: domain=1, version=2."""

    def __init__(self, domain="", version=9):
        self.domain = domain
        self.version = version

    def encode(self) -> bytes:
        return _enc_str(1, self.domain) + _enc_int(2, self.version)

    @classmethod
    def decode(cls, buf: bytes) -> "OperatorSetIdProto":
        o = cls()
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                o.domain = val.decode()
            elif field == 2:
                o.version = val
        return o


class ModelProto:
    """onnx.ModelProto: ir_version=1, producer_name=2, producer_version=3,
    model_version=5, graph=7, opset_import=8."""

    def __init__(self, graph: Optional[GraphProto] = None, ir_version=4,
                 producer_name="mxnet_tpu", producer_version="0.1",
                 opset_version=9):
        self.ir_version = ir_version
        self.producer_name = producer_name
        self.producer_version = producer_version
        self.graph = graph or GraphProto()
        self.opset_imports = [OperatorSetIdProto(version=opset_version)]

    def encode(self) -> bytes:
        out = bytearray(_enc_int(1, self.ir_version))
        out += _enc_str(2, self.producer_name)
        out += _enc_str(3, self.producer_version)
        out += _enc_len(7, self.graph.encode())
        for o in self.opset_imports:
            out += _enc_len(8, o.encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "ModelProto":
        m = cls(graph=None)
        m.opset_imports = []
        for field, wire, val in _iter_fields(buf):
            if field == 1:
                m.ir_version = val
            elif field == 2:
                m.producer_name = val.decode()
            elif field == 3:
                m.producer_version = val.decode()
            elif field == 7:
                m.graph = GraphProto.decode(val)
            elif field == 8:
                m.opset_imports.append(OperatorSetIdProto.decode(val))
        return m

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.encode())

    @classmethod
    def load(cls, path: str) -> "ModelProto":
        with open(path, "rb") as f:
            return cls.decode(f.read())
