"""Symbol → ONNX exporter.

Reference parity: ``python/mxnet/contrib/onnx/mx2onnx/export_onnx.py`` +
``_op_translations.py`` (MXNetGraph.create_onnx_graph_proto walks the graph
in topo order, one translator per op). Same structure here, but emitting via
the in-repo proto codec (no onnx dependency) and reading this framework's
Symbol IR directly instead of the JSON round-trip.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ...base import MXNetError
from .proto import (GraphProto, ModelProto, NodeProto, TensorProto,
                    ValueInfoProto, DTYPE_TO_ONNX)

__all__ = ["export_model", "MX2ONNX_TRANSLATORS"]

MX2ONNX_TRANSLATORS = {}


def register(op_name):
    def deco(fn):
        MX2ONNX_TRANSLATORS[op_name] = fn
        return fn
    return deco


def _pair(v, nd=2):
    v = tuple(v) if v else (1,) * nd
    return [int(x) for x in v]


class _Ctx:
    """Per-export state handed to translators."""

    def __init__(self, graph: GraphProto):
        self.graph = graph
        self._uid = [0]

    def add_node(self, op_type, inputs, outputs, name=None, **attrs):
        node = NodeProto(op_type=op_type, name=name or outputs[0],
                         inputs=list(inputs), outputs=list(outputs),
                         attrs=attrs)
        self.graph.nodes.append(node)
        return node

    def add_initializer(self, name, arr):
        arr = np.asarray(arr)
        self.graph.initializers.append(TensorProto.from_array(arr, name))
        self.graph.inputs.append(ValueInfoProto(
            name, DTYPE_TO_ONNX[arr.dtype], arr.shape))

    def fresh(self, hint):
        self._uid[0] += 1
        return f"{hint}_{self._uid[0]}"


# ---------------------------------------------------------------------------
# translators: (ctx, node_name, input_names, attrs) -> output name(s)
# ---------------------------------------------------------------------------

@register("Convolution")
def _conv(ctx, name, ins, attrs):
    kernel = _pair(attrs.get("kernel"))
    nd = len(kernel)
    pads = _pair(attrs.get("pad", (0,) * nd), nd)
    ctx.add_node("Conv", ins, [name],
                 kernel_shape=kernel,
                 strides=_pair(attrs.get("stride", (1,) * nd), nd),
                 dilations=_pair(attrs.get("dilate", (1,) * nd), nd),
                 pads=pads + pads,
                 group=int(attrs.get("num_group", 1)))
    return name


@register("Deconvolution")
def _deconv(ctx, name, ins, attrs):
    kernel = _pair(attrs.get("kernel"))
    nd = len(kernel)
    pads = _pair(attrs.get("pad", (0,) * nd), nd)
    ctx.add_node("ConvTranspose", ins, [name],
                 kernel_shape=kernel,
                 strides=_pair(attrs.get("stride", (1,) * nd), nd),
                 pads=pads + pads,
                 group=int(attrs.get("num_group", 1)))
    return name


@register("FullyConnected")
def _fc(ctx, name, ins, attrs):
    data = ins[0]
    if attrs.get("flatten", True):
        flat = ctx.fresh(name + "_flat")
        ctx.add_node("Flatten", [data], [flat], axis=1)
        data = flat
    if attrs.get("no_bias", False):
        # Gemm requires C; emit MatMul against the transposed weight
        wt = ctx.fresh(name + "_wT")
        ctx.add_node("Transpose", [ins[1]], [wt], perm=[1, 0])
        ctx.add_node("MatMul", [data, wt], [name])
    else:
        ctx.add_node("Gemm", [data, ins[1], ins[2]], [name],
                     alpha=1.0, beta=1.0, transA=0, transB=1)
    return name


@register("Activation")
def _act(ctx, name, ins, attrs):
    op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "softrelu": "Softplus", "softsign": "Softsign"}[
              attrs.get("act_type", "relu")]
    ctx.add_node(op, ins, [name])
    return name


@register("LeakyReLU")
def _leaky(ctx, name, ins, attrs):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        ctx.add_node("LeakyRelu", ins[:1], [name],
                     alpha=float(attrs.get("slope", 0.25)))
    elif act == "elu":
        ctx.add_node("Elu", ins[:1], [name],
                     alpha=float(attrs.get("slope", 0.25)))
    elif act == "prelu":
        ctx.add_node("PRelu", ins, [name])
    else:
        raise MXNetError(f"ONNX export: unsupported LeakyReLU {act}")
    return name


@register("BatchNorm")
def _bn(ctx, name, ins, attrs):
    # mx order: data gamma beta moving_mean moving_var == onnx order
    ctx.add_node("BatchNormalization", ins, [name],
                 epsilon=float(attrs.get("eps", 1e-3)),
                 momentum=float(attrs.get("momentum", 0.9)))
    return name


@register("Pooling")
def _pool(ctx, name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        ctx.add_node({"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[
            ptype], ins, [name])
        return name
    kernel = _pair(attrs.get("kernel"))
    nd = len(kernel)
    pads = _pair(attrs.get("pad", (0,) * nd), nd)
    kw = dict(kernel_shape=kernel,
              strides=_pair(attrs.get("stride", (1,) * nd), nd),
              pads=pads + pads)
    if ptype == "avg":
        kw["count_include_pad"] = 1 if attrs.get("count_include_pad", True) \
            else 0
    ctx.add_node({"max": "MaxPool", "avg": "AveragePool"}[ptype], ins,
                 [name], **kw)
    return name


@register("softmax")
@register("Softmax")
def _softmax(ctx, name, ins, attrs):
    ctx.add_node("Softmax", ins[:1], [name], axis=int(attrs.get("axis", -1)))
    return name


@register("SoftmaxOutput")
def _softmax_out(ctx, name, ins, attrs):
    ctx.add_node("Softmax", ins[:1], [name], axis=1)
    return name


@register("Flatten")
def _flatten(ctx, name, ins, attrs):
    ctx.add_node("Flatten", ins, [name], axis=1)
    return name


@register("Concat")
def _concat(ctx, name, ins, attrs):
    ctx.add_node("Concat", ins, [name], axis=int(attrs.get("dim", 1)))
    return name


@register("Dropout")
def _dropout(ctx, name, ins, attrs):
    ctx.add_node("Dropout", ins, [name], ratio=float(attrs.get("p", 0.5)))
    return name


@register("Reshape")
def _reshape(ctx, name, ins, attrs):
    shape_name = ctx.fresh(name + "_shape")
    ctx.add_initializer(shape_name,
                        np.asarray(attrs.get("shape", ()), np.int64))
    ctx.add_node("Reshape", [ins[0], shape_name], [name])
    return name


@register("transpose")
def _transpose(ctx, name, ins, attrs):
    axes = attrs.get("axes", ())
    kw = {"perm": [int(a) for a in axes]} if axes else {}
    ctx.add_node("Transpose", ins, [name], **kw)
    return name


@register("dot")
def _dot(ctx, name, ins, attrs):
    ctx.add_node("MatMul", ins, [name])
    return name


@register("add_n")
@register("ElementWiseSum")
def _add_n(ctx, name, ins, attrs):
    ctx.add_node("Sum", ins, [name])
    return name


@register("clip")
def _clip(ctx, name, ins, attrs):
    ctx.add_node("Clip", ins, [name], min=float(attrs.get("a_min", 0.0)),
                 max=float(attrs.get("a_max", 1.0)))
    return name


@register("mean")
def _mean(ctx, name, ins, attrs):
    axis = attrs.get("axis", None)
    kw = {"keepdims": 1 if attrs.get("keepdims", False) else 0}
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else (axis,)
        kw["axes"] = [int(a) for a in axes]
    ctx.add_node("ReduceMean", ins, [name], **kw)
    return name


@register("Embedding")
def _embedding(ctx, name, ins, attrs):
    # onnx Gather(weight, indices); mx order is (data=indices, weight)
    ctx.add_node("Gather", [ins[1], ins[0]], [name], axis=0)
    return name


@register("Cast")
def _cast(ctx, name, ins, attrs):
    dt = DTYPE_TO_ONNX[np.dtype(attrs.get("dtype", "float32"))]
    ctx.add_node("Cast", ins, [name], to=int(dt))
    return name


@register("squeeze")
def _squeeze(ctx, name, ins, attrs):
    ax = attrs.get("axis")
    kw = {}
    if ax is not None and ax != ():
        axes = (ax,) if isinstance(ax, int) else tuple(ax)
        kw["axes"] = [int(a) for a in axes]
    ctx.add_node("Squeeze", ins[:1], [name], **kw)
    return name


@register("expand_dims")
def _expand_dims(ctx, name, ins, attrs):
    ctx.add_node("Unsqueeze", ins[:1], [name],
                 axes=[int(attrs.get("axis", 0))])
    return name


@register("slice_axis")
def _slice_axis(ctx, name, ins, attrs):
    end = attrs.get("end")
    ctx.add_node("Slice", ins[:1], [name],
                 axes=[int(attrs.get("axis", 0))],
                 starts=[int(attrs.get("begin", 0))],
                 ends=[2 ** 31 - 1 if end in (None, "None") else int(end)])
    return name


@register("SliceChannel")
@register("split")
def _slice_channel(ctx, name, ins, attrs):
    n = int(attrs.get("num_outputs", 1))
    outs = [f"{name}_out{i}" for i in range(n)]
    ctx.add_node("Split", ins[:1], outs, axis=int(attrs.get("axis", 1)))
    if str(attrs.get("squeeze_axis", False)) in ("True", "1", "true"):
        sq = []
        for o in outs:
            ctx.add_node("Squeeze", [o], [o + "_sq"],
                         axes=[int(attrs.get("axis", 1))])
            sq.append(o + "_sq")
        outs = sq
    return outs


@register("LRN")
def _lrn_export(ctx, name, ins, attrs):
    ctx.add_node("LRN", ins[:1], [name],
                 alpha=float(attrs.get("alpha", 1e-4)),
                 beta=float(attrs.get("beta", 0.75)),
                 bias=float(attrs.get("knorm", 2.0)),
                 size=int(attrs.get("nsize", 5)))
    return name


@register("Pad")
@register("pad")
def _pad_export(ctx, name, ins, attrs):
    pw = [int(x) for x in attrs.get("pad_width", ())]
    nd = len(pw) // 2
    pads = [pw[2 * i] for i in range(nd)] + [pw[2 * i + 1] for i in range(nd)]
    ctx.add_node("Pad", ins[:1], [name], mode=attrs.get("mode", "constant"),
                 pads=pads, value=float(attrs.get("constant_value", 0.0)))
    return name


def _binary(onnx_op):
    def fn(ctx, name, ins, attrs):
        ctx.add_node(onnx_op, ins, [name])
        return name
    return fn


def _unary(onnx_op):
    def fn(ctx, name, ins, attrs):
        ctx.add_node(onnx_op, ins[:1], [name])
        return name
    return fn


for _mx, _onnx in [("elemwise_add", "Add"), ("broadcast_add", "Add"),
                   ("_plus", "Add"), ("elemwise_sub", "Sub"),
                   ("broadcast_sub", "Sub"), ("elemwise_mul", "Mul"),
                   ("broadcast_mul", "Mul"), ("elemwise_div", "Div"),
                   ("broadcast_div", "Div"), ("broadcast_maximum", "Max"),
                   ("broadcast_minimum", "Min"), ("broadcast_power", "Pow")]:
    register(_mx)(_binary(_onnx))

for _mx, _onnx in [("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
                   ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                   ("abs", "Abs"), ("negative", "Neg"), ("floor", "Floor"),
                   ("ceil", "Ceil"), ("identity", "Identity"),
                   ("_copy", "Identity")]:
    register(_mx)(_unary(_onnx))


def _scalar_op(onnx_op, attr_key="scalar"):
    def fn(ctx, name, ins, attrs):
        sc = ctx.fresh(name + "_scalar")
        ctx.add_initializer(sc, np.asarray(float(attrs.get(attr_key, 0.0)),
                                           np.float32))
        ctx.add_node(onnx_op, [ins[0], sc], [name])
        return name
    return fn


for _mx, _onnx in [("_plus_scalar", "Add"), ("_minus_scalar", "Sub"),
                   ("_mul_scalar", "Mul"), ("_div_scalar", "Div"),
                   ("_power_scalar", "Pow")]:
    register(_mx)(_scalar_op(_onnx))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def export_model(sym, params, input_shape, input_dtype=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export (Symbol, params) to an ONNX file.

    Matches the reference entry ``onnx_mxnet.export_model(sym, params,
    [in_shape], in_dtype, path)`` (mx2onnx/export_model.py). ``params`` maps
    arg/aux names to NDArray (or numpy). Returns the file path.
    """
    from ... import ndarray as nd_mod

    if hasattr(sym, "_outputs") is False:
        raise MXNetError("export_model expects a Symbol")
    params = {k.split(":", 1)[-1]: (v.asnumpy() if hasattr(v, "asnumpy")
                                    else np.asarray(v))
              for k, v in params.items()}

    graph = GraphProto(name=sym.name or "mxnet_tpu")
    ctx = _Ctx(graph)

    shapes = input_shape if isinstance(input_shape[0], (list, tuple)) \
        else [input_shape]
    dtypes = input_dtype if isinstance(input_dtype, (list, tuple)) \
        else [input_dtype] * len(shapes)

    order = sym.topo_nodes()

    # graph inputs: variables not provided by params
    var_inputs = [n.name for n in order
                  if n.is_var and n.name not in params]
    label_like = [v for v in var_inputs if v.endswith(("label", "_weight_"))]
    data_inputs = [v for v in var_inputs if v not in label_like]
    if len(data_inputs) != len(shapes):
        raise MXNetError(
            f"input_shape count {len(shapes)} != graph data inputs "
            f"{data_inputs}")

    outputs_of: Dict[int, List[str]] = {}
    for node in order:
        if node.is_var:
            if node.name in params:
                ctx.add_initializer(node.name, params[node.name])
            elif node.name in data_inputs:
                i = data_inputs.index(node.name)
                graph.inputs.append(ValueInfoProto(
                    node.name, DTYPE_TO_ONNX[np.dtype(dtypes[i])],
                    shapes[i]))
            else:
                continue  # label var unused at inference
            outputs_of[id(node)] = [node.name]
            continue
        fn = MX2ONNX_TRANSLATORS.get(node.op)
        if fn is None:
            raise MXNetError(f"ONNX export: op {node.op} not supported "
                             f"(node {node.name})")
        ins = []
        for (inp, idx) in node.inputs:
            names = outputs_of.get(id(inp))
            if names is None:
                continue  # dropped label path
            ins.append(names[min(idx, len(names) - 1)])
        out = fn(ctx, node.name, ins, node.attrs or {})
        outputs_of[id(node)] = [out] if isinstance(out, str) else list(out)

    for (out_node, idx) in sym._outputs:
        names = outputs_of[id(out_node)]
        graph.outputs.append(ValueInfoProto(
            names[min(idx, len(names) - 1)], 1, ()))

    model = ModelProto(graph=graph)
    model.save(onnx_file_path)
    if verbose:
        print(f"exported {len(graph.nodes)} nodes -> {onnx_file_path}")
    return onnx_file_path
