"""Accelerated-inference toggle (reference
``python/mxnet/contrib/tensorrt.py``: get/set_use_tensorrt +
init_tensorrt_params gate the TensorRT graph pass). TPU-native equivalent:
the flag gates ahead-of-time XLA compilation of bound inference executors —
there is no external engine to hand subgraphs to, XLA *is* the engine — so
the API is preserved and `init_tensorrt_params` simply returns the params
it was given (the XLA path needs no engine-side weight copy)."""
from __future__ import annotations

_USE_RT = False

__all__ = ["set_use_tensorrt", "get_use_tensorrt", "init_tensorrt_params"]


def set_use_tensorrt(status: bool) -> None:
    global _USE_RT
    _USE_RT = bool(status)


def get_use_tensorrt() -> bool:
    return _USE_RT


def init_tensorrt_params(sym, arg_params, aux_params):
    """Reference signature parity (tensorrt.py:init_tensorrt_params); the
    XLA inference path consumes params directly."""
    return arg_params, aux_params
