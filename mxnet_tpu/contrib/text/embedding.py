"""Token embeddings (reference ``contrib/text/embedding.py``).

The reference downloads GloVe/fastText files; this environment has zero
egress, so the download registry returns the known file names for API parity
while ``CustomEmbedding`` loads any local pretrained file in the same
``token v1 v2 ...`` format. Lookup/update semantics (``get_vecs_by_tokens``,
``update_token_vectors``, unknown-token handling) follow the reference.
"""
from __future__ import annotations

import io
import logging
from typing import List, Optional

import numpy as np

from ... import ndarray as nd

__all__ = ["TokenEmbeddingBase", "CustomEmbedding",
           "get_pretrained_file_names"]

_KNOWN_PRETRAINED = {
    "glove": ["glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
              "glove.6B.200d.txt", "glove.6B.300d.txt",
              "glove.840B.300d.txt", "glove.twitter.27B.25d.txt",
              "glove.twitter.27B.50d.txt", "glove.twitter.27B.100d.txt",
              "glove.twitter.27B.200d.txt"],
    "fasttext": ["wiki.en.vec", "wiki.simple.vec"],
}


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained-file registry (reference
    embedding.py:get_pretrained_file_names). Files must be supplied locally
    (no network egress on this platform)."""
    if embedding_name is None:
        return dict(_KNOWN_PRETRAINED)
    if embedding_name not in _KNOWN_PRETRAINED:
        raise KeyError(f"unknown embedding {embedding_name}")
    return list(_KNOWN_PRETRAINED[embedding_name])


class TokenEmbeddingBase:
    """Shared indexing + lookup (reference ``_TokenEmbedding``)."""

    def __init__(self, unknown_token="<unk>"):
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None  # NDArray (V, D)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return int(self._idx_to_vec.shape[1])

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    def _load_embedding_txt(self, file_path, elem_delim=" ",
                            encoding="utf8"):
        tokens, vecs = [], []
        vec_len = None
        with io.open(file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) <= 2:   # header line (fasttext) or junk
                    continue
                token, elems = parts[0], parts[1:]
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    logging.warning("line %d has %d elems, expected %d — "
                                    "skipped", line_num, len(elems), vec_len)
                    continue
                if token in self._token_to_idx:
                    continue
                try:
                    vec = [float(x) for x in elems]
                except ValueError:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                tokens.append(token)
                vecs.append(vec)
        if vec_len is None:
            raise ValueError(f"no vectors parsed from {file_path}")
        mat = np.zeros((len(self._idx_to_token), vec_len), np.float32)
        mat[1:len(vecs) + 1] = np.asarray(vecs, np.float32)
        self._idx_to_vec = nd.array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown → the unknown vector (index 0)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            if t in self._token_to_idx:
                idx.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                idx.append(self._token_to_idx[t.lower()])
            else:
                idx.append(0)
        vecs = self._idx_to_vec[np.asarray(idx)]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of existing tokens (reference
        embedding.py:update_token_vectors)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        arr = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors)
        if arr.ndim == 1:
            arr = arr[None]
        mat = np.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, vec in zip(toks, arr):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is unknown; only existing "
                                 "tokens can be updated")
            mat[self._token_to_idx[t]] = vec
        self._idx_to_vec = nd.array(mat)


class CustomEmbedding(TokenEmbeddingBase):
    """Embedding loaded from a local ``token v1 v2 ...`` text file
    (reference embedding.py:CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 vocabulary=None, unknown_token="<unk>"):
        super().__init__(unknown_token=unknown_token)
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding)
        if vocabulary is not None:
            self._restrict_to_vocab(vocabulary)

    def _restrict_to_vocab(self, vocabulary):
        old_vec = self._idx_to_vec
        old_map = self._token_to_idx
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        rows = [old_map.get(t, 0) for t in self._idx_to_token]
        self._idx_to_vec = old_vec[np.asarray(rows)]
