"""``mx.contrib.text`` — vocabulary + token embeddings (reference
``python/mxnet/contrib/text``: ``vocab.Vocabulary``,
``embedding.CustomEmbedding`` et al., ``utils.count_tokens_from_str``)."""
from . import utils
from . import vocab
from . import embedding
from .vocab import Vocabulary
from .embedding import CustomEmbedding, get_pretrained_file_names

__all__ = ["utils", "vocab", "embedding", "Vocabulary", "CustomEmbedding",
           "get_pretrained_file_names"]
