"""SVRG optimization (reference ``python/mxnet/contrib/svrg_optimization``).

Stochastic Variance-Reduced Gradient (Johnson & Zhang 2013): periodically
snapshot the weights, compute the full-dataset gradient at the snapshot, and
correct each minibatch gradient by ``g(w) − g(w_snap) + full_grad(w_snap)``.
"""
from .svrg_module import SVRGModule

__all__ = ["SVRGModule"]
