"""SVRGModule (reference ``contrib/svrg_optimization/svrg_module.py:30``).

Holds the training module plus a frozen-snapshot module over the same
symbol; ``update_full_grads`` sweeps the dataset to build the snapshot's
full gradient, and every minibatch gradient is corrected with the SVRG rule
before the optimizer step (reference ``_svrg_grads_update_rule`` :360).
"""
from __future__ import annotations

import logging

from ... import ndarray as nd
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if int(update_freq) < 1:
            raise ValueError("update_freq must be >= 1 (epochs between "
                             "full-gradient snapshots)")
        self.update_freq = int(update_freq)
        # frozen-weight twin over the same symbol (reference _mod_aux)
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, **kwargs)
        self._param_dict = None   # full grads at the snapshot weights

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        super().bind(data_shapes, label_shapes, for_training, **kwargs)
        self._mod_aux.bind(data_shapes, label_shapes, for_training, **kwargs)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  allow_missing=False, force_init=True)

    def forward(self, data_batch, is_train=None):
        super().forward(data_batch, is_train)
        if is_train or (is_train is None and self.for_training):
            self._mod_aux.forward(data_batch, is_train=True)

    def backward(self, out_grads=None):
        super().backward(out_grads)
        if self._mod_aux.binded:
            self._mod_aux.backward(out_grads)
        if self._param_dict is not None:
            self._update_svrg_gradients()

    def update_full_grads(self, train_data):
        """Snapshot current weights into the aux module and accumulate the
        full-dataset gradient there (reference svrg_module.py:292)."""
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg_params=arg, aux_params=aux)
        train_data.reset()
        accum = {}
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            ex = self._mod_aux._exec_group.execs[0]
            for name, grad in ex.grad_dict.items():
                if grad is None:
                    continue
                if name not in accum:
                    accum[name] = grad.copy()
                else:
                    accum[name] += grad
            nbatch += 1
        if nbatch == 0:
            raise ValueError("empty train_data in update_full_grads")
        self._param_dict = {k: v / nbatch for k, v in accum.items()}

    def _update_svrg_gradients(self):
        """g ← g(w) − g(w_snap) + full_grad(w_snap) (reference :360-393)."""
        ex = self._exec_group.execs[0]
        ex_aux = self._mod_aux._exec_group.execs[0]
        for name, grad in ex.grad_dict.items():
            if grad is None or name not in self._param_dict:
                continue
            g_aux = ex_aux.grad_dict.get(name)
            if g_aux is None:
                continue
            corrected = grad - g_aux + self._param_dict[name]
            grad[:] = corrected

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=1, **kwargs):
        """Training loop with periodic full-gradient snapshots (reference
        svrg_module.py:395). Accepts the core BaseModule.fit options."""
        from ...initializer import Uniform
        from ... import metric as metric_mod

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer=initializer or Uniform(0.01))
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            train_data.reset()
            eval_metric.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward(batch, is_train=True)
                self.backward()
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    batch_end_callback(type("P", (), {
                        "epoch": epoch, "nbatch": nbatch,
                        "eval_metric": eval_metric, "locals": None})())
            name, val = eval_metric.get()
            logging.info("Epoch[%d] Train-%s=%s", epoch, name, val)
            if eval_data is not None:
                eval_metric.reset()
                eval_data.reset()
                for batch in eval_data:
                    self.forward(batch, is_train=False)
                    self.update_metric(eval_metric, batch.label)
                vname, vval = eval_metric.get()
                logging.info("Epoch[%d] Validation-%s=%s", epoch, vname, vval)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                epoch_end_callback(epoch, self._symbol, arg, aux)
