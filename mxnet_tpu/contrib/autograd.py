"""Legacy experimental autograd API (reference
``python/mxnet/contrib/autograd.py`` — the pre-``mx.autograd`` surface:
set_is_training, train_section, backward, grad/grad_and_loss decorators).
Thin adapters over the first-class ``mxnet_tpu.autograd``."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from .. import ndarray as nd

__all__ = ["set_is_training", "train_section", "test_section", "backward",
           "compute_gradient", "grad_and_loss", "grad"]


def set_is_training(is_train: bool):
    """Reference contrib/autograd.py:set_is_training; returns previous."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    _ag.set_recording(is_train)
    return prev


train_section = _ag.record
test_section = _ag.pause


def backward(outputs, out_grads=None, retain_graph=False):
    outs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
    grads = None
    if out_grads is not None:
        # no truthiness on NDArray (multi-element __bool__ is ambiguous)
        grads = list(out_grads) if isinstance(out_grads, (list, tuple)) \
            else [out_grads]
    return _ag.backward(outs, grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """Deprecated alias (reference :89)."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator: returns (gradients, loss) (reference :120)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            idx = argnum if isinstance(argnum, (list, tuple)) else [argnum]
            variables = [args[i] for i in idx]
        for x in variables:
            x.attach_grad()
        with _ag.record():
            outputs = func(*args)
        backward([outputs] if not isinstance(outputs, (list, tuple))
                 else list(outputs))
        grads = [x.grad for x in variables]
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Decorator: returns gradients only (reference :149)."""
    g_l = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return g_l(*args)[0]
    return wrapped
