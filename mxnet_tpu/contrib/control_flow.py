"""Control-flow operators: foreach / while_loop / cond.

Reference parity: ``src/operator/control_flow.cc:1255-1423`` (_foreach,
_while_loop, _cond with full gradients) and the python frontends
``mx.nd.contrib.foreach`` etc.

TPU-first: these lower straight to ``lax.scan`` / ``lax.while_loop`` /
``lax.cond`` — XLA's native structured control flow, compiled once regardless
of trip count (the reference re-executes the subgraph per step through the
engine). Gradients flow through ``foreach``/``cond`` via the tape by treating
the whole construct as one vjp node, like CachedOp; ``while_loop`` is
forward-only (XLA while is not reverse-differentiable — same restriction the
reference documents for non-static loops).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap, _wrap

__all__ = ["foreach", "while_loop", "cond"]


def _wrap_list(xs):
    return [_wrap(x) for x in xs]


def _unwrap_list(xs):
    if isinstance(xs, NDArray):
        return [_unwrap(xs)]
    return [_unwrap(x) for x in xs]


def _maybe_single(lst, was_single):
    return lst[0] if was_single and len(lst) == 1 else lst


def foreach(body: Callable, data, init_states):
    """Scan ``body(x_t, states) -> (out_t, new_states)`` over axis 0 of
    ``data`` (reference control_flow.cc _foreach). Compiles to one
    ``lax.scan``; differentiable through the tape."""
    single_data = isinstance(data, NDArray)
    single_state = isinstance(init_states, NDArray)
    data_list = _unwrap_list(data)
    state_list = _unwrap_list(init_states)
    n_state = len(state_list)

    def scan_fn(carry, xs):
        xs_nd = _wrap_list(list(xs))
        st_nd = _wrap_list(list(carry))
        with autograd.pause():
            out, new_states = body(_maybe_single(xs_nd, single_data),
                                   _maybe_single(st_nd, single_state))
        out_list = _unwrap_list(out)
        ns_list = _unwrap_list(new_states)
        return tuple(ns_list), tuple(out_list)

    def run(*flat):
        d = flat[:len(data_list)]
        s = flat[len(data_list):]
        final_states, outs = lax.scan(scan_fn, tuple(s), tuple(d))
        return tuple(outs) + tuple(final_states)

    if autograd.is_recording():
        inputs = data_list + state_list
        holders = (_wrap_list(data_list) if not single_data else [data]) + \
            (_wrap_list(state_list) if not single_state else [init_states])
        # rebuild holders referencing original NDArrays for tape parents
        holders = (list(data) if not single_data else [data]) + \
            (list(init_states) if not single_state else [init_states])
        res, vjp_fn = jax.vjp(run, *inputs)
        st = autograd._st()

        def node_vjp(cts):
            if not isinstance(cts, tuple):
                cts = (cts,)
            full = []
            for i, r in enumerate(res):
                ct = cts[i] if i < len(cts) and cts[i] is not None else \
                    jnp.zeros_like(r)
                full.append(ct)
            return vjp_fn(tuple(full))

        parents = [getattr(h, "_ag_node", None) for h in holders]
        slots = [getattr(h, "_ag_slot", 0) for h in holders]
        node = autograd._Node(node_vjp, parents, slots, len(res), st.counter,
                              "foreach")
        node.saved_outputs = list(res)
        st.counter += 1
        st.tape.append(node)
        wrapped = []
        for i, r in enumerate(res):
            w = _wrap(r)
            w._ag_node = node
            w._ag_slot = i
            wrapped.append(w)
    else:
        res = run(*(data_list + state_list))
        wrapped = _wrap_list(res)

    n_out = len(wrapped) - n_state
    outs = wrapped[:n_out]
    states = wrapped[n_out:]
    return _maybe_single(outs, True if n_out == 1 else False), \
        _maybe_single(states, single_state)


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int = None):
    """Reference _while_loop semantics with XLA lowering. The reference
    collects per-step outputs into a max_iterations buffer; same here.
    Forward-only (document parity: gradients require bounded scan — use
    foreach)."""
    single = isinstance(loop_vars, NDArray)
    vars_list = _unwrap_list(loop_vars)
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (static bound "
                         "for XLA; the reference requires it too)")

    def c(state):
        i, vs = state
        with autograd.pause():
            keep = cond_fn(_maybe_single(_wrap_list(list(vs)), single))
        return jnp.logical_and(i < max_iterations,
                               jnp.asarray(_unwrap(keep), bool).reshape(()))

    def b(state):
        i, vs = state
        with autograd.pause():
            _, new_vars = func(_maybe_single(_wrap_list(list(vs)), single))
        return i + 1, tuple(_unwrap_list(new_vars))

    steps, final = lax.while_loop(c, b, (jnp.asarray(0), tuple(vars_list)))
    return _wrap(steps), _maybe_single(_wrap_list(list(final)), single)


def cond(pred_fn: Union[Callable, NDArray], then_func: Callable,
         else_func: Callable, inputs=None):
    """Reference _cond: both branches traced once, selected at run time by
    ``lax.cond``."""
    if callable(pred_fn):
        with autograd.pause():
            pred = pred_fn(*(inputs or []))
    else:
        pred = pred_fn
    p = jnp.asarray(_unwrap(pred), bool).reshape(())
    ins = [_unwrap(x) for x in (inputs or [])]

    def t(xs):
        with autograd.pause():
            out = then_func(*_wrap_list(list(xs))) if xs else then_func()
        return tuple(_unwrap_list(out))

    def e(xs):
        with autograd.pause():
            out = else_func(*_wrap_list(list(xs))) if xs else else_func()
        return tuple(_unwrap_list(out))

    res = lax.cond(p, t, e, tuple(ins))
    wrapped = _wrap_list(list(res))
    return wrapped[0] if len(wrapped) == 1 else wrapped
