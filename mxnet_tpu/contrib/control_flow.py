"""Control-flow operators: foreach / while_loop / cond.

Reference parity: ``src/operator/control_flow.cc:1255-1423`` (_foreach,
_while_loop, _cond with full gradients) and the python frontends
``mx.nd.contrib.foreach`` etc.

TPU-first: these lower straight to ``lax.scan`` / ``lax.while_loop`` /
``lax.cond`` — XLA's native structured control flow, compiled once regardless
of trip count (the reference re-executes the subgraph per step through the
engine). Gradients flow through ``foreach``/``cond`` via the tape by treating
the whole construct as one vjp node, like CachedOp; the imperative
``while_loop`` is forward-only (raw XLA while is not reverse-differentiable).

Both forms exist, like the reference: called with NDArrays these execute
eagerly; called with Symbols they build ``_foreach``/``_cond``/``_while_loop``
GRAPH nodes whose bodies are stored subgraphs, lowered inside the enclosing
whole-graph XLA program (symbolic ``while_loop`` compiles to a gated
``lax.scan`` over ``max_iterations``, which makes it differentiable — better
than the reference, which documents its while gradient as unsupported).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap, _wrap

__all__ = ["foreach", "while_loop", "cond"]


def _wrap_list(xs):
    return [_wrap(x) for x in xs]


def _unwrap_list(xs):
    if isinstance(xs, NDArray):
        return [_unwrap(xs)]
    return [_unwrap(x) for x in xs]


def _maybe_single(lst, was_single):
    return lst[0] if was_single and len(lst) == 1 else lst


def foreach(body: Callable, data, init_states):
    """Scan ``body(x_t, states) -> (out_t, new_states)`` over axis 0 of
    ``data`` (reference control_flow.cc _foreach). Compiles to one
    ``lax.scan``; differentiable through the tape. Accepts Symbols too —
    then it builds a ``_foreach`` graph node whose body is a stored
    subgraph, exactly the reference's symbolic form."""
    if _check_homogeneous("foreach", data, init_states):
        return _sym_foreach(body, data, init_states)
    single_data = isinstance(data, NDArray)
    single_state = isinstance(init_states, NDArray)
    data_list = _unwrap_list(data)
    state_list = _unwrap_list(init_states)
    n_state = len(state_list)

    def scan_fn(carry, xs):
        xs_nd = _wrap_list(list(xs))
        st_nd = _wrap_list(list(carry))
        with autograd.pause():
            out, new_states = body(_maybe_single(xs_nd, single_data),
                                   _maybe_single(st_nd, single_state))
        out_list = _unwrap_list(out)
        ns_list = _unwrap_list(new_states)
        return tuple(ns_list), tuple(out_list)

    def run(*flat):
        d = flat[:len(data_list)]
        s = flat[len(data_list):]
        final_states, outs = lax.scan(scan_fn, tuple(s), tuple(d))
        return tuple(outs) + tuple(final_states)

    if autograd.is_recording():
        inputs = data_list + state_list
        holders = (_wrap_list(data_list) if not single_data else [data]) + \
            (_wrap_list(state_list) if not single_state else [init_states])
        # rebuild holders referencing original NDArrays for tape parents
        holders = (list(data) if not single_data else [data]) + \
            (list(init_states) if not single_state else [init_states])
        res, vjp_fn = jax.vjp(run, *inputs)
        st = autograd._st()

        def node_vjp(cts):
            if not isinstance(cts, tuple):
                cts = (cts,)
            full = []
            for i, r in enumerate(res):
                ct = cts[i] if i < len(cts) and cts[i] is not None else \
                    jnp.zeros_like(r)
                full.append(ct)
            return vjp_fn(tuple(full))

        parents = [getattr(h, "_ag_node", None) for h in holders]
        slots = [getattr(h, "_ag_slot", 0) for h in holders]
        node = autograd._Node(node_vjp, parents, slots, len(res), st.counter,
                              "foreach")
        node.saved_outputs = list(res)
        st.counter += 1
        st.tape.append(node)
        wrapped = []
        for i, r in enumerate(res):
            w = _wrap(r)
            w._ag_node = node
            w._ag_slot = i
            wrapped.append(w)
    else:
        res = run(*(data_list + state_list))
        wrapped = _wrap_list(res)

    n_out = len(wrapped) - n_state
    outs = wrapped[:n_out]
    states = wrapped[n_out:]
    return _maybe_single(outs, True if n_out == 1 else False), \
        _maybe_single(states, single_state)


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int = None):
    """Reference _while_loop semantics with XLA lowering. The reference
    collects per-step outputs into a max_iterations buffer; same here.
    Forward-only (document parity: gradients require bounded scan — use
    foreach)."""
    if _check_homogeneous("while_loop", loop_vars):
        return _sym_while_loop(cond_fn, func, loop_vars, max_iterations)
    single = isinstance(loop_vars, NDArray)
    vars_list = _unwrap_list(loop_vars)
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (static bound "
                         "for XLA; the reference requires it too)")

    # reference contract (ndarray/contrib.py:231-290): returns (per-step
    # outputs stacked along axis 0 and padded to max_iterations, final
    # states). ONE lax.while_loop whose carry holds preallocated output
    # buffers — true early exit (no wasted iterations after the predicate
    # stops) while still collecting per-step outputs.
    def probe(vs):
        with autograd.pause():
            out, new_vars = func(_maybe_single(_wrap_list(list(vs)), single))
        out_list = [] if out is None else _unwrap_list(out)
        return tuple(out_list), tuple(_unwrap_list(new_vars))

    out_shapes = jax.eval_shape(lambda vs: probe(vs)[0], tuple(vars_list))
    bufs = tuple(jnp.zeros((int(max_iterations),) + tuple(s.shape), s.dtype)
                 for s in out_shapes)

    def keep_going(carry):
        i, vs, _ = carry
        with autograd.pause():
            keep = cond_fn(_maybe_single(_wrap_list(list(vs)), single))
        return jnp.logical_and(i < int(max_iterations),
                               jnp.asarray(_unwrap(keep), bool).reshape(()))

    def body(carry):
        i, vs, bs = carry
        ys, nv = probe(vs)
        bs = tuple(lax.dynamic_update_index_in_dim(b, y, i, 0)
                   for b, y in zip(bs, ys))
        return i + 1, nv, bs

    _, final, bufs = lax.while_loop(keep_going, body,
                                    (jnp.asarray(0), tuple(vars_list), bufs))
    outputs = _wrap_list(list(bufs))
    outputs = (outputs[0] if len(outputs) == 1 else outputs) \
        if outputs else []
    return outputs, _maybe_single(_wrap_list(list(final)), single)


def cond(pred_fn: Union[Callable, NDArray], then_func: Callable,
         else_func: Callable, inputs=None):
    """Reference _cond: both branches traced once, selected at run time by
    ``lax.cond``."""
    pred_group = None if callable(pred_fn) else pred_fn
    if _check_homogeneous("cond", pred_group, inputs):
        return _sym_cond(pred_fn, then_func, else_func, inputs)
    if callable(pred_fn):
        with autograd.pause():
            pred = pred_fn(*(inputs or []))
    else:
        pred = pred_fn
    p = jnp.asarray(_unwrap(pred), bool).reshape(())
    ins = [_unwrap(x) for x in (inputs or [])]

    def t(xs):
        with autograd.pause():
            out = then_func(*_wrap_list(list(xs))) if xs else then_func()
        return tuple(_unwrap_list(out))

    def e(xs):
        with autograd.pause():
            out = else_func(*_wrap_list(list(xs))) if xs else else_func()
        return tuple(_unwrap_list(out))

    res = lax.cond(p, t, e, tuple(ins))
    wrapped = _wrap_list(list(res))
    return wrapped[0] if len(wrapped) == 1 else wrapped


# ---------------------------------------------------------------------------
# symbolic control flow — reference _foreach / _while_loop / _cond as GRAPH
# nodes (src/operator/control_flow.cc:1255-1423), so hybridized blocks and
# Module-bound symbols can contain loops. The subgraph body is stored in the
# subgraph registry (subgraph.py) and lowered to lax.scan / lax.cond /
# gated-scan inside the enclosing whole-graph XLA program; gradients flow
# because jax differentiates through the structured control flow primitive.
# ---------------------------------------------------------------------------
import itertools as _itertools

_cf_uid = _itertools.count()


def _truthy(v) -> bool:
    return str(v).lower() in ("true", "1")


def _check_homogeneous(name, *groups):
    """All-Symbol or all-NDArray across every listed value; mixing the two
    graph forms has no meaning — raise the same clear error cond does."""
    from ..symbol.symbol import Symbol as _Sym
    flat = []
    for g in groups:
        if g is None:
            continue
        flat.extend(g if isinstance(g, (list, tuple)) else [g])
    has_sym = any(isinstance(x, _Sym) for x in flat)
    has_nd = any(isinstance(x, NDArray) for x in flat)
    if has_sym and has_nd:
        raise MXNetError(f"{name}: inputs must be all Symbols or all "
                         "NDArrays, not a mix")
    return has_sym


def _free_var_entries(sub, bound_names):
    """(names, entries) of the subgraph's free variables — outer-graph vars
    the body closed over (weights etc.), wired as extra node inputs."""
    names, entries = [], []
    for n in sub.topo_nodes():
        if n.is_var and n.name not in bound_names:
            names.append(n.name)
            entries.append((n, 0))
        if not n.is_var:
            from ..executor import _AUX_UPDATE_RULES
            if n.op in _AUX_UPDATE_RULES and not _truthy(
                    (n.attrs or {}).get("use_global_stats")):
                raise MXNetError(
                    f"op {n.op!r} ({n.name}) updates auxiliary state, which "
                    "a control-flow subgraph cannot propagate (its scan "
                    "carry holds loop states only) — move it outside the "
                    "loop or set use_global_stats=True")
    return names, entries


def _lowered_sub(sg_id, is_train):
    from ..subgraph import lowered_subgraph
    return lowered_subgraph(sg_id, is_train)


def _sym_foreach(body, data, init_states):
    from ..symbol.symbol import Symbol, Variable, _Node, Group
    from ..subgraph import _store_subgraph
    uid = next(_cf_uid)
    single_data = not isinstance(data, (list, tuple))
    datas = [data] if single_data else list(data)
    single_state = not isinstance(init_states, (list, tuple))
    states = [init_states] if single_state else list(init_states)
    x_names = [f"__foreach{uid}_x{i}" for i in range(len(datas))]
    s_names = [f"__foreach{uid}_s{i}" for i in range(len(states))]
    x_vars = [Variable(n) for n in x_names]
    s_vars = [Variable(n) for n in s_names]
    out, new_states = body(x_vars[0] if single_data else x_vars,
                           s_vars[0] if single_state else s_vars)
    outs = [out] if isinstance(out, Symbol) else list(out)
    new_states = [new_states] if isinstance(new_states, Symbol) \
        else list(new_states)
    if len(new_states) != len(states):
        raise MXNetError("foreach body must return as many states as given")
    sub = Group(outs + new_states)
    sg_id = _store_subgraph(sub)
    bound = {*x_names, *s_names}
    free_names, free_entries = _free_var_entries(sub, bound)
    node = _Node("_foreach", f"foreach{uid}",
                 {"subgraph_id": sg_id, "n_out": len(outs),
                  "n_state": len(states), "x_names": tuple(x_names),
                  "state_names": tuple(s_names),
                  "free_names": tuple(free_names)},
                 [d._outputs[0] for d in datas]
                 + [s._outputs[0] for s in states]
                 + free_entries)
    result = Symbol([(node, i) for i in range(len(outs) + len(states))])
    out_syms = [result[i] for i in range(len(outs))]
    state_syms = [result[len(outs) + i] for i in range(len(states))]
    return (out_syms[0] if len(out_syms) == 1 else out_syms), \
        (state_syms[0] if single_state else state_syms)


def _sym_cond(pred, then_func, else_func, inputs=None):
    from ..symbol.symbol import Symbol, Variable, _Node, Group
    from ..subgraph import _store_subgraph
    uid = next(_cf_uid)
    ins = list(inputs or [])
    if callable(pred):
        # predicate composed in the OUTER graph over the actual inputs
        pred = pred(*ins)
    in_names = [f"__cond{uid}_i{k}" for k in range(len(ins))]
    in_vars = [Variable(n) for n in in_names]

    def build(func):
        out = func(*in_vars)
        outs = [out] if isinstance(out, Symbol) else list(out)
        return outs

    t_outs = build(then_func)
    e_outs = build(else_func)
    if len(t_outs) != len(e_outs):
        raise MXNetError("cond branches must return the same arity")
    t_sub, e_sub = Group(t_outs), Group(e_outs)
    t_id, e_id = _store_subgraph(t_sub), _store_subgraph(e_sub)
    bound = set(in_names)
    t_free, t_entries = _free_var_entries(t_sub, bound)
    e_free, e_entries = _free_var_entries(e_sub, bound)
    node = _Node("_cond", f"cond{uid}",
                 {"then_id": t_id, "else_id": e_id, "n_out": len(t_outs),
                  "n_in": len(ins), "in_names": tuple(in_names),
                  "then_free": tuple(t_free), "else_free": tuple(e_free)},
                 [pred._outputs[0]] + [s._outputs[0] for s in ins]
                 + t_entries + e_entries)
    result = Symbol([(node, i) for i in range(len(t_outs))])
    return result if len(t_outs) > 1 else result[0]


def _sym_while_loop(cond_fn, func, loop_vars, max_iterations):
    from ..symbol.symbol import Symbol, Variable, _Node, Group
    from ..subgraph import _store_subgraph
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    uid = next(_cf_uid)
    single = isinstance(loop_vars, Symbol)
    states = [loop_vars] if single else list(loop_vars)
    s_names = [f"__while{uid}_s{i}" for i in range(len(states))]
    s_vars = [Variable(n) for n in s_names]
    arg = s_vars[0] if single else s_vars
    pred = cond_fn(arg)
    step = func(arg)
    out, new_states = step
    outs = [] if out is None else (
        [out] if isinstance(out, Symbol) else list(out))
    new_states = [new_states] if isinstance(new_states, Symbol) \
        else list(new_states)
    cond_sub = Group([pred])
    body_sub = Group(outs + new_states)
    c_id, b_id = _store_subgraph(cond_sub), _store_subgraph(body_sub)
    bound = set(s_names)
    c_free, c_entries = _free_var_entries(cond_sub, bound)
    b_free, b_entries = _free_var_entries(body_sub, bound)
    node = _Node("_while_loop", f"while{uid}",
                 {"cond_id": c_id, "body_id": b_id, "n_out": len(outs),
                  "n_state": len(states), "state_names": tuple(s_names),
                  "max_iterations": int(max_iterations),
                  "cond_free": tuple(c_free), "body_free": tuple(b_free)},
                 [s._outputs[0] for s in states] + c_entries + b_entries)
    result = Symbol([(node, i) for i in range(len(outs) + len(states))])
    out_syms = [result[i] for i in range(len(outs))]
    state_syms = [result[len(outs) + i] for i in range(len(states))]
    return (out_syms[0] if len(out_syms) == 1 else out_syms), \
        (state_syms[0] if single else state_syms)


# ------------------------------------------------------- the op kernels
from ..ops.registry import register as _register


@_register("_foreach",
           num_outputs=lambda a: int(a["n_out"]) + int(a["n_state"]),
           needs_rng=True)
def _foreach_op(*inputs, subgraph_id=0, n_out=1, n_state=0, x_name=None,
                x_names=(), state_names=(), free_names=(), is_train=False,
                rng=None):
    """lax.scan over the stored subgraph; outputs = stacked per-step outs
    then final states (control_flow.cc _foreach output contract). Accepts
    multiple scanned inputs via x_names (reference foreach takes a list of
    data symbols); legacy single-input graphs carry x_name."""
    fn = _lowered_sub(subgraph_id, is_train)
    if not x_names:
        x_names = (x_name if x_name is not None else "x",)
    x_names = tuple(x_names)
    nd_ = len(x_names)
    datas = tuple(inputs[:nd_])
    states = tuple(inputs[nd_:nd_ + int(n_state)])
    frees = dict(zip(free_names, inputs[nd_ + int(n_state):]))
    if rng is None:
        rng = jax.random.PRNGKey(0)
    step_keys = jax.random.split(rng, datas[0].shape[0])  # fresh key per step

    def step(carry, xs):
        xvals, key = xs
        feed = dict(zip(x_names, xvals))
        feed.update(zip(state_names, carry))
        feed.update(frees)
        outs, _ = fn(feed, key)
        return tuple(outs[int(n_out):]), tuple(outs[:int(n_out)])

    final_states, ys = lax.scan(step, states, (datas, step_keys))
    return tuple(ys) + tuple(final_states)


@_register("_cond", num_outputs=lambda a: int(a["n_out"]), needs_rng=True)
def _cond_op(*inputs, then_id=0, else_id=0, n_out=1, n_in=0, in_names=(),
             then_free=(), else_free=(), is_train=False, rng=None):
    t_fn = _lowered_sub(then_id, is_train)
    e_fn = _lowered_sub(else_id, is_train)
    pred = jnp.asarray(inputs[0], bool).reshape(())
    ins = inputs[1:1 + int(n_in)]
    t_frees = dict(zip(then_free,
                       inputs[1 + int(n_in):1 + int(n_in) + len(then_free)]))
    e_frees = dict(zip(else_free, inputs[1 + int(n_in) + len(then_free):]))
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def t(xs):
        feed = dict(zip(in_names, xs))
        feed.update(t_frees)
        outs, _ = t_fn(feed, jax.random.fold_in(rng, 0))
        return tuple(outs)

    def e(xs):
        feed = dict(zip(in_names, xs))
        feed.update(e_frees)
        outs, _ = e_fn(feed, jax.random.fold_in(rng, 1))
        return tuple(outs)

    res = lax.cond(pred, t, e, tuple(ins))
    return tuple(res)


@_register("_while_loop",
           num_outputs=lambda a: int(a["n_out"]) + int(a["n_state"]),
           needs_rng=True)
def _while_loop_op(*inputs, cond_id=0, body_id=0, n_out=1, n_state=1,
                   state_names=(), max_iterations=1, cond_free=(),
                   body_free=(), is_train=False, rng=None):
    """Gated scan over max_iterations (differentiable, unlike raw
    lax.while_loop): steps past the predicate keep state frozen and emit
    zero-padded outputs, the reference's padding contract."""
    c_fn = _lowered_sub(cond_id, is_train)
    b_fn = _lowered_sub(body_id, is_train)
    states = tuple(inputs[:int(n_state)])
    c_frees = dict(zip(cond_free,
                       inputs[int(n_state):int(n_state) + len(cond_free)]))
    b_frees = dict(zip(body_free, inputs[int(n_state) + len(cond_free):]))
    if rng is None:
        rng = jax.random.PRNGKey(0)

    step_keys = jax.random.split(rng, int(max_iterations))

    def step(carry, key):
        done, st = carry
        feed = dict(zip(state_names, st))
        c_feed = dict(feed)
        c_feed.update(c_frees)
        (pred,), _ = c_fn(c_feed, key)
        run = jnp.logical_and(jnp.asarray(pred, bool).reshape(()),
                              jnp.logical_not(done))
        # double-where: past-exit iterations see SAFE (all-ones) state so a
        # body like 1/x cannot produce NaN/Inf whose gradient would poison
        # the jnp.where gating below (the classic where-NaN pitfall)
        b_feed = {n: jnp.where(run, s, jnp.ones_like(s))
                  for n, s in zip(state_names, st)}
        b_feed.update(b_frees)
        outs, _ = b_fn(b_feed, jax.random.fold_in(key, 1))
        new_st = tuple(jnp.where(run, n, o) for n, o in
                       zip(outs[int(n_out):], st))
        ys = tuple(jnp.where(run, y, jnp.zeros_like(y))
                   for y in outs[:int(n_out)])
        return (jnp.logical_not(run), new_st), ys

    (_, final), ys = lax.scan(step, (jnp.asarray(False), states), step_keys)
    return tuple(ys) + tuple(final)
