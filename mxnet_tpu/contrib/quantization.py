"""Int8 quantization.

Reference parity: ``src/operator/quantization/`` (quantize/dequantize/
requantize, quantized conv/FC, calibration pass
``quantize_graph_pass.cc``) + the driver ``python/mxnet/contrib/quantization.py``.

TPU-first: int8 matmuls feed the MXU natively; quantize/dequantize are
elementwise XLA ops that fuse with their neighbors, so no dedicated
"quantized_conv" kernels are needed — a quantized graph is the float graph
with (quantize → int8 op → dequantize) islands that XLA fuses. Calibration
(entropy/minmax thresholds) runs on host over captured activations.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap, _wrap
from ..ops.registry import register


@register("_contrib_quantize", aliases=["contrib_quantize"], num_outputs=3,
          differentiable=False)
def _quantize(data, min_range, max_range, out_type="int8"):
    """Affine-quantize float → int8 given calibrated range (reference
    quantization/quantize.cc)."""
    mn = jnp.minimum(min_range, 0.0)
    mx = jnp.maximum(max_range, 0.0)
    scale = 127.0 / jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    return q, -amax, amax


@register("_contrib_dequantize", aliases=["contrib_dequantize"],
          differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32"):
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * (amax / 127.0)


@register("_contrib_requantize", aliases=["contrib_requantize"], num_outputs=3,
          differentiable=False)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None, out_type="int8"):
    f = data.astype(jnp.float32) * (jnp.maximum(jnp.abs(min_range),
                                                jnp.abs(max_range)) / 0x7FFFFFFF)
    if min_calib_range is not None:
        mn, mx = min_calib_range, max_calib_range
    else:
        mn, mx = jnp.min(f), jnp.max(f)
    amax = jnp.maximum(abs(mn) if not hasattr(mn, "shape") else jnp.abs(mn),
                       abs(mx) if not hasattr(mx, "shape") else jnp.abs(mx))
    q = jnp.clip(jnp.round(f * (127.0 / amax)), -127, 127).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_quantized_fully_connected", num_outputs=3,
          differentiable=False,
          arg_names=("data", "weight", "bias", "min_data", "max_data",
                     "min_weight", "max_weight", "min_bias", "max_bias"))
def _quantized_fc(data, weight, bias, min_data, max_data, min_weight,
                  max_weight, min_bias=None, max_bias=None, num_hidden=1,
                  no_bias=False, flatten=True):
    """int8×int8→int32 matmul on the MXU (reference quantized_fully_connected.cc)."""
    d = data.astype(jnp.int32)
    if flatten and d.ndim > 2:
        d = d.reshape(d.shape[0], -1)
    acc = jnp.matmul(d, weight.astype(jnp.int32).T,
                     preferred_element_type=jnp.int32)
    scale_d = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    scale_w = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    out_scale = scale_d * scale_w
    if not no_bias and bias is not None:
        scale_b = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        acc = acc + jnp.round(bias.astype(jnp.float32) * (scale_b / out_scale)
                              ).astype(jnp.int32)
    rng = out_scale * 0x7FFFFFFF
    return acc, -rng, rng


def calib_minmax(activations: np.ndarray):
    return float(np.min(activations)), float(np.max(activations))


def calib_entropy(activations: np.ndarray, num_bins: int = 8001,
                  num_quantized_bins: int = 255):
    """KL-divergence threshold search (reference quantization.py
    _get_optimal_threshold)."""
    arr = np.abs(activations.ravel())
    amax = float(arr.max()) if arr.size else 1.0
    if amax == 0:
        return -1.0, 1.0
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    for i in range(num_quantized_bins, num_bins + 1, num_bins // 64 or 1):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = len(p) / num_quantized_bins
        q = np.zeros_like(p)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), int((j + 1) * factor) or 1
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        p /= p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        kl = float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return -best_t, best_t


def quantize_params(params: Dict[str, NDArray]):
    """Quantize a parameter dict to int8 + ranges."""
    out = {}
    for name, arr in params.items():
        a = arr.asnumpy()
        amax = float(np.abs(a).max()) or 1.0
        q = np.clip(np.round(a * (127.0 / amax)), -127, 127).astype(np.int8)
        from .. import ndarray as nd
        out[name + "_quantized"] = nd.array(q, dtype="int8")
        out[name + "_min"] = nd.array(np.float32(-amax))
        out[name + "_max"] = nd.array(np.float32(amax))
    return out


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8", **kwargs):
    """Driver with reference signature (contrib/quantization.py:quantize_model).
    Round-1 scope: parameter quantization + passthrough symbol; the graph
    pass that rewrites conv/FC islands lands with the subgraph framework."""
    qarg = dict(arg_params)
    qarg.update(quantize_params({k: v for k, v in arg_params.items()
                                 if k.endswith("weight")}))
    return sym, qarg, dict(aux_params)
