"""Int8 quantization.

Reference parity: ``src/operator/quantization/`` (quantize/dequantize/
requantize, quantized conv/FC, calibration pass
``quantize_graph_pass.cc``) + the driver ``python/mxnet/contrib/quantization.py``.

TPU-first: int8 matmuls feed the MXU natively; quantize/dequantize are
elementwise XLA ops that fuse with their neighbors, so no dedicated
"quantized_conv" kernels are needed — a quantized graph is the float graph
with (quantize → int8 op → dequantize) islands that XLA fuses. Calibration
(entropy/minmax thresholds) runs on host over captured activations.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap, _wrap
# every int8 op — the codec AND quantized_fully_connected — is registered
# at package import time in ops/quantize_ops.py / ops/parity_ops.py, so
# quantized graphs bind (simple_bind included) without importing contrib;
# the re-exports below keep the historical contrib surface working
from ..ops.quantize_ops import (_dequantize, _quantize,  # noqa: F401
                                _quantized_fc, _requantize)


def calib_minmax(activations: np.ndarray):
    return float(np.min(activations)), float(np.max(activations))


def calib_entropy(activations: np.ndarray, num_bins: int = 8001,
                  num_quantized_bins: int = 255,
                  min_percentile: float = None):
    """KL-divergence threshold search (reference quantization.py
    _get_optimal_threshold).

    ``min_percentile`` (default None = pure reference behavior) floors the
    KL-optimal threshold at that percentile of |x|; pass e.g. 99.0 to stop
    a noisy KL search from clipping below the bulk of the distribution.
    This floor is a divergence from the reference when enabled — calibrated
    ranges will differ from reference-calibrated models."""
    arr = np.abs(activations.ravel())
    amax = float(arr.max()) if arr.size else 1.0
    if amax == 0:
        return -1.0, 1.0
    if arr.size < 4 * num_quantized_bins:
        # too few samples for a meaningful KL histogram search (the
        # reference calibrates over full epochs); min/max is strictly
        # better than a noise-driven threshold here
        return -amax, amax
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    for i in range(num_quantized_bins, num_bins + 1, num_bins // 64 or 1):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = len(p) / num_quantized_bins
        q = np.zeros_like(p)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), int((j + 1) * factor) or 1
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        p /= p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        kl = float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, t
    if min_percentile is not None:
        best_t = max(best_t, float(np.percentile(arr, min_percentile)))
    return -best_t, best_t


def quantize_params(params: Dict[str, NDArray]):
    """Quantize a parameter dict to int8 + ranges."""
    out = {}
    for name, arr in params.items():
        a = arr.asnumpy()
        amax = float(np.abs(a).max()) or 1.0
        q = np.clip(np.round(a * (127.0 / amax)), -127, 127).astype(np.int8)
        from .. import ndarray as nd
        out[name + "_quantized"] = nd.array(q, dtype="int8")
        out[name + "_min"] = nd.array(np.float32(-amax))
        out[name + "_max"] = nd.array(np.float32(amax))
    return out


def quantize_graph(sym, arg_params, excluded_sym_names=(),
                   calib_ranges=None):
    """The int8 graph pass (reference quantize_graph_pass.cc): rewrite every
    FullyConnected/Convolution node into a quantize -> int8 op -> dequantize
    island. Weights/biases become int8 parameter variables (``*_quantized``
    with ``*_min``/``*_max`` ranges); activations quantize at runtime from
    observed min/max, or from calibrated ranges when ``calib_ranges`` maps a
    node name to (min, max).

    Returns (new_symbol, extra_arg_params) — merge extras into arg_params.
    """
    from .. import ndarray as nd_mod
    from ..symbol.symbol import Symbol, _Node

    calib_ranges = calib_ranges or {}
    excluded = set(excluded_sym_names)
    extra: Dict[str, "object"] = {}
    remap: Dict[int, _Node] = {}

    q_var_cache: Dict[str, tuple] = {}

    def q_param_vars(pname):
        """int8 weight/bias variables backed by quantized params; shared
        params (tied layers) quantize once and reuse the same var nodes."""
        if pname in q_var_cache:
            return q_var_cache[pname]
        # one source of truth for the int8 math: quantize_params
        extra.update(quantize_params({pname: arg_params[pname]}))
        nodes = (_Node(None, pname + "_quantized", {}, []),
                 _Node(None, pname + "_min", {}, []),
                 _Node(None, pname + "_max", {}, []))
        q_var_cache[pname] = nodes
        return nodes

    def new_entry(entry):
        src, idx = entry
        return (remap[id(src)], idx)

    for node in sym.topo_nodes():
        if node.is_var:
            remap[id(node)] = node
            continue
        inputs = [new_entry(e) for e in node.inputs]
        _no_bias = str(node.attrs.get("no_bias", False)).lower() in ("true",
                                                                     "1")
        # same bias discipline as quant.qpass: a node WITH a bias must
        # have it as a param var — never silently zero a computed bias
        bias_quantizable = _no_bias or (
            len(node.inputs) >= 3 and node.inputs[2][0].is_var
            and node.inputs[2][0].name in arg_params)
        quantizable = (node.op in ("FullyConnected", "Convolution")
                       and node.name not in excluded
                       and len(node.inputs) >= 2
                       and node.inputs[1][0].is_var
                       and node.inputs[1][0].name in arg_params
                       and bias_quantizable)
        if not quantizable:
            nn = _Node(node.op, node.name, dict(node.attrs), inputs)
            remap[id(node)] = nn
            continue

        data_e = inputs[0]
        wname = node.inputs[1][0].name
        wq, wmin, wmax = q_param_vars(wname)

        # activation ranges: calibrated constants, else runtime min/max
        if node.name in calib_ranges:
            mn_v, mx_v = calib_ranges[node.name]
            extra[node.name + "_data_min"] = nd_mod.array(np.float32(mn_v))
            extra[node.name + "_data_max"] = nd_mod.array(np.float32(mx_v))
            mn_e = (_Node(None, node.name + "_data_min", {}, []), 0)
            mx_e = (_Node(None, node.name + "_data_max", {}, []), 0)
        else:
            mn_e = (_Node("min", node.name + "_rt_min", {}, [data_e]), 0)
            mx_e = (_Node("max", node.name + "_rt_max", {}, [data_e]), 0)
        qd = _Node("_contrib_quantize", node.name + "_quantize", {},
                   [data_e, mn_e, mx_e])

        no_bias = str(node.attrs.get("no_bias", False)).lower() in ("true",
                                                                    "1")
        if not no_bias and len(node.inputs) >= 3 \
                and node.inputs[2][0].is_var \
                and node.inputs[2][0].name in arg_params:
            bname = node.inputs[2][0].name
        else:
            # the int8 ops take bias positionally: synthesize zeros
            bname = node.name + "_zero_bias"
            out_ch = int(node.attrs.get("num_hidden",
                                        node.attrs.get("num_filter", 1)))
            arg_params = dict(arg_params)
            arg_params[bname] = nd_mod.zeros((out_ch,))
        bq, bmin, bmax = q_param_vars(bname)

        qop = ("_contrib_quantized_fully_connected"
               if node.op == "FullyConnected" else "_contrib_quantized_conv")
        attrs = dict(node.attrs)
        attrs["no_bias"] = False
        # positional order: data, weight, bias, min_data, max_data,
        # min_weight, max_weight, min_bias, max_bias
        qn = _Node(qop, node.name + "_int8", attrs,
                   [(qd, 0), (wq, 0), (bq, 0), (qd, 1), (qd, 2),
                    (wmin, 0), (wmax, 0), (bmin, 0), (bmax, 0)])
        # int32 accumulator -> int8 (requantize) -> float (dequantize),
        # the reference island shape (quantize_graph_pass.cc)
        rq = _Node("_contrib_requantize", node.name + "_requantize", {},
                   [(qn, 0), (qn, 1), (qn, 2)])
        deq = _Node("_contrib_dequantize", node.name + "_dequantize", {},
                    [(rq, 0), (rq, 1), (rq, 2)])
        remap[id(node)] = deq

    new_sym = Symbol([(remap[id(n)], i) for (n, i) in sym._outputs])
    return new_sym, extra


def _collect_calib_ranges(sym, arg_params, aux_params, data_names,
                          calib_data, num_calib_examples, mode,
                          min_percentile=None):
    """Run the FLOAT graph over calibration batches, recording each
    quantizable node's input range (reference calibration pass)."""
    import mxnet_tpu as mx
    from ..symbol.symbol import Symbol

    targets = {}
    for node in sym.topo_nodes():
        if node.op in ("FullyConnected", "Convolution"):
            targets[node.name] = node.inputs[0]
    if not targets:
        return {}
    probe = Symbol(list(targets.values()))
    names = list(targets)
    # streaming stats: 'naive' keeps a running min/max; 'entropy' keeps a
    # bounded subsample per layer — never the full activation history
    # (a real conv net's activations would be tens of GB otherwise)
    minmax = {n: (np.inf, -np.inf) for n in names}
    samples = {n: [] for n in names}
    cap = 1 << 20         # per-layer element budget for the entropy search
    kept = {n: 0 for n in names}
    seen = 0
    exe = None
    rs = np.random.RandomState(0)
    for batch in calib_data:
        datas = batch.data if hasattr(batch, "data") else [batch]
        if exe is None:   # bind ONCE: the executor's jit cache is
            feed = {dn: d for dn, d in zip(data_names, datas)}
            for k, v in arg_params.items():
                feed.setdefault(k, v)
            exe = probe.bind(mx.cpu(), feed,
                             aux_states=dict(aux_params) or None)
            outs = exe.forward()
        else:             # per-instance; later batches reuse the program
            outs = exe.forward(**{dn: d for dn, d in zip(data_names, datas)})
        for n, o in zip(names, outs):
            a = np.asarray(o.asnumpy()).ravel()
            lo, hi = minmax[n]
            minmax[n] = (min(lo, float(a.min())), max(hi, float(a.max())))
            if mode == "entropy" and kept[n] < cap:
                take = min(cap - kept[n], a.size)
                # with-replacement sampling: O(take), statistically
                # equivalent for the KL histogram
                sel = a if take == a.size else a[rs.randint(0, a.size, take)]
                samples[n].append(sel)
                kept[n] += take
        seen += datas[0].shape[0]
        if num_calib_examples and seen >= num_calib_examples:
            break
    ranges = {}
    for n in names:
        if mode == "entropy":
            ranges[n] = calib_entropy(np.concatenate(samples[n])
                                      if samples[n] else np.zeros(1),
                                      min_percentile=min_percentile)
        else:
            ranges[n] = minmax[n]
    return ranges


def _trace_gluon(net):
    """Capture an initialized gluon net as (symbol, arg_params, aux_params)
    using the same symbol trace hybridize() uses."""
    from .. import symbol as sym_mod

    data = sym_mod.Variable("data")
    out = net(data)
    if isinstance(out, (list, tuple)):
        out = out[0]
    var_names = {n.name for n in out.topo_nodes() if n.is_var}
    arg_params, aux_params = {}, {}
    for p in net.collect_params().values():
        if p.name in var_names and p.name != "data":
            dst = aux_params if p.grad_req == "null" else arg_params
            dst[p.name] = p.data()
    return out, arg_params, aux_params


def quantized_resnet_bench(net, x, steps=20):
    """Int8-vs-bf16 inference throughput of a gluon net on the current
    default device (the VERDICT-r2 'prove int8 end-to-end' measurement;
    reference driver: benchmark/python/quantization/benchmark_op.py).

    Returns diagnostic fields for bench.py's JSON line:
    ``int8_infer_img_s_per_chip``, ``bf16_infer_img_s_per_chip``,
    ``int8_vs_bf16`` (speedup ratio).
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx

    x = jnp.asarray(getattr(x, "_data", x))
    batch = int(x.shape[0])
    on_accel = jax.devices()[0].platform != "cpu"
    ctx = mx.tpu() if on_accel else mx.cpu()

    sym, arg_params, aux_params = _trace_gluon(net)

    def _timed(exe, feed, n):
        outs = exe.forward(**feed)   # compile + warm
        outs[0].wait_to_read()
        t0 = _time.perf_counter()
        for _ in range(n):
            outs = exe.forward(**feed)
        outs[0].wait_to_read()
        return n * batch / (_time.perf_counter() - t0)

    from ..ndarray import array as _arr

    # bf16 baseline: cast params and data so convs hit the MXU in bf16
    # (on CPU keep f32 — this path is only a correctness/driver fallback)
    def cast(a):
        a = jnp.asarray(getattr(a, "_data", a))
        return _arr(a.astype(jnp.bfloat16) if on_accel else a)
    fargs = {k: cast(v) for k, v in arg_params.items()}
    fargs["data"] = cast(x)
    faux = {k: cast(v) for k, v in aux_params.items()}
    fexe = sym.bind(ctx, fargs, grad_req="null", aux_states=faux)
    bf16_ips = _timed(fexe, {}, steps)

    qsym, qarg, qaux = quantize_model(sym, arg_params, aux_params,
                                      data_names=("data",),
                                      calib_mode="none")
    qarg = dict(qarg)
    qarg["data"] = _arr(x.astype(jnp.float32))
    qexe = qsym.bind(ctx, qarg, grad_req="null", aux_states=qaux or None)
    int8_ips = _timed(qexe, {}, steps)

    return {
        "int8_infer_img_s_per_chip": round(int8_ips, 2),
        "bf16_infer_img_s_per_chip": round(bf16_ips, 2),
        "int8_vs_bf16": round(int8_ips / bf16_ips, 3) if bf16_ips else None,
    }


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   calib_min_percentile=99.0, **kwargs):
    """Driver with the reference signature
    (contrib/quantization.py:quantize_model): rewrites conv/FC into int8
    islands via :func:`quantize_graph`. calib_mode 'none' quantizes
    activations from runtime min/max; 'naive' (min/max over calib_data) and
    'entropy' (KL threshold) bake calibrated constant ranges in.

    ``calib_min_percentile`` (framework extension, NOT in the reference):
    floors the entropy-calibrated threshold at that percentile of |x| so a
    noisy small-sample KL search cannot clip below the bulk of the
    distribution. Default 99.0; pass None for bit-faithful reference
    calibration (ranges then match reference-calibrated models)."""
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError(f"unsupported quantized_dtype {quantized_dtype!r}")
    calib_ranges = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} requires calib_data")
        calib_ranges = _collect_calib_ranges(
            sym, arg_params, aux_params, data_names, calib_data,
            num_calib_examples, calib_mode,
            min_percentile=calib_min_percentile)
    elif calib_mode != "none":
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    qsym, extra = quantize_graph(sym, arg_params,
                                 excluded_sym_names=excluded_sym_names,
                                 calib_ranges=calib_ranges)
    qarg = dict(arg_params)
    qarg.update(extra)
    return qsym, qarg, dict(aux_params)
