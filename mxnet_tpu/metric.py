"""Evaluation metrics (reference: ``python/mxnet/metric.py``, 1,649 LoC
registry of ~15 metrics)."""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE", "MSE",
           "RMSE", "CrossEntropy", "NegativeLogLikelihood", "PearsonCorrelation",
           "Perplexity", "Loss", "CompositeEvalMetric", "CustomMetric", "create",
           "np_metric"]

_METRIC_REGISTRY: Dict[str, type] = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _alias(name, klass):
    _METRIC_REGISTRY[name] = klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    key = str(metric).lower()
    if key not in _METRIC_REGISTRY:
        raise MXNetError(f"unknown metric {metric!r}")
    return _METRIC_REGISTRY[key](*args, **kwargs)


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, labels: Dict, preds: Dict):
        if self.output_names is not None:
            preds = [preds[n] for n in self.output_names]
        else:
            preds = list(preds.values())
        if self.label_names is not None:
            labels = [labels[n] for n in self.label_names]
        else:
            labels = list(labels.values())
        self.update(labels, preds)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label).astype("int32").reshape(-1)
            argsorted = np.argsort(pred, axis=1)[:, -self.top_k:]
            self.sum_metric += float((argsorted == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        if hasattr(self, "_tp"):
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label).reshape(-1).astype("int32")
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.reshape(-1) > 0.5).astype("int32")
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            precision = self._tp / max(self._tp + self._fp, 1e-12)
            recall = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * precision * recall / max(precision + recall, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self._tp = self._fp = self._fn = self._tn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label).reshape(-1).astype("int32")
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.reshape(-1) > 0.5).astype("int32")
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            denom = math.sqrt((self._tp + self._fp) * (self._tp + self._fn)
                              * (self._tn + self._fp) * (self._tn + self._fn))
            mcc = ((self._tp * self._tn - self._fp * self._fn) / denom
                   if denom else 0.0)
            self.sum_metric = mcc
            self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred)
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += float((-np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


_alias("nll_loss", NegativeLogLikelihood)
_alias("ce", CrossEntropy)
_alias("acc", Accuracy)
_alias("top_k_accuracy", TopKAccuracy)
_alias("top_k_acc", TopKAccuracy)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel()
            pred = _to_np(pred).ravel()
            self.sum_metric += float(np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred).reshape(-1, _to_np(pred).shape[-1])
            probs = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(np.log(np.maximum(probs, 1e-10)).sum())
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference metric.py:Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_np(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def update_dict(self, labels, preds):
        for m in self.metrics:
            m.update_dict(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.extend(_as_list(name))
            values.extend(_as_list(value))
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        super().__init__(name or getattr(feval, "__name__", "custom"),
                         output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            reval = self._feval(_to_np(label), _to_np(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np_metric(**kwargs):
    def deco(f):
        return CustomMetric(f, **kwargs)
    return deco
