"""Profiler — chrome-trace JSON + XLA/TPU trace sessions.

Reference parity: ``src/profiler/profiler.{h,cc}`` + ``python/mxnet/profiler.py``
(set_config/start/stop/dump, mode bitmask {symbolic, imperative, api, memory}
profiler.h:256-262, ProfileDomain/Task/Event/Counter/Marker objects
profiler.h:556+, aggregate summary aggregate_stats.cc, env autostart
MXNET_PROFILER_AUTOSTART).

TPU-first: host-side events (op dispatches, graph executions, API calls) are
recorded directly in chrome-trace format; device-side timing comes from an
XLA profiler session (``jax.profiler``) whose TensorBoard trace dir sits next
to the JSON file — the split mirrors the reference's CPU-op vs GPU-kernel
event streams.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .base import get_env

__all__ = ["set_config", "start", "stop", "pause", "resume", "dump", "dumps",
           "Domain", "Task", "Event", "Counter", "Marker", "profiler_set_state",
           "set_state", "set_kvstore_handle"]

_lock = threading.Lock()


class _ProfilerState:
    def __init__(self):
        self.running = False
        # pause depth, not a flag: pause()/resume() nest (refcounted), so a
        # library span that brackets its own pause/resume can never un-pause
        # a user's outer pause (reference profiler.cc pause counter)
        self.pause_depth = 0
        self.events: List[dict] = []
        self.filename = "profile.json"
        self.modes = {"symbolic": True, "imperative": True, "api": False,
                      "memory": False}
        self.aggregate = False
        self.xla_trace_dir: Optional[str] = None
        self.t0 = time.perf_counter()

    def us(self):
        return (time.perf_counter() - self.t0) * 1e6


_prof = _ProfilerState()


# ---- server-process profiling over the kvstore control channel -----------
# Reference: profiler commands ride the ps-lite control wire to server nodes
# (KVStoreServerProfilerCommand, include/mxnet/kvstore.h:49; exercised by
# tests/nightly/test_server_profiling.py). TPU-native: "servers" are every
# rank's in-process store shard; commands broadcast through the coordination
# service (kvstore._send_command_to_servers) and each rank applies them to
# its server-role profile state below.

profiler_kvstore_handle = None

# the server role shares the process-wide event stream but owns its state:
# config/run/pause arriving on the control channel never clobber what the
# local worker-side profiler is doing
_server = {"filename": "server_profile.json", "running": False,
           "paused": False, "started_engine": False}


def set_kvstore_handle(kvstore) -> None:
    """Register the kvstore whose control channel carries
    profile_process='server' commands (reference profiler.py:29)."""
    global profiler_kvstore_handle
    profiler_kvstore_handle = kvstore


def _send_server_cmd(head: int, body: str) -> None:
    from .base import MXNetError
    if profiler_kvstore_handle is None:
        raise MXNetError(
            "profile_process='server' needs a dist kvstore registered via "
            "profiler.set_kvstore_handle(kv)")
    profiler_kvstore_handle._send_command_to_servers(head, body)


def _server_set_config(body: str, rank: int) -> None:
    cfg = json.loads(body)
    with _lock:
        fname = cfg.get("filename")
        if fname:
            _server["filename"] = "rank%d_%s" % (rank, fname)


def _server_set_state(body: str) -> None:
    st = json.loads(body).get("state", "stop")
    if st == "run":
        _server["running"] = True
        if not _prof.running:           # share the process event stream
            start()
            _server["started_engine"] = True
    else:
        _server["running"] = False
        if _server["started_engine"]:
            stop()
            _server["started_engine"] = False


def _server_pause(body: str) -> None:
    _server["paused"] = bool(json.loads(body).get("paused", True))


def _server_dump(rank: int) -> None:
    with _lock:
        trace = {"traceEvents": list(_prof.events), "displayTimeUnit": "ms"}
    with open(_server["filename"], "w") as f:
        json.dump(trace, f)


def set_config(profile_all=False, profile_symbolic=False, profile_imperative=False,
               profile_memory=False, profile_api=False, filename="profile.json",
               aggregate_stats=False, profile_process="worker",
               xla_trace_dir=None, **kwargs):
    if profile_process == "server":
        from .kvstore import CMD_SET_PROFILER_CONFIG
        _send_server_cmd(CMD_SET_PROFILER_CONFIG,
                         json.dumps({"filename": filename,
                                     "profile_all": bool(profile_all)}))
        return
    with _lock:
        _prof.filename = filename
        _prof.aggregate = aggregate_stats
        _prof.xla_trace_dir = xla_trace_dir
        if profile_all:
            for k in _prof.modes:
                _prof.modes[k] = True
        else:
            _prof.modes.update(symbolic=profile_symbolic,
                               imperative=profile_imperative,
                               memory=profile_memory, api=profile_api)


def start():
    with _lock:
        _prof.running = True
        _prof.pause_depth = 0
        _prof.t0 = time.perf_counter()
        _prof.events = []
    if _prof.xla_trace_dir:
        import jax
        try:
            # device/XLA lanes only — the python tracer adds tens of
            # thousands of interpreter-frame events we don't want merged
            opts = jax.profiler.ProfileOptions()
            opts.python_tracer_level = 0
            jax.profiler.start_trace(_prof.xla_trace_dir,
                                     profiler_options=opts)
        except Exception:
            jax.profiler.start_trace(_prof.xla_trace_dir)


def stop():
    with _lock:
        _prof.running = False
    if _prof.xla_trace_dir:
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        n = _merge_xla_trace(_prof.xla_trace_dir)
        if n:
            record_event("xla_device_trace_merged", "profiler", _prof.us(),
                         0.0, {"events": n})


def _merge_xla_trace(trace_dir: str) -> int:
    """Fold the XLA profiler's own chrome trace (device lanes: per-op XLA
    timings, TPU steps) into our event list so ``dump()`` emits ONE trace
    with host + device rows — the reference's engine ``opr_profile`` gives
    the same merged view (src/profiler/profiler.h:556).

    jax.profiler.stop_trace writes plugins/profile/<run>/<host>.trace.json.gz
    (TensorBoard layout); we take the newest run, shift its timestamps to
    this profiler's zero, and keep its pid/tid lane metadata."""
    import glob
    import gzip
    paths = glob.glob(os.path.join(trace_dir, "plugins", "profile", "*",
                                   "*.trace.json.gz"))
    if not paths:
        return 0
    latest = max(paths, key=os.path.getmtime)
    try:
        with gzip.open(latest, "rt") as f:
            data = json.load(f)
    except Exception:
        return 0
    evs = data.get("traceEvents") or []
    stamped = [e for e in evs if isinstance(e.get("ts"), (int, float))
               and e.get("ph") != "M"]
    if not stamped:
        return 0
    t_min = min(e["ts"] for e in stamped)
    merged = 0
    with _lock:
        for e in evs:
            e = dict(e)
            if str(e.get("name", "")).startswith("$"):
                continue        # python-tracer interpreter frames
            # device lanes keep their own pid; offset into our pid space so
            # they can never collide with the host process row
            if isinstance(e.get("pid"), int):
                e["pid"] = e["pid"] + (1 << 20)
            if isinstance(e.get("ts"), (int, float)) and e.get("ph") != "M":
                e["ts"] = e["ts"] - t_min
            e.setdefault("args", {})
            if e.get("ph") != "M":
                e["args"]["lane"] = "xla-device"
            _prof.events.append(e)
            merged += 1
    return merged


def pause(profile_process="worker"):
    """Suspend event recording. Nestable: each ``pause()`` must be matched
    by one ``resume()`` — recording restarts only when the depth returns to
    zero, so instrumentation bracketing its own pause/resume cannot
    un-pause an enclosing user pause."""
    if profile_process == "server":
        from .kvstore import CMD_PROFILER_PAUSE
        return _send_server_cmd(CMD_PROFILER_PAUSE,
                                json.dumps({"paused": True}))
    with _lock:
        _prof.pause_depth += 1


def resume(profile_process="worker"):
    """Undo one ``pause()`` (refcounted; extra resumes are no-ops)."""
    if profile_process == "server":
        from .kvstore import CMD_PROFILER_PAUSE
        return _send_server_cmd(CMD_PROFILER_PAUSE,
                                json.dumps({"paused": False}))
    with _lock:
        _prof.pause_depth = max(0, _prof.pause_depth - 1)


def profiler_set_state(state="stop"):
    if state == "run":
        start()
    else:
        stop()


def set_state(state="stop", profile_process="worker"):
    """Reference mx.profiler.set_state: run/stop the worker profiler, or —
    with profile_process='server' — every server role over the kvstore
    control channel (tests/nightly/test_server_profiling.py)."""
    if profile_process == "server":
        from .kvstore import CMD_SET_PROFILER_STATE
        return _send_server_cmd(CMD_SET_PROFILER_STATE,
                                json.dumps({"state": state}))
    profiler_set_state(state)


def is_active(kind: str = "imperative") -> bool:
    return _prof.running and _prof.pause_depth == 0 \
        and _prof.modes.get(kind, False)


def recording() -> bool:
    """True while a worker profiling session is running and not paused —
    the gate observability spans use to mirror themselves into the
    chrome-trace stream regardless of mode bits."""
    return _prof.running and _prof.pause_depth == 0


def record_event(name: str, category: str, t_start_us: float, dur_us: float,
                 args: Optional[dict] = None):
    with _lock:
        _prof.events.append({
            "name": name, "cat": category, "ph": "X",
            "ts": t_start_us, "dur": dur_us,
            "pid": os.getpid(), "tid": threading.get_ident() % (1 << 31),
            "args": args or {}})


class _Scope:
    def __init__(self, name, category):
        self.name = name
        self.category = category

    def __enter__(self):
        self.start = _prof.us()
        return self

    def __exit__(self, *exc):
        record_event(self.name, self.category, self.start,
                     _prof.us() - self.start)
        return False


def scope(name: str, category: str = "operator") -> _Scope:
    return _Scope(name, category)


def _aggregate_table(events) -> str:
    """Per-name count/total/mean/max table (reference aggregate_stats.cc
    ``DumpTable``), sorted by total descending."""
    agg: Dict[str, List[float]] = defaultdict(list)
    for e in events:
        name, dur = e.get("name"), e.get("dur")
        if name is None or dur is None:  # metadata / phase-less rows
            continue
        agg[name].append(dur)
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(us)':>14}{'Mean(us)':>12}"
             f"{'Max(us)':>12}"]
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs):>14.1f}"
                     f"{sum(durs)/len(durs):>12.1f}{max(durs):>12.1f}")
    return "\n".join(lines)


def dumps(reset=False) -> str:
    """Aggregate text summary (reference aggregate_stats.cc table)."""
    with _lock:
        events = list(_prof.events)
        if reset:
            _prof.events = []
    return _aggregate_table(events)


def dump(finished=True, profile_process="worker"):
    """Write the chrome trace JSON (load in chrome://tracing / Perfetto).

    When the session was configured with ``aggregate_stats=True``, also
    write the aggregate summary table (count/total/mean/max per name —
    reference aggregate_stats.cc) to ``<filename>.aggregate.txt``."""
    if profile_process == "server":
        from .kvstore import CMD_PROFILER_DUMP
        return _send_server_cmd(CMD_PROFILER_DUMP, "")
    with _lock:
        events = list(_prof.events)
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        with open(_prof.filename, "w") as f:
            json.dump(trace, f)
        aggregate, filename = _prof.aggregate, _prof.filename
        if finished:
            _prof.events = []
    if aggregate:
        with open(filename + ".aggregate.txt", "w") as f:
            f.write(_aggregate_table(events) + "\n")


# ---- user-facing objects (reference profiler.py:Domain/Task/Event/...) ----
class Domain:
    def __init__(self, name):
        self.name = name


class Task:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None

    def start(self):
        self._start = _prof.us()

    def stop(self):
        if self._start is not None:
            record_event(self.name, self.domain.name, self._start,
                         _prof.us() - self._start)
            self._start = None


class Event(Task):
    pass


class Counter:
    def __init__(self, domain, name, value=0):
        self.domain = domain
        self.name = name
        self.value = value
        self._emit()

    def _emit(self):
        with _lock:
            _prof.events.append({"name": self.name, "cat": self.domain.name,
                                 "ph": "C", "ts": _prof.us(),
                                 "pid": os.getpid(),
                                 "args": {"value": self.value}})

    def set_value(self, value):
        self.value = value
        self._emit()

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    __iadd__ = increment
    __isub__ = decrement


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope_="process"):
        with _lock:
            _prof.events.append({"name": self.name, "cat": self.domain.name,
                                 "ph": "i", "ts": _prof.us(), "s": "p",
                                 "pid": os.getpid()})


# reference back-compat alias (python/mxnet/profiler.py dump_profile)
dump_profile = dump

if get_env("MXNET_PROFILER_AUTOSTART", False):
    set_config(profile_all=True)
    start()
