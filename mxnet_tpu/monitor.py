"""Monitor — per-layer output/statistic tap (reference:
``python/mxnet/monitor.py`` over ``GraphExecutor::SetMonitorCallback``,
``src/executor/graph_executor.cc:104``)."""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from .ndarray import NDArray
from .observability import catalog as _telemetry
from .observability import metrics as _obs_metrics

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        if stat_func is None:
            def stat_func(x):
                return abs(x).mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self.step = 0
        self.exes = []
        self.trainers = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(array)))

        self.stat_helper = stat_helper

    def install(self, exe, monitor_all: bool = False) -> None:
        exe.set_monitor_callback(self.stat_helper, monitor_all)
        self.exes.append(exe)

    def install_trainer(self, trainer) -> None:
        """Tap a trainer exposing ``anomaly_stats()`` (DataParallelTrainer
        with grad_guard, resilience.ResilientTrainer): each ``toc`` drains
        its grad-anomaly counters (skip count, norm EMA, last norm) into the
        stat stream next to the layer taps."""
        self.trainers.append(trainer)

    def tic(self) -> None:
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List:
        if not self.activated:
            return []
        self.activated = False
        for trainer in self.trainers:
            stats = getattr(trainer, "anomaly_stats", None)
            if stats is None:
                continue
            for name, value in sorted(stats().items()):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, value))
        res = []
        # sort=True orders by (name, step): fully deterministic regardless
        # of the callback arrival order the executor happened to produce
        # (a name-only key left equal names in arrival order)
        queue = sorted(self.queue, key=lambda x: (x[1], x[0])) \
            if self.sort else self.queue
        publish = _obs_metrics.enabled()
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            items = [v_list] if not isinstance(v_list, list) else v_list
            # one device->host sync per NDArray stat, reused by both the
            # formatted log string and the gauge below
            host = [float(v.asnumpy().reshape(-1)[0])
                    if isinstance(v, NDArray) else v for v in items]
            if publish:
                # mirror each stat into the shared registry so layer
                # statistics land in the same exposition endpoint as the
                # step/kv/checkpoint metrics (first element of multi-value
                # stats — the stat_func scalar in the common case)
                try:
                    _telemetry.MONITOR_STAT.set(float(host[0]), stat=k)
                except (TypeError, ValueError, IndexError):
                    pass        # non-numeric or empty user stat: log-only
            v = ", ".join(f"{h:.5f}" if isinstance(orig, NDArray)
                          else str(orig)
                          for orig, h in zip(items, host))
            res.append((n, k, v))
        self.queue = []
        return res

    def toc_print(self) -> None:
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
