"""Diagnostic core shared by mxlint's two front ends (graph & trace).

A finding is a :class:`Diagnostic` — rule id, severity, human message,
location, fix hint — and a lint run returns a :class:`Report` that renders
as text or JSON, filters by severity, honors suppressions, and asserts
cleanliness inside pytest. The structure deliberately mirrors what NNVM's
pass manager surfaces as CHECK failures in the reference
(``infer_graph_attr_pass.cc``), except findings are *data*, not aborts:
every later perf PR can regression-test against rule ids.

Severity contract (what the CLI exit code keys off):

* ``error``   — will run wrong or unacceptably slow on TPU; CI should fail.
* ``warning`` — likely perf hazard / footgun; surfaced, does not fail CI
  unless ``--fail-on warning``.
* ``info``    — advisory.

Suppression: every rule can be silenced per-site with a source comment
``# mxlint: disable=MXL-Txxx[,MXL-Tyyy]`` on the flagged line (or on the
``def`` line for whole-function findings), or per-run via the
``suppress=(...)`` argument / ``--suppress`` CLI flag.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Tuple

__all__ = ["Severity", "Diagnostic", "Report", "RuleDef", "RULES",
           "register_rule", "parse_disable_comment"]

# ordered severities, lowest first
_SEVERITY_ORDER = ("info", "warning", "error")


class Severity:
    INFO = "info"
    WARNING = "warning"
    ERROR = "error"

    @staticmethod
    def rank(sev: str) -> int:
        return _SEVERITY_ORDER.index(sev)


@dataclass(frozen=True)
class RuleDef:
    """One lint rule in the catalog. docs/static_analysis.md mirrors this
    registry by hand; tests/test_mxlint.py cross-checks ids and severities
    against the doc so they cannot drift."""
    rule_id: str
    severity: str
    title: str
    doc: str


RULES: Dict[str, RuleDef] = {}


def register_rule(rule_id: str, severity: str, title: str, doc: str) -> RuleDef:
    rd = RuleDef(rule_id, severity, title, doc)
    RULES[rule_id] = rd
    return rd


@dataclass
class Diagnostic:
    rule_id: str
    message: str
    #: where: op/node name for graph findings, ``file:line`` for trace ones
    location: str = ""
    hint: str = ""
    #: severity defaults to the rule's registered severity
    severity: str = ""

    def __post_init__(self):
        if not self.severity:
            rd = RULES.get(self.rule_id)
            self.severity = rd.severity if rd else Severity.WARNING

    def to_dict(self) -> Dict[str, str]:
        return {"rule": self.rule_id, "severity": self.severity,
                "message": self.message, "location": self.location,
                "hint": self.hint}

    def render(self) -> str:
        loc = f" [{self.location}]" if self.location else ""
        hint = f"\n      hint: {self.hint}" if self.hint else ""
        return f"{self.severity.upper():7s} {self.rule_id}{loc}: " \
               f"{self.message}{hint}"


_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\-\s]+)")


def parse_disable_comment(line: str) -> Tuple[str, ...]:
    """Rule ids suppressed by an inline ``# mxlint: disable=...`` comment
    (``all`` silences every rule on that line)."""
    m = _DISABLE_RE.search(line)
    if not m:
        return ()
    return tuple(t.strip() for t in m.group(1).split(",") if t.strip())


class Report:
    """Ordered collection of findings from one lint run."""

    def __init__(self, subject: str = "", front_end: str = ""):
        self.subject = subject
        self.front_end = front_end
        self.findings: List[Diagnostic] = []
        self._suppressed: List[Diagnostic] = []
        self._suppress_ids: set = set()

    # ------------------------------------------------------------- building
    def set_suppressions(self, rule_ids: Iterable[str]) -> "Report":
        self._suppress_ids = {r.strip() for r in rule_ids if r and r.strip()}
        return self

    def add(self, diag: Diagnostic, inline_disables: Sequence[str] = ()) -> None:
        if diag.rule_id in self._suppress_ids or "all" in self._suppress_ids \
                or diag.rule_id in inline_disables or "all" in inline_disables:
            self._suppressed.append(diag)
        else:
            self.findings.append(diag)

    # ------------------------------------------------------------- querying
    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def by_rule(self, rule_id: str) -> List[Diagnostic]:
        return [d for d in self.findings if d.rule_id == rule_id]

    def at_least(self, severity: str) -> List[Diagnostic]:
        r = Severity.rank(severity)
        return [d for d in self.findings if Severity.rank(d.severity) >= r]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.at_least(Severity.ERROR)

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.findings if d.severity == Severity.WARNING]

    @property
    def suppressed(self) -> List[Diagnostic]:
        return list(self._suppressed)

    def ok(self, fail_on: str = Severity.ERROR) -> bool:
        return not self.at_least(fail_on)

    # ------------------------------------------------------------ rendering
    def to_text(self) -> str:
        head = f"mxlint ({self.front_end or 'lint'}): {self.subject}"
        if not self.findings:
            body = "  clean — no findings"
            if self._suppressed:
                body += f" ({len(self._suppressed)} suppressed)"
            return f"{head}\n{body}"
        lines = [head]
        order = sorted(self.findings,
                       key=lambda d: -Severity.rank(d.severity))
        lines += ["  " + d.render() for d in order]
        n_err = len(self.errors)
        lines.append(f"  {len(self.findings)} finding(s): {n_err} error(s), "
                     f"{len(self.warnings)} warning(s)"
                     + (f", {len(self._suppressed)} suppressed"
                        if self._suppressed else ""))
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "subject": self.subject,
            "front_end": self.front_end,
            "findings": [d.to_dict() for d in self.findings],
            "suppressed": [d.to_dict() for d in self._suppressed],
            "summary": {"errors": len(self.errors),
                        "warnings": len(self.warnings),
                        "total": len(self.findings)},
        }, indent=2)

    # ------------------------------------------------------------- pytest
    def assert_clean(self, fail_on: str = Severity.ERROR) -> None:
        """Raise AssertionError (with the rendered report) if any finding at
        or above ``fail_on`` severity survived suppression — the pytest
        front door, e.g. ``lint_step(step, args).assert_clean()``."""
        bad = self.at_least(fail_on)
        if bad:
            raise AssertionError(
                f"mxlint found {len(bad)} finding(s) at severity >= "
                f"{fail_on}:\n{self.to_text()}")

    def __repr__(self):
        return (f"<Report {self.subject!r}: {len(self.findings)} finding(s), "
                f"{len(self.errors)} error(s)>")
