"""lockwatch — runtime lock-order sanitizer for the threaded host spine.

``MXNET_LOCKCHECK=1`` makes :func:`make_lock` / :func:`make_rlock` hand
out instrumented locks instead of plain ``threading`` ones. Each watched
lock keeps a per-thread held-set and feeds a process-wide acquisition-order
graph; at acquire time the sanitizer flags

* **MXL-C300** — this acquisition creates a cycle in the order graph
  (lock A is being taken under lock B somewhere after B was taken under
  A elsewhere): a potential deadlock, reported with *both* stacks.
* **MXL-C303** — the acquiring thread already holds this exact
  non-reentrant lock: a certain self-deadlock, reported **and raised** as
  :class:`LockWatchDeadlock` (blocking forever helps nobody).

It also publishes host-side telemetry (``mxtpu_lock_hold_ms{site}``,
``mxtpu_lock_contention_total{site}``,
``mxtpu_lockwatch_findings_total{rule}``) — all of it host-only
bookkeeping: nothing here runs under ``jit`` or changes a traced program,
so StableHLO is bitwise identical with the sanitizer on or off (pinned by
test_mxrace.py's invariance guard).

When ``MXNET_LOCKCHECK`` is off (the default) the factories return plain
``threading.Lock()``/``RLock()`` — zero overhead, byte-identical
behavior. The static twin is :mod:`~mxnet_tpu.analysis.concurrency`; the
CLI report pretty-printer is ``tools/mxrace.py report``.
"""
from __future__ import annotations

import json
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ..base import get_env, logger, register_config

__all__ = ["make_lock", "make_rlock", "enabled", "findings", "reset",
           "assert_no_findings", "write_report", "render_report",
           "WatchedLock", "LockWatchDeadlock"]

register_config(
    "MXNET_LOCKCHECK", False, bool,
    "Swap every make_lock()/make_rlock() site for an instrumented lock: "
    "per-thread held-sets, a process-wide acquisition-order graph, "
    "deadlock findings with both stacks, and mxtpu_lock_* telemetry. "
    "Host-only; the traced program is bitwise unchanged.")
register_config(
    "MXNET_LOCKCHECK_STACK_DEPTH", 12, int,
    "Stack frames captured per lockwatch order-graph edge/finding.")


def enabled() -> bool:
    return bool(get_env("MXNET_LOCKCHECK", False))


class LockWatchDeadlock(RuntimeError):
    """Raised when a thread blocking-acquires a non-reentrant watched lock
    it already holds — the acquire would never return."""


class _Tls(threading.local):
    def __init__(self):
        self.held: List["WatchedLock"] = []   # acquisition order, newest last
        self.suppress = False                 # re-entrancy guard (telemetry)


_tls = _Tls()

# the graph state below is guarded by a *plain* lock: watched locks are
# only ever acquired before _graph_lock, never under it, so the sanitizer
# cannot deadlock the code it watches
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Dict[str, Any]] = {}   # (a,b) -> first sighting
_adj: Dict[str, set] = {}
_findings: List[Dict[str, Any]] = []
_known_cycles: set = set()


def _stack(skip: int = 2) -> str:
    depth = int(get_env("MXNET_LOCKCHECK_STACK_DEPTH", 12))
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-depth:])


def _count_finding(rule: str) -> None:
    try:
        from ..observability import metrics as _m
        if _m.enabled():
            from ..observability import catalog as _c
            _c.LOCKWATCH_FINDINGS.inc(rule=rule)
    except Exception:       # telemetry must never break the watched code
        pass


def _record(rule: str, message: str, site: str, stack: str,
            other_site: str = "", other_stack: str = "") -> Dict[str, Any]:
    finding = {
        "rule": rule, "message": message, "site": site,
        "thread": threading.current_thread().name,
        "stack": stack, "other_site": other_site,
        "other_stack": other_stack, "time": time.time(),
    }
    with _graph_lock:
        _findings.append(finding)
    logger.error("lockwatch %s: %s", rule, message)
    _count_finding(rule)
    return finding


def _path_exists(src: str, dst: str) -> bool:
    """Reachability in the order graph (callers hold _graph_lock)."""
    seen = {src}
    frontier = [src]
    while frontier:
        n = frontier.pop()
        if n == dst:
            return True
        for nxt in _adj.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return False


def _note_order(held: "WatchedLock", acquiring: "WatchedLock",
                stack: str) -> None:
    a, b = held.site, acquiring.site
    if a == b:      # two instances of one site — ordering unknowable here
        return
    with _graph_lock:
        if (a, b) in _edges:
            return
        # does b already reach a? then adding a->b closes a cycle
        cycle = _path_exists(b, a)
        _edges[(a, b)] = {
            "stack": stack,
            "thread": threading.current_thread().name,
        }
        _adj.setdefault(a, set()).add(b)
        cycle_key = frozenset((a, b))
        if not cycle or cycle_key in _known_cycles:
            return
        _known_cycles.add(cycle_key)
        other = _edges.get((b, a), {})
    _record(
        "MXL-C300",
        "lock-order inversion: %s acquired while holding %s, but the "
        "order graph already has a %s -> %s path (potential deadlock)"
        % (b, a, b, a),
        site=b, stack=stack,
        other_site=a, other_stack=other.get("stack", ""))


class WatchedLock:
    """Drop-in for ``threading.Lock``/``RLock`` with order tracking.

    Exposes acquire/release/__enter__/__exit__/locked plus the private
    hooks ``threading.Condition`` uses, so ``Condition(make_lock(...))``
    works and wait() correctly pops/pushes the held-set.
    """

    __slots__ = ("site", "reentrant", "_lock", "_depth_tls",
                 "_acquired_at")

    def __init__(self, site: str, reentrant: bool = False):
        self.site = site
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._acquired_at = 0.0       # valid while held (owner writes it)

    # ------------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tls = _tls
        if tls.suppress:
            return self._lock.acquire(blocking, timeout)
        held_here = sum(1 for l in tls.held if l is self)
        stack = None
        if held_here and not self.reentrant:
            stack = _stack()
            _record(
                "MXL-C303",
                "re-entrant acquire of non-reentrant lock %s (depth %d) — "
                "self-deadlock" % (self.site, held_here + 1),
                site=self.site, stack=stack)
            if blocking and timeout in (-1, None):
                raise LockWatchDeadlock(
                    "lockwatch: thread %r would deadlock re-acquiring %s\n%s"
                    % (threading.current_thread().name, self.site, stack))
        elif not held_here:
            for h in tls.held:
                _note_order(h, self, stack or (stack := _stack()))
        # measure contention: try uncontended first
        got = self._lock.acquire(False)
        if not got:
            if not blocking:
                return False
            self._publish_contention()
            got = self._lock.acquire(True, timeout)
        if got:
            tls.held.append(self)
            if held_here == 0:
                self._acquired_at = time.perf_counter()
        return got

    # ------------------------------------------------------------- release
    def release(self) -> None:
        tls = _tls
        if tls.suppress:
            self._lock.release()
            return
        held_ms = None
        for i in range(len(tls.held) - 1, -1, -1):
            if tls.held[i] is self:
                del tls.held[i]
                break
        if not any(l is self for l in tls.held):
            held_ms = (time.perf_counter() - self._acquired_at) * 1e3
        self._lock.release()
        if held_ms is not None:
            self._publish_hold(held_ms)

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        try:
            return self._lock.locked()
        except AttributeError:      # RLock on older Pythons
            if self._lock.acquire(False):
                self._lock.release()
                return False
            return True

    # Condition() integration: delegate the wait/notify save-restore hooks
    # through our own acquire/release so the held-set stays truthful
    def _release_save(self):
        if self.reentrant:
            tls = _tls
            depth = sum(1 for l in tls.held if l is self)
            for _ in range(depth):
                self.release()
            return depth
        self.release()
        return 1

    def _acquire_restore(self, state) -> None:
        for _ in range(state if isinstance(state, int) and state > 0 else 1):
            self.acquire()

    def _is_owned(self) -> bool:
        return any(l is self for l in _tls.held)

    # ----------------------------------------------------------- telemetry
    def _publish_contention(self) -> None:
        tls = _tls
        if tls.suppress:
            return
        tls.suppress = True
        try:
            from ..observability import metrics as _m
            if _m.enabled():
                from ..observability import catalog as _c
                _c.LOCK_CONTENTION.inc(site=self.site)
        except Exception:
            pass
        finally:
            tls.suppress = False

    def _publish_hold(self, ms: float) -> None:
        tls = _tls
        if tls.suppress:
            return
        tls.suppress = True
        try:
            from ..observability import metrics as _m
            if _m.enabled():
                from ..observability import catalog as _c
                _c.LOCK_HOLD_MS.observe(ms, site=self.site)
        except Exception:
            pass
        finally:
            tls.suppress = False

    def __repr__(self) -> str:
        return "<WatchedLock %s%s>" % (self.site,
                                       " (reentrant)" if self.reentrant
                                       else "")


# --------------------------------------------------------------------------
# factories — the only API instrumented modules call
# --------------------------------------------------------------------------
def make_lock(site: str):
    """A ``threading.Lock()`` — or a watched one under MXNET_LOCKCHECK=1.

    ``site`` names the lock *class-wide* (e.g. ``"serving.queueing."
    "BoundedRequestQueue._lock"``): instances share the label, which is
    exactly what the order graph wants (an order inversion between two
    queues is an inversion between the queue class's locks).
    """
    if enabled():
        return WatchedLock(site, reentrant=False)
    return threading.Lock()


def make_rlock(site: str):
    """``threading.RLock()`` — or a watched reentrant lock (re-entry is
    legal and tracked; ordering findings still apply)."""
    if enabled():
        return WatchedLock(site, reentrant=True)
    return threading.RLock()


# --------------------------------------------------------------------------
# findings API (what chaos tests and tools/mxrace.py consume)
# --------------------------------------------------------------------------
def findings() -> List[Dict[str, Any]]:
    with _graph_lock:
        return [dict(f) for f in _findings]


def reset() -> None:
    """Clear findings and the acquisition-order graph (test isolation)."""
    with _graph_lock:
        _findings.clear()
        _edges.clear()
        _adj.clear()
        _known_cycles.clear()


def assert_no_findings() -> None:
    got = findings()
    if got:
        raise AssertionError(
            "lockwatch recorded %d finding(s):\n%s"
            % (len(got), render_report({"findings": got})))


def edges() -> Dict[str, List[str]]:
    """The current acquisition-order graph, site -> successor sites."""
    with _graph_lock:
        return {a: sorted(bs) for a, bs in _adj.items()}


def write_report(path: str) -> str:
    data = {"findings": findings(), "order_graph": edges()}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
    return path


def render_report(data: Dict[str, Any]) -> str:
    """Pretty-print a lockwatch report dict (tools/mxrace.py report)."""
    out: List[str] = []
    fnd = data.get("findings", [])
    if not fnd:
        out.append("lockwatch: no findings")
    else:
        out.append("lockwatch: %d finding(s)" % len(fnd))
        for f in fnd:
            out.append("  %s [%s] thread=%s" % (
                f.get("rule", "?"), f.get("site", "?"),
                f.get("thread", "?")))
            out.append("    " + f.get("message", ""))
            if f.get("stack"):
                out.append("    acquire stack:")
                out.extend("      " + ln for ln
                           in f["stack"].rstrip().splitlines())
            if f.get("other_stack"):
                out.append("    prior %s stack:" % f.get("other_site", ""))
                out.extend("      " + ln for ln
                           in f["other_stack"].rstrip().splitlines())
    graph = data.get("order_graph") or {}
    if graph:
        out.append("acquisition order graph:")
        for a in sorted(graph):
            for b in graph[a]:
                out.append("  %s -> %s" % (a, b))
    return "\n".join(out)
