"""Graph lint — static analysis over Symbol / CachedOp graphs.

The reference validates graphs with NNVM passes (shape/dtype inference,
``infer_graph_attr_pass.cc:325``) that hard-abort on the first violation.
Here the same walk runs as a *linter*: every node is abstract-evaluated with
``jax.eval_shape`` (dtype-tracking, unlike ``Symbol.infer_shape`` which only
carries shapes), and violations accumulate as diagnostics instead of
aborting, so one run reports every hazard in the graph.

Rules (catalog in docs/static_analysis.md):

* MXL-G100 infer-failure       (error)   node fails abstract evaluation
* MXL-G101 float64-creep       (error)   node widens to float64 on TPU
* MXL-G102 unregistered-op     (error)   op has no TPU lowering in the registry
* MXL-G103 host-op             (warning) host=True op inside a jitted graph
* MXL-G104 dangling-input      (error)   graph input whose shape can't be
                                         inferred or bound
* MXL-G105 unused-input        (warning) provided binding not consumed
* MXL-G106 dead-subgraph       (warning) saved-graph nodes unreachable from
                                         any head
"""
from __future__ import annotations

import ast
import json
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .diagnostics import Diagnostic, Report, register_rule

__all__ = ["lint_symbol", "lint_symbol_json"]

register_rule(
    "MXL-G100", "error", "infer-failure",
    "A node fails shape/dtype abstract evaluation — binding this graph "
    "would raise at executor build time.")
register_rule(
    "MXL-G101", "error", "float64-creep",
    "A node produces float64 from non-float64 inputs (or an input is "
    "declared float64). TPUs have no native f64: XLA emulates it at a "
    "severe slowdown and doubles HBM traffic.")
register_rule(
    "MXL-G102", "error", "unregistered-op",
    "The op has no entry in ops/registry.py — there is no TPU lowering; "
    "executing the graph raises at bind time.")
register_rule(
    "MXL-G103", "warning", "host-op",
    "The op is registered host=True (data-dependent shapes, eager host "
    "execution). Inside a jitted graph it forces a host round-trip per "
    "step and blocks whole-graph XLA fusion.")
register_rule(
    "MXL-G104", "error", "dangling-input",
    "A graph input's shape can neither be inferred from the graph nor was "
    "it provided — simple_bind/infer_shape on this symbol will fail.")
register_rule(
    "MXL-G105", "warning", "unused-input",
    "A provided input binding is not consumed by any node reachable from "
    "the outputs — a stale feed dict entry or a typo'd name.")
register_rule(
    "MXL-G106", "warning", "dead-subgraph",
    "A saved graph contains nodes unreachable from any head — dead weight "
    "that inflates load time and usually indicates a truncated or "
    "mis-exported model.")
register_rule(
    "MXL-G107", "warning", "layout-propagation-missed",
    "The graph contains NCHW 2-D convolutions and is being captured with "
    "the layout pass disabled — each conv pays per-step relayouts the "
    "automatic NCHW→NHWC propagation (mxnet_tpu.passes) removes; the "
    "measured r4 win is one knob away.")
register_rule(
    "MXL-G108", "warning", "uncalibrated-quantized-graph",
    "The graph contains _contrib_quantize nodes whose activation ranges "
    "are absent/defaulted (computed from each batch at runtime instead of "
    "baked-in calibrated constants): every affected island pays two extra "
    "full reductions per step and quantizes outlier-stretched ranges — "
    "calibrate once (quant.collect / tools/mxquant.py calibrate) and "
    "quantize from the CalibTable.")


def _parse_shape_attr(v: str) -> Optional[Tuple[int, ...]]:
    try:
        t = ast.literal_eval(v)
        return tuple(int(x) for x in t)
    except (ValueError, SyntaxError, TypeError):
        return None


def _parse_dtype_attr(v: str):
    """``__dtype__`` attrs are ``str(dtype)`` — accept 'float64',
    "<class 'numpy.float64'>", 'np.float64' and friends."""
    try:
        return np.dtype(v)
    except TypeError:
        pass
    # longest names first: 'bfloat16' contains 'float16', every 'uintN'
    # contains 'intN' — repr-style attrs ("<class 'ml_dtypes.bfloat16'>")
    # must not match the shorter substring
    for name in ("float64", "bfloat16", "float32", "float16",
                 "uint64", "uint32", "uint16", "uint8",
                 "int64", "int32", "int8", "bool"):
        if name in str(v):
            return jnp.bfloat16 if name == "bfloat16" else np.dtype(name)
    return None


def _is_f64(aval) -> bool:
    return aval is not None and np.dtype(aval.dtype) in (
        np.dtype(np.float64), np.dtype(np.complex128))


def lint_symbol(symbol, shapes: Optional[Dict[str, Sequence[int]]] = None,
                dtypes: Optional[Dict[str, Any]] = None,
                suppress: Sequence[str] = (),
                subject: str = "",
                passes_applied: Optional[Sequence[str]] = None) -> Report:
    """Lint a Symbol graph. ``shapes``/``dtypes`` play the role of the
    bind-time feed dict: shapes the walker can't backfill from parameter
    rules must come from here (exactly like ``simple_bind``'s kwargs).

    ``passes_applied`` names the graph-pass pipeline the caller runs over
    this graph before binding (``()`` = passes explicitly off).  When the
    caller declares a pipeline WITHOUT the layout pass and the graph holds
    NCHW 2-D convolutions, MXL-G107 fires; ``None`` (unknown capture
    context — e.g. a bare ``Symbol.lint``) keeps the rule silent."""
    from ..ops.registry import get_op
    from ..executor import _PARAM_SHAPE_RULES
    from .._imperative import _op_signature_flags

    report = Report(subject or f"symbol {symbol.name!r}", "graph")
    report.set_suppressions(suppress)
    shapes = {k: tuple(v) for k, v in (shapes or {}).items()}
    dtypes = dict(dtypes or {})

    nodes = symbol.topo_nodes()
    var_shape: Dict[str, Tuple[int, ...]] = {}
    var_dtype: Dict[str, Any] = {}
    consumed_vars = set()
    for n in nodes:
        if not n.is_var:
            continue
        s = shapes.get(n.name)
        if s is None and "__shape__" in n._attr_dict:
            s = _parse_shape_attr(n._attr_dict["__shape__"])
        if s is not None:
            var_shape[n.name] = tuple(s)
        dt = dtypes.get(n.name)
        if dt is None and "__dtype__" in n._attr_dict:
            dt = _parse_dtype_attr(n._attr_dict["__dtype__"])
        if dt is not None:
            var_dtype[n.name] = np.dtype(dt)   # ml_dtypes covers bfloat16
            if var_dtype[n.name] == np.dtype(np.float64):
                report.add(Diagnostic(
                    "MXL-G101",
                    f"input {n.name!r} is declared float64",
                    location=f"var:{n.name}", severity="warning",
                    hint="declare float32 (MXNET_DEFAULT_DTYPE) unless the "
                         "math genuinely needs f64 emulation"))

    # a variable that IS a graph output counts as consumed (passthrough
    # heads in a Group): its binding is required, not stale
    for (node, _idx) in symbol._outputs:
        if node.is_var:
            consumed_vars.add(node.name)

    # pass-rewritten graphs interpose transposes between parameter vars
    # and the ops whose rules derive their shapes; the single-walk
    # backfill below can't see through them, so borrow the executor's
    # fixpoint inference (transpose backward-backfill included) — only
    # when such a chain exists, and never letting its failure mask the
    # per-node findings this walk reports
    if any(not n.is_var and n.op == "transpose" and n.inputs
           and n.inputs[0][0].is_var
           and n.inputs[0][0].name not in var_shape for n in nodes):
        try:
            from ..executor import _GraphLowering
            inferred = _GraphLowering(symbol).infer_shapes(dict(var_shape))
            for n in nodes:
                if n.is_var and n.name not in var_shape \
                        and isinstance(inferred.get(n.name), tuple):
                    var_shape[n.name] = tuple(inferred[n.name])
        except Exception:
            pass

    entry_aval: Dict[Tuple[int, int], Any] = {}
    dead_vars = set()    # consumed vars whose shape never resolved

    for node in nodes:
        if node.is_var:
            continue
        loc = f"{node.op}:{node.name}"
        try:
            opdef = get_op(node.op)
        except MXNetError:
            report.add(Diagnostic(
                "MXL-G102", f"op {node.op!r} has no TPU lowering "
                f"(not in ops/registry.py)", location=loc,
                hint="register a jax lowering or replace the op before "
                     "binding on TPU"))
            for (src, _) in node.inputs:
                if src.is_var:
                    consumed_vars.add(src.name)
            continue
        if opdef.host:
            report.add(Diagnostic(
                "MXL-G103", f"op {node.op!r} is host-only (host=True): it "
                "executes eagerly on CPU and forces a device round-trip",
                location=loc,
                hint="keep host ops out of jitted training graphs; run "
                     "them in the input pipeline instead"))
            # host ops have data-dependent shapes and host-side numpy
            # bodies — abstract eval would spuriously fail; their outputs
            # stay unknown and downstream nodes are skipped
            for (src, _) in node.inputs:
                if src.is_var:
                    consumed_vars.add(src.name)
            continue

        arg_names = opdef.arg_names() or []
        # parameter-shape backfill, same rule table the executor uses
        rule = _PARAM_SHAPE_RULES.get(node.op)
        if rule is not None and node.inputs:
            src0, idx0 = node.inputs[0]
            ds = (var_shape.get(src0.name) if src0.is_var
                  else (tuple(entry_aval[(id(src0), idx0)].shape)
                        if (id(src0), idx0) in entry_aval else None))
            if ds is not None:
                try:
                    param_shapes = rule(dict(node.attrs), tuple(ds))
                except KeyError:
                    param_shapes = {}
                for i, (src, _) in enumerate(node.inputs):
                    if src.is_var and src.name not in var_shape \
                            and i < len(arg_names) \
                            and arg_names[i] in param_shapes:
                        var_shape[src.name] = param_shapes[arg_names[i]]

        in_avals = []
        missing = False
        unresolved_vars = []
        poisoned = False   # depends on a skipped node (host/unregistered/
                           # failed): its unknowns are NOT the user's fault
        for (src, idx) in node.inputs:
            if src.is_var:
                consumed_vars.add(src.name)
                if src.name not in var_shape:
                    unresolved_vars.append(src.name)
                    missing = True
                    continue
                dt = var_dtype.get(src.name, jnp.float32)
                in_avals.append(jax.ShapeDtypeStruct(var_shape[src.name], dt))
            else:
                av = entry_aval.get((id(src), idx))
                if av is None:
                    poisoned = True
                    missing = True
                    continue
                in_avals.append(av)
        if missing:
            if not poisoned:
                # genuinely dangling: no upstream finding explains it.
                # Downstream of a host op (MXL-G103, warning) or a failed/
                # unregistered node the shape backfill simply couldn't run —
                # flagging those params as errors would escalate a warning-
                # severity graph into a CI failure
                dead_vars.update(unresolved_vars)
            continue   # root cause reported above or as MXL-G104 below

        attrs = dict(node.attrs)
        accepts_train, accepts_rng = _op_signature_flags(opdef)
        if accepts_train and "is_train" not in attrs:
            attrs["is_train"] = True

        def run(*arrs):
            kw = dict(attrs)
            if accepts_rng:
                kw["rng"] = jax.random.PRNGKey(0)
            return opdef.fn(*arrs, **kw)

        try:
            out_avals = jax.eval_shape(run, *in_avals)
        except Exception as e:
            report.add(Diagnostic(
                "MXL-G100", f"abstract evaluation failed: {e}", location=loc,
                hint="fix the shape/attr mismatch; this graph cannot bind"))
            continue
        if not isinstance(out_avals, tuple):
            out_avals = (out_avals,)
        for i, av in enumerate(out_avals):
            entry_aval[(id(node), i)] = av
        # a node is the f64 *source* when its output is f64 but its inputs
        # aren't all f64 — including zero-input creators (zeros/arange/
        # random with dtype='float64'), where all([]) would vacuously pass
        if any(_is_f64(av) for av in out_avals) \
                and not (in_avals and all(_is_f64(av) for av in in_avals)):
            report.add(Diagnostic(
                "MXL-G101", f"op {node.op!r} widens to float64 "
                f"(inputs: {[str(a.dtype) for a in in_avals]})", location=loc,
                hint="TPUs emulate f64; cast to float32 or drop the "
                     "widening attr (e.g. dtype='float64')"))

    for name in sorted(dead_vars):
        report.add(Diagnostic(
            "MXL-G104", f"input {name!r} is dangling: consumed by the graph "
            "but its shape can neither be inferred nor was it provided",
            location=f"var:{name}",
            hint="pass its shape to lint_symbol/simple_bind, or declare it "
                 "with Variable(shape=...)"))

    for name in sorted(set(shapes) | set(dtypes)):
        if name not in consumed_vars and name != "__outputs__":
            report.add(Diagnostic(
                "MXL-G105", f"provided input {name!r} is not consumed by "
                "any node reachable from the outputs",
                location=f"var:{name}",
                hint="remove the stale binding or check the name for typos"))

    # ---- uncalibrated quantized graph (MXL-G108): a quantize node whose
    # min/max inputs are COMPUTED nodes (runtime min/max over the batch)
    # rather than constant range variables was quantized without a
    # calibration table — legal, but slower and less accurate than the
    # calibrated flow, and usually an oversight in a shipped model
    uncal = [n for n in nodes
             if not n.is_var and n.op == "_contrib_quantize"
             and len(n.inputs) >= 3
             and any(not src.is_var for (src, _i) in n.inputs[1:3])]
    if uncal:
        shown = ", ".join(n.name for n in uncal[:3]) \
            + ("…" if len(uncal) > 3 else "")
        report.add(Diagnostic(
            "MXL-G108",
            f"{len(uncal)} quantize node(s) run with runtime (uncalibrated)"
            f" activation ranges: {shown}",
            location="graph",
            hint="collect a CalibTable (quant.collect or tools/mxquant.py "
                 "calibrate) and re-quantize from it — calibrated ranges "
                 "drop the per-step min/max reductions and clip outliers "
                 "(docs/quantization.md, 'Calibration')"))

    # ---- layout propagation missed (MXL-G107): a capture-context check —
    # only when the caller DECLARED its pipeline (passes_applied is not
    # None) and that pipeline lacks the layout pass
    if passes_applied is not None and "layout" not in tuple(passes_applied):
        # the SAME predicate the layout pass uses for eligibility, so the
        # rule can never warn about convs the pass wouldn't convert
        from ..passes.layout import is_nchw_conv
        nchw = [n for n in nodes if not n.is_var and is_nchw_conv(n)]
        if nchw:
            shown = ", ".join(n.name for n in nchw[:3]) \
                + ("…" if len(nchw) > 3 else "")
            report.add(Diagnostic(
                "MXL-G107",
                f"{len(nchw)} NCHW conv(s) captured with the layout pass "
                f"disabled: {shown}",
                location="graph",
                hint="drop passes=False (or add 'layout' to MXNET_PASSES) "
                     "so the automatic NCHW→NHWC propagation converts "
                     "them, or build the net with layout='NHWC'"))
    return report


def lint_symbol_json(json_str: str, shapes=None, dtypes=None,
                     suppress: Sequence[str] = (),
                     subject: str = "") -> Report:
    """Lint a *serialized* graph (``Symbol.tojson`` / ``.save`` output).
    Runs the reachability check JSON makes possible — nodes present in the
    file but unreachable from any head (MXL-G106) — then the full
    :func:`lint_symbol` walk over the loaded graph."""
    from ..symbol import load_json

    data = json.loads(json_str)
    sym = load_json(json_str)
    report = lint_symbol(sym, shapes=shapes, dtypes=dtypes,
                         suppress=suppress,
                         subject=subject or "saved graph")
    report.front_end = "graph"
    if isinstance(data, dict) and "nodes" in data and "heads" in data:
        reach = set()
        stack = [h[0] for h in data["heads"]]
        while stack:
            i = stack.pop()
            if i in reach:
                continue
            reach.add(i)
            stack.extend(src for (src, _i, _v)
                         in data["nodes"][i].get("inputs", []))
        dead = [jn["name"] for i, jn in enumerate(data["nodes"])
                if i not in reach]
        if dead:
            shown = ", ".join(dead[:5]) + ("…" if len(dead) > 5 else "")
            report.add(Diagnostic(
                "MXL-G106", f"{len(dead)} node(s) unreachable from any "
                f"head: {shown}", location="graph json",
                hint="re-export the symbol from its live outputs "
                     "(Symbol.tojson only serializes reachable nodes)"))
    return report
