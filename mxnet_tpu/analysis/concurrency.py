"""Concurrency lint — static AST analysis of the threaded host spine.

The reference framework's C++ dependency engine makes concurrency safe by
construction: every conflicting operation is serialized by the engine, so
user code never holds a lock. Our host spine (serving, resilience, io,
observability) is raw Python ``threading``, and lock misuse has been the
repo's single recurring bug class. This front end models
``threading.Lock/RLock/Condition`` attributes per class, builds an
inter-method lock-acquisition graph, and reports the MXL-C300 rule family
through the shared diagnostics core (inline ``# mxlint: disable=``, JSON,
``assert_clean``).

What the model can see (and its honest limits):

* Lock identity is ``Class.attr`` (or ``module:NAME``) — two *instances*
  of one class share an identity, so instance-vs-instance ordering between
  same-class locks is out of scope (the runtime twin
  :mod:`~mxnet_tpu.analysis.lockwatch` tracks real instances).
* Cross-object resolution rides type annotations (``st: _ModelState``)
  and ``self.attr = ScannedClass(...)`` constructor assignments; anything
  else is opaque.
* Call-graph expansion is depth-limited and lexical — callbacks, dynamic
  dispatch and inheritance are invisible.

Runtime twin: ``MXNET_LOCKCHECK=1`` (:mod:`.lockwatch`). CLI:
``tools/mxrace.py``. Rule catalog: docs/static_analysis.md.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .diagnostics import (Diagnostic, Report, Severity, register_rule,
                          parse_disable_comment)

__all__ = ["lint_concurrency"]

# --------------------------------------------------------------------------
# rule catalog (docs/static_analysis.md mirrors this table; the drift test
# in tests/test_mxlint.py cross-checks ids/severities/titles)
# --------------------------------------------------------------------------
register_rule(
    "MXL-C300", Severity.ERROR, "lock-order-inversion",
    "Two locks are acquired in opposite orders on different code paths "
    "(a cycle in the inter-method lock-acquisition graph). Two threads "
    "taking the paths concurrently deadlock.")
register_rule(
    "MXL-C301", Severity.WARNING, "blocking-under-lock",
    "An untimed blocking call (queue get/put, Thread.join, sleep, "
    "socket/HTTP, device sync such as block_until_ready/np.asarray) runs "
    "while a lock is held — every other thread needing that lock stalls "
    "for the full blocking duration.")
register_rule(
    "MXL-C302", Severity.WARNING, "wait-without-while",
    "Condition.wait() can return spuriously and after stolen wakeups; "
    "waiting anywhere but a while-predicate loop acts on a guess.")
register_rule(
    "MXL-C303", Severity.ERROR, "reentrant-acquire",
    "A call path re-enters a method that re-acquires a plain Lock the "
    "caller already holds — self-deadlock (the PR-12 shape: queue.close() "
    "called back under the queue's own lock).")
register_rule(
    "MXL-C304", Severity.WARNING, "guard-inconsistent-state",
    "An attribute is written under a lock in one method but read or "
    "written lock-free in another — the lock guards nothing; readers see "
    "torn or stale state.")
register_rule(
    "MXL-C305", Severity.WARNING, "unjoined-thread",
    "A Thread is started but its owning scope has no join() and no stop "
    "Event it ever sets — the thread leaks past shutdown and races "
    "teardown.")
register_rule(
    "MXL-C306", Severity.WARNING, "acquire-without-finally",
    "lock.acquire() with no release() in a finally block — any exception "
    "between acquire and release leaves the lock held forever.")

_MAX_DEPTH = 5          # call-graph expansion depth bound

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
               "make_lock": "lock", "make_rlock": "rlock"}
_BLOCKING_HTTP_MODULES = {"requests", "urllib", "urllib2", "httpx",
                          "http", "socket"}
_SOCKET_ATTRS = {"recv", "recvfrom", "accept", "connect", "sendall"}
_DEVICE_SYNC_ATTRS = {"block_until_ready", "asnumpy", "wait_to_read",
                      "device_get"}
_NP_MODULES = {"np", "numpy"}


# --------------------------------------------------------------------------
# per-function facts
# --------------------------------------------------------------------------
class _Ev:
    """One ordered event inside a function body, with the lock multiset
    lexically held at its site (``with`` statements seen so far)."""
    __slots__ = ("kind", "line", "held", "data")

    def __init__(self, kind: str, line: int, held: Tuple[str, ...], data):
        self.kind = kind        # acquire | blocking | call | wait
        self.line = line
        self.held = held
        self.data = data


class _Func:
    def __init__(self, file: str, cls: str, name: str, def_line: int):
        self.file = file
        self.cls = cls                  # "" for module-level functions
        self.name = name
        self.def_line = def_line
        self.events: List[_Ev] = []
        # attr -> list of (line, frozenset(held), is_write, method)
        self.accesses: List[Tuple[str, int, frozenset, bool]] = []
        self.manual_acquires: List[Tuple[str, int]] = []
        self.finally_released: Set[str] = set()

    @property
    def qualname(self) -> str:
        stem = os.path.splitext(os.path.basename(self.file))[0]
        return ".".join(p for p in (stem, self.cls, self.name) if p)


class _Class:
    def __init__(self, name: str, file: str, line: int):
        self.name = name
        self.file = file
        self.line = line
        # attr -> (kind, alias_of_lid or "")
        self.locks: Dict[str, Tuple[str, str]] = {}
        self.attr_types: Dict[str, str] = {}    # attr -> raw ctor class name
        self.infra_attrs: Set[str] = set()      # locks/events/threads/queues
        self.method_names: Set[str] = set()     # known before bodies scan
        self.methods: Dict[str, _Func] = {}
        self.thread_starts: List[Tuple[int, str]] = []  # (line, method)
        self.has_join = False
        self.event_set = False                  # a stop Event gets .set()


class _Model:
    """Everything the scan learned, across all files."""

    def __init__(self):
        self.classes: Dict[str, _Class] = {}
        self.mod_locks: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.mod_funcs: Dict[str, Optional[Tuple]] = {}  # name -> fkey|None
        self.funcs: Dict[Tuple, _Func] = {}              # fkey -> _Func
        self.lock_kinds: Dict[str, str] = {}             # lid -> kind
        self.lines: Dict[str, List[str]] = {}
        # module-scope thread hygiene (C305)
        self.mod_thread_starts: Dict[str, List[int]] = {}
        self.mod_has_join: Dict[str, bool] = {}
        self.mod_event_set: Dict[str, bool] = {}


def _lock_ctor_kind(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return _LOCK_CTORS.get(f.id)
    if isinstance(f, ast.Attribute):
        return _LOCK_CTORS.get(f.attr)
    return None


def _ctor_parts(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(ctor name, explicit module prefix or None) for ``Foo()`` /
    ``mod.Foo()`` call expressions."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id, None
    if isinstance(f, ast.Attribute):
        mod = f.value.id if isinstance(f.value, ast.Name) else None
        return f.attr, mod
    return None, None


_INFRA_MODULES = {"threading", "queue", "multiprocessing"}


def _is_ctor(call: ast.Call, *names: str) -> bool:
    got, _ = _ctor_parts(call)
    return got in names


def _is_scanned_ctor(call: ast.Call, classes) -> Optional[str]:
    """The scanned class a constructor call builds — unless the call is
    explicitly qualified into threading/queue (``threading.Event()`` must
    not resolve to a repo class that happens to be named Event)."""
    got, mod = _ctor_parts(call)
    if mod in _INFRA_MODULES:
        return None
    return got if got in classes else None


def _ann_class(ann, classes: Dict[str, _Class]) -> Optional[str]:
    """Pick the scanned class a parameter annotation refers to, if any."""
    if ann is None:
        return None
    names: List[str] = []
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            names.extend(re.findall(r"\w+", node.value))
    for n in names:
        if n in classes:
            return n
    return None


# --------------------------------------------------------------------------
# pass A — collect classes, lock attrs, attr types
# --------------------------------------------------------------------------
def _collect(model: _Model, file: str, tree: ast.Module) -> None:
    mod = os.path.splitext(os.path.basename(file))[0]
    model.mod_locks.setdefault(mod, {})
    model.mod_thread_starts.setdefault(mod, [])
    model.mod_has_join.setdefault(mod, False)
    model.mod_event_set.setdefault(mod, False)
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            kind = _lock_ctor_kind(node.value)
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lid = f"{mod}:{t.id}"
                        model.mod_locks[mod][t.id] = (kind, "")
                        model.lock_kinds[lid] = kind
        if isinstance(node, ast.ClassDef) and node.name not in model.classes:
            cm = _Class(node.name, file, node.lineno)
            cm.method_names = {s.name for s in node.body if isinstance(
                s, (ast.FunctionDef, ast.AsyncFunctionDef))}
            model.classes[node.name] = cm
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # pre-register module functions so calls resolve regardless of
            # file scan order; ambiguous names resolve to nothing
            fkey = (file, "", node.name)
            if node.name in model.mod_funcs and \
                    model.mod_funcs[node.name] != fkey:
                model.mod_funcs[node.name] = None
            else:
                model.mod_funcs[node.name] = fkey


def _collect_class_attrs(model: _Model, file: str, tree: ast.Module) -> None:
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        cm = model.classes.get(node.name)
        if cm is None or cm.file != file:
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call):
                continue
            for t in stmt.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                kind = _lock_ctor_kind(stmt.value)
                if kind:
                    alias = ""
                    if kind == "condition" and stmt.value.args:
                        a0 = stmt.value.args[0]
                        if isinstance(a0, ast.Attribute) and \
                                isinstance(a0.value, ast.Name) and \
                                a0.value.id == "self":
                            alias = f"{node.name}.{a0.attr}"
                    cm.locks[t.attr] = (kind, alias)
                    cm.infra_attrs.add(t.attr)
                    lid = alias or f"{node.name}.{t.attr}"
                    if not alias:
                        model.lock_kinds[lid] = kind
                elif _is_ctor(stmt.value, "Thread", "Event", "Queue",
                              "SimpleQueue", "LifoQueue", "Semaphore",
                              "BoundedSemaphore", "Barrier", "local"):
                    cm.infra_attrs.add(t.attr)
                else:
                    ctor = _is_scanned_ctor(stmt.value, model.classes)
                    if ctor:
                        cm.attr_types[t.attr] = ctor


# --------------------------------------------------------------------------
# pass B — scan every function body into ordered events
# --------------------------------------------------------------------------
class _FuncScan:
    def __init__(self, model: _Model, file: str, mod: str, cls: str,
                 fnode, func: _Func):
        self.m = model
        self.file = file
        self.mod = mod
        self.cls = cls
        self.f = func
        self.held: List[str] = []
        self.while_depth = 0
        self.local_types: Dict[str, str] = {}
        self.thread_names: Set[str] = set()
        self.event_names: Set[str] = set()
        if cls:
            self.local_types["self"] = cls
        args = fnode.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = _ann_class(a.annotation, model.classes)
            if t:
                self.local_types[a.arg] = t

    # ------------------------------------------------------------ resolve
    def _type_of(self, node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base and base in self.m.classes:
                return self.m.classes[base].attr_types.get(node.attr)
        return None

    def _lock_of(self, node) -> Optional[str]:
        """Resolve an expression to a lock id, following Condition
        aliases to the underlying lock."""
        if isinstance(node, ast.Name):
            ent = self.m.mod_locks.get(self.mod, {}).get(node.id)
            if ent:
                return f"{self.mod}:{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base and base in self.m.classes:
                ent = self.m.classes[base].locks.get(node.attr)
                if ent:
                    kind, alias = ent
                    return alias or f"{base}.{node.attr}"
        return None

    def _cond_of(self, node) -> Optional[str]:
        """Lock id when the expression is a *Condition* attribute."""
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base and base in self.m.classes:
                ent = self.m.classes[base].locks.get(node.attr)
                if ent and ent[0] == "condition":
                    return ent[1] or f"{base}.{node.attr}"
        if isinstance(node, ast.Name):
            ent = self.m.mod_locks.get(self.mod, {}).get(node.id)
            if ent and ent[0] == "condition":
                return f"{self.mod}:{node.id}"
        return None

    # ------------------------------------------------------------- events
    def _ev(self, kind: str, line: int, data) -> None:
        self.f.events.append(_Ev(kind, line, tuple(self.held), data))

    def scan(self, body: Sequence[ast.stmt]) -> None:
        for s in body:
            self.stmt(s)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.With) or isinstance(s, ast.AsyncWith):
            pushed = 0
            for item in s.items:
                self.exprs(item.context_expr)
                lid = self._lock_of(item.context_expr)
                if lid is not None:
                    self._ev("acquire", item.context_expr.lineno, lid)
                    self.held.append(lid)
                    pushed += 1
            self.scan(s.body)
            for _ in range(pushed):
                self.held.pop()
        elif isinstance(s, ast.While):
            self.exprs(s.test)
            self.while_depth += 1
            self.scan(s.body)
            self.while_depth -= 1
            self.scan(s.orelse)
        elif isinstance(s, ast.For):
            self.exprs(s.iter)
            self.scan(s.body)
            self.scan(s.orelse)
        elif isinstance(s, (ast.If,)):
            self.exprs(s.test)
            self.scan(s.body)
            self.scan(s.orelse)
        elif isinstance(s, ast.Try):
            self.scan(s.body)
            for h in s.handlers:
                self.scan(h.body)
            self.scan(s.orelse)
            for fs in s.finalbody:
                self._note_finally_releases(fs)
            self.scan(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: scanned lexically — a closure defined under a
            # lock usually runs under it (take_batch's collector shape)
            self.scan(s.body)
        elif isinstance(s, ast.ClassDef):
            pass
        elif isinstance(s, ast.Assign):
            self._note_types(s)
            self.exprs(s.value)
            for t in s.targets:
                self.target(t)
        elif isinstance(s, ast.AugAssign):
            self.exprs(s.value)
            self.exprs(s.target)        # read
            self.target(s.target)       # + write
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.exprs(s.value)
                self.target(s.target)
        elif isinstance(s, (ast.Expr, ast.Return)):
            v = s.value
            if v is not None:
                self.exprs(v)
        elif isinstance(s, (ast.Assert, ast.Raise, ast.Delete)):
            for child in ast.iter_child_nodes(s):
                self.exprs(child)

    def _note_types(self, s: ast.Assign) -> None:
        if len(s.targets) != 1 or not isinstance(s.targets[0], ast.Name):
            return
        name = s.targets[0].id
        v = s.value
        if isinstance(v, ast.Call):
            ctor, cmod = _ctor_parts(v)
            scanned = _is_scanned_ctor(v, self.m.classes)
            if scanned:
                self.local_types[name] = scanned
            elif ctor == "Thread":
                self.thread_names.add(name)
            elif ctor == "Event":
                self.event_names.add(name)
        elif isinstance(v, (ast.Name, ast.Attribute)):
            t = self._type_of(v)
            if t:
                self.local_types[name] = t
            if isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self" \
                    and self.cls:
                cm = self.m.classes.get(self.cls)
                if cm and v.attr in cm.infra_attrs:
                    # `t = self._thread` — keep threadness for .join checks
                    self.thread_names.add(name)

    def target(self, t) -> None:
        """Record attribute *writes* (C304)."""
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self" and self.cls:
            self.f.accesses.append(
                (t.attr, t.lineno, frozenset(self.held), True))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.target(e)
        elif isinstance(t, ast.Subscript):
            self.exprs(t.value)     # d[k] = v reads (and mutates) d

    # ------------------------------------------------ expression traversal
    def exprs(self, node) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self.call(n)
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and n.value.id == "self" \
                    and isinstance(n.ctx, ast.Load) and self.cls:
                self.f.accesses.append(
                    (n.attr, n.lineno, frozenset(self.held), False))

    @staticmethod
    def _kw(call: ast.Call, *names: str) -> bool:
        return any(k.arg in names for k in call.keywords)

    def call(self, c: ast.Call) -> None:
        f = c.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        name = f.id if isinstance(f, ast.Name) else None

        # --- manual acquire / release on a known lock (C306 bookkeeping)
        if attr in ("acquire", "release"):
            lid = self._lock_of(f.value)
            if lid is not None:
                if attr == "acquire":
                    nonblocking = self._kw(c, "blocking") and any(
                        k.arg == "blocking"
                        and isinstance(k.value, ast.Constant)
                        and k.value.value is False for k in c.keywords)
                    if c.args and isinstance(c.args[0], ast.Constant) \
                            and c.args[0].value in (False, 0):
                        nonblocking = True
                    if not nonblocking:
                        self.f.manual_acquires.append((lid, c.lineno))
                        self._ev("acquire", c.lineno, lid)
                return

        # --- Condition.wait: C302 territory, never C301 (wait releases
        # the lock it rides)
        if attr == "wait":
            cond = self._cond_of(f.value)
            if cond is not None:
                self._ev("wait", c.lineno, (cond, self.while_depth > 0))
                return
            lid = self._lock_of(f.value)
            if lid is not None:     # Event-style wait on a lock? unlikely
                return
            if not c.args and not self._kw(c, "timeout"):
                self._ev("blocking", c.lineno, "untimed .wait()")
            return

        # --- thread lifecycle (C305 bookkeeping)
        if attr == "start":
            started = False
            v = f.value
            if isinstance(v, ast.Name) and v.id in self.thread_names:
                started = True
            elif isinstance(v, ast.Call) and _is_ctor(v, "Thread"):
                started = True
            elif isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self":
                cm = self.m.classes.get(self.cls)
                if cm and v.attr in cm.infra_attrs and \
                        v.attr in getattr(cm, "_thread_attrs", set()):
                    started = True
            if started:
                self._note_thread_start(c.lineno)
        if (attr and "join" in attr) or (name and "join" in name):
            self._note_join()
        if attr == "set" and isinstance(f.value, (ast.Name, ast.Attribute)):
            self._note_event_set(f.value)

        # --- blocking-call heuristics (C301)
        desc = self._blocking_desc(c, attr, name, f)
        if desc:
            self._ev("blocking", c.lineno, desc)
            return

        # --- resolvable calls feed the inter-method expansion
        if attr is not None:
            t = self._type_of(f.value)
            if t and t in self.m.classes and \
                    attr in self.m.classes[t].method_names:
                self._ev("call", c.lineno, ("class", t, attr))
                return
        if name is not None and self.m.mod_funcs.get(name) is not None:
            self._ev("call", c.lineno, ("func", name))

    def _blocking_desc(self, c: ast.Call, attr, name, f) -> Optional[str]:
        # sleep
        if name == "sleep" or (attr == "sleep" and isinstance(
                f.value, ast.Name) and f.value.id == "time"):
            return "time.sleep()"
        # untimed join: zero positional args, no timeout kwarg (str.join
        # and os.path.join always pass a positional argument)
        if attr == "join" and not c.args and not self._kw(c, "timeout"):
            return "untimed .join()"
        if attr == "get" and not c.args and not self._kw(c, "timeout"):
            if self._type_of(f.value) is None:
                return "untimed queue .get()"
        if attr == "put" and c.args and not self._kw(c, "timeout") \
                and self._type_of(f.value) is None:
            # only receivers that look like stdlib queues — .put on an
            # unknown dict-like would drown the signal
            rname = f.value.attr if isinstance(f.value, ast.Attribute) \
                else (f.value.id if isinstance(f.value, ast.Name) else "")
            if "q" in rname.lower() and not any(
                    k.arg == "block" and isinstance(k.value, ast.Constant)
                    and k.value.value is False for k in c.keywords):
                return "untimed queue .put()"
        # network
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in _BLOCKING_HTTP_MODULES:
            return f"{f.value.id}.{attr}() network call"
        if name == "urlopen" or attr == "urlopen":
            return "urlopen() network call"
        if attr in _SOCKET_ATTRS:
            return f"socket .{attr}()"
        # device syncs
        if attr in _DEVICE_SYNC_ATTRS or name in _DEVICE_SYNC_ATTRS:
            return f"device sync {attr or name}()"
        if attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in _NP_MODULES:
            return "np.asarray() host transfer"
        return None

    # hooks filled in by _scan_file (scope-level C305 state)
    def _note_thread_start(self, line: int) -> None:
        if self.cls:
            self.m.classes[self.cls].thread_starts.append((line, self.f.name))
        else:
            self.m.mod_thread_starts[self.mod].append(line)

    def _note_join(self) -> None:
        if self.cls:
            self.m.classes[self.cls].has_join = True
        else:
            self.m.mod_has_join[self.mod] = True

    def _note_event_set(self, recv) -> None:
        is_event = False
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and recv.value.id == "self":
            cm = self.m.classes.get(self.cls)
            if cm and recv.attr in getattr(cm, "_event_attrs", set()):
                is_event = True
        elif isinstance(recv, ast.Name) and recv.id in self.event_names:
            is_event = True     # local stop Event (generator/closure shape)
        if is_event:
            if self.cls:
                self.m.classes[self.cls].event_set = True
            else:
                self.m.mod_event_set[self.mod] = True

    def _note_finally_releases(self, s) -> None:
        for n in ast.walk(s):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "release":
                lid = self._lock_of(n.func.value)
                if lid is not None:
                    self.f.finally_released.add(lid)


def _scan_file(model: _Model, file: str, tree: ast.Module) -> None:
    mod = os.path.splitext(os.path.basename(file))[0]

    def scan_func(fnode, cls: str) -> None:
        func = _Func(file, cls, fnode.name, fnode.lineno)
        model.funcs[(file, cls, fnode.name)] = func
        if cls:
            model.classes[cls].methods[fnode.name] = func
        sc = _FuncScan(model, file, mod, cls, fnode, func)
        sc.scan(fnode.body)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_func(node, "")
        elif isinstance(node, ast.ClassDef):
            cm = model.classes.get(node.name)
            if cm is None or cm.file != file:
                continue
            # pre-compute thread/event attr sets for the scanner hooks
            thread_attrs, event_attrs = set(), set()
            for st in ast.walk(node):
                if isinstance(st, ast.Assign) and \
                        isinstance(st.value, ast.Call):
                    for t in st.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            if _is_ctor(st.value, "Thread"):
                                thread_attrs.add(t.attr)
                            elif _is_ctor(st.value, "Event"):
                                event_attrs.add(t.attr)
            cm._thread_attrs = thread_attrs
            cm._event_attrs = event_attrs
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan_func(sub, node.name)


# --------------------------------------------------------------------------
# pass C — call-graph expansion: C300 edges, C301, C303
# --------------------------------------------------------------------------
class _Expander:
    def __init__(self, model: _Model):
        self.m = model
        # (a, b) -> (file, line, path string)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.findings: Dict[Tuple, Tuple[Diagnostic, List[Tuple[str, int]]]]\
            = {}

    def _fkey(self, target) -> Optional[Tuple]:
        if target[0] == "class":
            _, cls, meth = target
            fn = self.m.classes[cls].methods.get(meth)
            return (fn.file, fn.cls, fn.name) if fn else None
        fkey = self.m.mod_funcs.get(target[1])
        return fkey

    def _add(self, rule: str, file: str, line: int, msg: str, hint: str,
             extra_lines: Sequence[Tuple[str, int]] = ()) -> None:
        key = (rule, file, line, msg)
        if key in self.findings:
            return
        d = Diagnostic(rule, msg, location=f"{file}:{line}", hint=hint)
        self.findings[key] = (d, [(file, line)] + list(extra_lines))

    def run(self) -> None:
        for fkey in list(self.m.funcs):
            self._expand(fkey, (), None, 0, [])
        self._cycles()

    def _expand(self, fkey, held: Tuple[str, ...], site, depth: int,
                stack: List) -> None:
        if fkey in stack or depth > _MAX_DEPTH:
            return
        f = self.m.funcs.get(fkey)
        if f is None:
            return
        path = "->".join(self.m.funcs[k].qualname for k in stack + [fkey])
        for ev in f.events:
            H = held + ev.held
            if ev.kind == "acquire":
                lock = ev.data
                where = site or (f.file, ev.line)
                if lock in H and self.m.lock_kinds.get(lock) != "rlock":
                    self._add(
                        "MXL-C303", where[0], where[1],
                        f"call path {path} re-acquires non-reentrant lock "
                        f"{lock} already held (self-deadlock)",
                        "make the inner method lock-free (callers hold the "
                        "lock) or split a _locked() variant; RLock only "
                        "hides the design smell")
                else:
                    seen: Set[str] = set()
                    for h in H:
                        if h != lock and h not in seen:
                            seen.add(h)
                            self.edges.setdefault(
                                (h, lock),
                                (where[0], where[1], path))
            elif ev.kind == "blocking":
                if H:
                    where = site or (f.file, ev.line)
                    locks = ", ".join(dict.fromkeys(H))
                    via = f" (via {path})" if site else ""
                    self._add(
                        "MXL-C301", where[0], where[1],
                        f"{ev.data} while holding {locks}{via}",
                        "move the blocking call outside the lock, or use a "
                        "timeout and re-check state after reacquiring")
            elif ev.kind == "wait":
                cond, in_while = ev.data
                if not in_while and site is None:
                    self._add(
                        "MXL-C302", f.file, ev.line,
                        f"Condition.wait on {cond} outside a while-predicate "
                        "loop (spurious wakeups act on a guess)",
                        "wrap the wait in `while not <predicate>:` and "
                        "re-test after every wakeup")
            elif ev.kind == "call":
                callee = self._fkey(ev.data)
                if callee is None:
                    continue
                if not H:
                    # the callee's own root pass covers the lock-free case
                    continue
                nsite = site or (f.file, ev.line)
                self._expand(callee, H, nsite, depth + 1, stack + [fkey])

    # ----------------------------------------------------------- C300 SCCs
    def _cycles(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in adj[v]:
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

        for v in adj:
            if v not in index:
                strongconnect(v)

        for comp in sccs:
            cs = set(comp)
            cyc_edges = [((a, b), info) for (a, b), info in
                         self.edges.items() if a in cs and b in cs]
            cyc_edges.sort(key=lambda e: (e[1][0], e[1][1]))
            parts = [f"{a} -> {b} at {fl}:{ln} ({p})"
                     for (a, b), (fl, ln, p) in cyc_edges]
            file, line = cyc_edges[0][1][0], cyc_edges[0][1][1]
            self._add(
                "MXL-C300", file, line,
                "lock-order inversion between {%s}: %s"
                % (", ".join(sorted(cs)), "; ".join(parts)),
                "pick one global order for these locks and acquire them "
                "in that order on every path (or collapse to one lock)",
                extra_lines=[(fl, ln) for _, (fl, ln, _) in cyc_edges])


# --------------------------------------------------------------------------
# pass D — per-scope rules: C304, C305, C306
# --------------------------------------------------------------------------
def _scope_rules(model: _Model, add) -> None:
    # C304 — guard-inconsistent attributes, one finding per (class, attr)
    for cls in model.classes.values():
        guarded: Dict[str, Tuple[str, int]] = {}     # attr -> write site
        guarded_meth: Dict[str, str] = {}
        for mname, fn in cls.methods.items():
            if mname == "__init__":
                continue
            for attr, line, held, is_write in fn.accesses:
                if is_write and held and attr not in cls.infra_attrs:
                    guarded.setdefault(attr, (fn.file, line))
                    guarded_meth.setdefault(attr, mname)
        for attr, (wfile, wline) in guarded.items():
            for mname, fn in cls.methods.items():
                if mname == "__init__" or mname == guarded_meth[attr]:
                    continue
                if mname.endswith("_locked"):
                    # repo convention: a *_locked helper is only ever
                    # called with the guard already held
                    continue
                hit = next(((fn.file, line) for a, line, held, _w
                            in fn.accesses if a == attr and not held), None)
                if hit:
                    add("MXL-C304", hit[0], hit[1],
                        f"{cls.name}.{attr} is written under a lock in "
                        f"{guarded_meth[attr]}() ({wfile}:{wline}) but "
                        f"accessed lock-free in {mname}()",
                        "take the same lock here, or document why this "
                        "access is race-free and suppress",
                        fn.def_line)
                    break       # one finding per attr is signal enough

    # C305 — threads without a stop/join path
    for cls in model.classes.values():
        if cls.thread_starts and not cls.has_join and not cls.event_set:
            line, meth = cls.thread_starts[0]
            add("MXL-C305", cls.file, line,
                f"{cls.name}.{meth}() starts a thread but the class has "
                "no join() call and never sets a stop Event",
                "add a close()/stop() that sets a stop Event and joins "
                "with a timeout")
    for mod, starts in model.mod_thread_starts.items():
        if starts and not model.mod_has_join.get(mod) \
                and not model.mod_event_set.get(mod):
            for fn in model.funcs.values():
                if fn.cls == "" and \
                        os.path.splitext(os.path.basename(fn.file))[0] == mod:
                    add("MXL-C305", fn.file, starts[0],
                        f"module {mod} starts a thread with no join() and "
                        "no stop Event set anywhere in the module",
                        "pair the start with a stop/join function")
                    break

    # C306 — manual acquire without a finally release
    for fn in model.funcs.values():
        for lid, line in fn.manual_acquires:
            if lid not in fn.finally_released:
                add("MXL-C306", fn.file, line,
                    f"manual {lid}.acquire() in {fn.qualname}() with no "
                    "release() in a finally block",
                    "use `with lock:` or wrap in try/finally",
                    fn.def_line)


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------
def _iter_py(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            out.append(p)
    return out


def lint_concurrency(paths, *, suppress: Sequence[str] = (),
                     subject: str = "") -> Report:
    """Static concurrency lint over ``paths`` (files or directories).

    Returns a :class:`Report` with MXL-C300..C306 findings. Inline
    ``# mxlint: disable=MXL-Cxxx`` comments on the flagged line (or the
    enclosing ``def``/any cycle-edge line for C300) suppress per-site;
    ``suppress=("MXL-C304",)`` suppresses per-run.

        lint_concurrency(["mxnet_tpu/"]).assert_clean("warning")
    """
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    files = _iter_py(paths)
    model = _Model()
    trees: List[Tuple[str, ast.Module]] = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        model.lines[f] = src.splitlines()
        trees.append((f, ast.parse(src, filename=f)))
    for f, t in trees:
        _collect(model, f, t)
    for f, t in trees:
        _collect_class_attrs(model, f, t)
    for f, t in trees:
        _scan_file(model, f, t)

    report = Report(subject=subject or ", ".join(os.fspath(p) for p in paths),
                    front_end="concurrency")
    report.set_suppressions(suppress)

    def disables_at(file: str, line: int) -> Tuple[str, ...]:
        lines = model.lines.get(file, ())
        if 1 <= line <= len(lines):
            return parse_disable_comment(lines[line - 1])
        return ()

    exp = _Expander(model)
    exp.run()

    pending: List[Tuple[Diagnostic, List[Tuple[str, int]]]] = \
        list(exp.findings.values())

    def add(rule, file, line, msg, hint, def_line=None):
        d = Diagnostic(rule, msg, location=f"{file}:{line}", hint=hint)
        sites = [(file, line)]
        if def_line is not None:
            sites.append((file, def_line))
        pending.append((d, sites))

    _scope_rules(model, add)

    pending.sort(key=lambda p: (p[0].location, p[0].rule_id))
    for diag, sites in pending:
        inline: List[str] = []
        for file, line in sites:
            inline.extend(disables_at(file, line))
        report.add(diag, inline_disables=tuple(inline))
    return report
