"""mxlint — static graph & trace analysis for TPU correctness/perf hazards.

Two front ends over one diagnostic core:

* :func:`lint_symbol` / :func:`lint_symbol_json` — walk a Symbol/CachedOp
  graph (shape+dtype abstract eval, registry cross-check) before it binds.
* :func:`lint_step` / :func:`lint_trainer` — abstract-eval a trainer step
  function the way jit will see it, plus source/closure inspection for the
  hazards a jaxpr can't show (host syncs, retrace triggers).

Findings are :class:`Diagnostic` records in a :class:`Report` (text / JSON /
``assert_clean`` for pytest). ``tools/mxlint.py`` is the CLI. Rule catalog:
``docs/static_analysis.md``.

    from mxnet_tpu import analysis
    analysis.lint_symbol(net_sym, shapes={"data": (64, 3, 224, 224)})
    analysis.lint_step(train_step, (params, batch)).assert_clean()
"""
from .diagnostics import Diagnostic, Report, RuleDef, RULES, Severity
from .graph_lint import lint_symbol, lint_symbol_json
from .trace_lint import (lint_step, lint_trainer, lint_data_iter,
                         lint_server)

__all__ = ["Diagnostic", "Report", "RuleDef", "RULES", "Severity",
           "lint_symbol", "lint_symbol_json", "lint_step", "lint_trainer",
           "lint_data_iter", "lint_server"]
