"""mxlint — static graph, trace & concurrency analysis for mxnet_tpu.

Three front ends over one diagnostic core:

* :func:`lint_symbol` / :func:`lint_symbol_json` — walk a Symbol/CachedOp
  graph (shape+dtype abstract eval, registry cross-check) before it binds.
* :func:`lint_step` / :func:`lint_trainer` — abstract-eval a trainer step
  function the way jit will see it, plus source/closure inspection for the
  hazards a jaxpr can't show (host syncs, retrace triggers).
* :func:`lint_concurrency` — AST analysis of the threaded host spine:
  lock-order inversions, blocking calls under locks, guard-inconsistent
  shared state (the MXL-C300 family). Runtime twin: :mod:`.lockwatch`
  (``MXNET_LOCKCHECK=1``).

Findings are :class:`Diagnostic` records in a :class:`Report` (text / JSON /
``assert_clean`` for pytest). ``tools/mxlint.py`` and ``tools/mxrace.py``
are the CLIs. Rule catalog: ``docs/static_analysis.md``.

    from mxnet_tpu import analysis
    analysis.lint_symbol(net_sym, shapes={"data": (64, 3, 224, 224)})
    analysis.lint_step(train_step, (params, batch)).assert_clean()
    analysis.lint_concurrency(["mxnet_tpu/"]).assert_clean("warning")

The graph/trace front ends import jax and are loaded lazily (PEP 562) so
that stdlib-only consumers — the concurrency linter, the lockwatch runtime
sanitizer, and every instrumented lock site — never pay for (or cycle
into) the heavy half of the package.
"""
from .diagnostics import Diagnostic, Report, RuleDef, Severity

__all__ = ["Diagnostic", "Report", "RuleDef", "RULES", "Severity",
           "lint_symbol", "lint_symbol_json", "lint_step", "lint_trainer",
           "lint_data_iter", "lint_server", "lint_concurrency", "lockwatch"]

# symbol -> submodule that defines it (imported on first attribute access)
_LAZY = {
    "lint_symbol": ".graph_lint",
    "lint_symbol_json": ".graph_lint",
    "lint_step": ".trace_lint",
    "lint_trainer": ".trace_lint",
    "lint_data_iter": ".trace_lint",
    "lint_server": ".trace_lint",
    "lint_concurrency": ".concurrency",
    "lockwatch": None,          # the submodule itself
}

# every front end that registers rules — RULES must reflect all of them
_FRONT_ENDS = (".graph_lint", ".trace_lint", ".concurrency")


def __getattr__(name):
    import importlib
    if name == "RULES":
        # the catalog is complete only once every front end has registered
        for mod in _FRONT_ENDS:
            importlib.import_module(mod, __name__)
        from .diagnostics import RULES
        return RULES
    if name in _LAZY:
        target = _LAZY[name]
        if target is None:
            return importlib.import_module("." + name, __name__)
        mod = importlib.import_module(target, __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
