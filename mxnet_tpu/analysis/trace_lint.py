"""Trace lint — static analysis over trainer step functions.

Where graph lint walks a Symbol DAG, trace lint inspects a *Python step
function* the way jit will see it: ``jax.make_jaxpr`` abstract-evaluates the
function (nothing executes on device), the AOT ``lower()`` surface exposes
donation, and the function's own source/closure are scanned for the hazards
that never show up in a jaxpr — host syncs and retrace triggers. This is the
layer the reference gets from NNVM's pass manager between graph and engine;
for a trace-and-compile stack it has to look at the trace instead.

Rules (catalog in docs/static_analysis.md):

* MXL-T200 trace-failure        (error)   step function fails abstract eval
* MXL-T201 host-sync-in-step    (error)   .item()/np.asarray()/device_get/
                                          wait_to_read in the step body
* MXL-T202 retrace-closure-scalar (warning) Python scalar captured by closure
* MXL-T203 weak-type-arg        (warning) Python-scalar / weak-typed sample
                                          arg (weak-type flip ⇒ retrace)
* MXL-T204 unhashable-static-arg (error)  static_argnums arg is an array /
                                          unhashable (retrace per value or
                                          TypeError)
* MXL-T205 missed-donation      (warning) input buffer matches an output but
                                          is not donated
* MXL-T206 replicated-constant  (warning) large constant baked into the
                                          trace (replicated per device under
                                          a sharded mesh)
* MXL-T207 float64-in-trace     (error)   f64 appears in args or is
                                          introduced by a primitive
* MXL-T208 unresumable-data-iter (warning) resilient run fed by an iterator
                                          without state()/set_state()
* MXL-T209 unscaled-lowprec-loss (warning) bf16/fp16 compute_dtype step
                                          with no loss-scale state (tiny
                                          grads underflow silently)
* MXL-T210 uninstrumented-hot-loop (warning) telemetry is enabled but the
                                          trainer's step-time attribution
                                          is switched off (perf blind spot)
* MXL-T211 untuned-hot-loop     (warning) trainer runs all-default perf
                                          levers while the tuner cache has
                                          a differing measured best config
                                          for the same model/device
* MXL-T213 inelastic-restore    (warning) ResilientTrainer whose newest
                                          checkpoint manifest records a
                                          different mesh topology, without
                                          elastic adoption enabled
* MXL-T212 replicated-optimizer-at-scale (warning) multi-device trainer on
                                          the default all-reduce path with
                                          fully replicated optimizer state
                                          while the tuner cache holds a
                                          measured reduce_scatter win for
                                          the same signature
* MXL-T214 unbounded-serving-queue (warning) a model server configured with
                                          no request-queue bound or no
                                          default deadline — overload
                                          becomes unbounded latency
                                          instead of typed rejections
* MXL-T215 fp32-serving-with-int8-win (warning) a model serving on the f32
                                          tier while the cost ledger holds
                                          a measured int8 win for the same
                                          model/device signature
* MXL-T216 untraced-serving-path (warning) a serving model with declared
                                          deadlines/SLOs but request
                                          tracing disabled (or sample
                                          rate 0) — a breach leaves no
                                          per-request timeline
* MXL-T218 unbudgeted-hbm-overcommit (warning) the server's summed
                                          ledger-estimated footprints
                                          exceed the per-chip HBM budget,
                                          or a multi-model server runs
                                          with footprint evidence on file
                                          but no budget configured — the
                                          memory-aware refusal paths are
                                          blind
* MXL-T219 no-retry-budget      (warning) a serving model enables retries
                                          and/or hedged requests with no
                                          retry budget — a correlated
                                          failure amplifies offered load
                                          onto the degraded backend
                                          (retry-storm)
* MXL-T220 ungated-rollout      (warning) a live model rollout ramps with
                                          its safety gates off: automatic
                                          rollback disabled, shadow
                                          agreement sampling off, or a
                                          canary with no SLO — a bad
                                          version reaches 100% of traffic
                                          with nothing to stop it
"""
from __future__ import annotations

import ast
import inspect
import json
import textwrap
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from .diagnostics import (Diagnostic, Report, parse_disable_comment,
                          register_rule)

__all__ = ["lint_step", "lint_trainer", "lint_data_iter", "lint_server"]

register_rule(
    "MXL-T200", "error", "trace-failure",
    "The step function fails jax abstract evaluation with the given sample "
    "arguments — jit of this function will raise the same way.")
register_rule(
    "MXL-T201", "error", "host-sync-in-step",
    "The step body forces a host↔device synchronization (.item(), "
    ".asnumpy(), np.asarray(...), jax.device_get(...), wait_to_read()): "
    "inside a hot loop this serializes the async dispatch pipeline; inside "
    "a jitted function it fails tracing outright.")
register_rule(
    "MXL-T202", "warning", "retrace-closure-scalar",
    "A Python scalar is captured by closure. jit bakes it in as a "
    "constant: changing it either retraces (re-jit per step) or is "
    "silently ignored (stale trace).")
register_rule(
    "MXL-T203", "warning", "weak-type-arg",
    "A sample argument is a Python scalar (weak-typed). Alternating weak "
    "and strong types for the same parameter triggers a retrace per flip.")
register_rule(
    "MXL-T204", "error", "unhashable-static-arg",
    "A static_argnums position receives an array or unhashable value — "
    "jit either raises TypeError or recompiles for every distinct value.")
register_rule(
    "MXL-T205", "warning", "missed-donation",
    "An input buffer has the same shape/dtype as an output (param/state "
    "threading) but is not donated — XLA must double-buffer it, costing "
    "HBM equal to the undonated bytes.")
register_rule(
    "MXL-T206", "warning", "replicated-constant",
    "A large constant is baked into the trace (closure-captured array). "
    "It is embedded in the executable and replicated on every device of a "
    "sharded mesh; pass it as an argument and shard it instead.")
register_rule(
    "MXL-T207", "error", "float64-in-trace",
    "float64 appears in the traced computation. TPUs emulate f64 at a "
    "severe slowdown (jax_enable_x64 is on package-wide, so np.float64 "
    "inputs silently stay f64).")
register_rule(
    "MXL-T208", "warning", "unresumable-data-iter",
    "A ResilientTrainer / resilient_fit run is fed by a data iterator "
    "without the checkpointable-iterator state protocol (state()/"
    "set_state()): a resume silently restarts the epoch from batch 0, "
    "re-training already-seen batches and skewing convergence.")
register_rule(
    "MXL-T209", "warning", "unscaled-lowprec-loss",
    "A bf16/fp16 compute_dtype step trains with no loss-scale state: the "
    "short low-precision mantissa underflows tiny gradients to zero "
    "(silently, unlike overflow — no NaN ever surfaces), stalling or "
    "skewing convergence late in training. Enable in-trace dynamic loss "
    "scaling (DataParallelTrainer(loss_scaling=True)) or contrib.amp's "
    "LossScaler.")
register_rule(
    "MXL-T210", "warning", "uninstrumented-hot-loop",
    "The trainer runs with telemetry enabled but step-time attribution "
    "disabled: the hot loop publishes no mxtpu_step_breakdown_ms / "
    "mxtpu_device_util / mxtpu_mfu gauges, so a slowdown cannot be "
    "attributed to device compute vs host dispatch vs data-feed stall — "
    "exactly the blind spot that kept perf flat across bench rounds.")
register_rule(
    "MXL-T212", "warning", "replicated-optimizer-at-scale",
    "A multi-device trainer runs the default all-reduce gradient path with "
    "fully replicated optimizer state although the autotuner cache holds a "
    "MEASURED reduce_scatter win for the same model/device/chip-count "
    "signature: every chip burns N x the optimizer-state HBM and the "
    "heavier collective, while the ZeRO-1 sharded optimizer "
    "(DataParallelTrainer(grad_reduce='reduce_scatter')) is one ctor "
    "kwarg away with a measurement already on file.")
register_rule(
    "MXL-T213", "warning", "inelastic-restore",
    "A ResilientTrainer whose checkpoint directory's newest manifest "
    "records a different mesh topology (n_devices/dp extent) than the "
    "live mesh, without elastic adoption enabled: the very first "
    "auto-resume will raise TopologyMismatch instead of training. "
    "Enable elastic data parallelism (ResilientTrainer(elastic=True), "
    "MXNET_ELASTIC=1, or resilience.ElasticTrainer) to adopt the "
    "checkpoint — ZeRO-1 optimizer state re-sharded N→M, global batch "
    "re-split, iterator state credited back.")
register_rule(
    "MXL-T214", "warning", "unbounded-serving-queue",
    "A serving model is configured with no request-queue bound (max_queue="
    "0) or no default per-request deadline (deadline_ms=0): under "
    "overload the server queues without limit and answers arbitrarily "
    "late instead of shedding load with typed Overloaded/DeadlineExceeded "
    "rejections — the exact collapse mode admission control exists to "
    "prevent. Set ModelConfig(max_queue=, deadline_ms=) or the "
    "MXNET_SERVE_MAX_QUEUE / MXNET_SERVE_DEADLINE_MS knobs.")
register_rule(
    "MXL-T215", "warning", "fp32-serving-with-int8-win",
    "A model serves on the f32 tier while the cost ledger holds a "
    "MEASURED int8 win for the same model/device signature (a "
    "label='quant' row where int8 beat f32): every request pays the f32 "
    "latency although the cheaper executable is one knob away "
    "(ModelConfig(tier='int8') or MXNET_SERVE_TIER=int8) — the same "
    "best_cached discipline as MXL-T211/T212: no row, different device, "
    "or an int8 tier already serving all stay silent.")
register_rule(
    "MXL-T216", "warning", "untraced-serving-path",
    "A serving model declares latency objectives (a per-request deadline "
    "and/or an SLO) but serves with request tracing disabled or a zero "
    "sample rate: when the deadline or SLO is breached there is no "
    "per-request span timeline to attribute the miss to queue wait vs "
    "batch assembly vs device time — the exact evidence the objectives "
    "exist to produce. Enable tracing (ModelConfig(trace=True) / "
    "MXNET_SERVE_TRACE=1) with a nonzero sample rate "
    "(MXNET_TRACE_SAMPLE); error/shed/expired and tail traces are "
    "always retained regardless of the rate.")
register_rule(
    "MXL-T217", "warning", "unisolated-multi-tenant-fleet",
    "Multiple models share one serving process with no tenant isolation "
    "declared: either no fleet controller is attached (no per-tenant "
    "quotas, fair-share weights or priority classes — one tenant's storm "
    "is every tenant's outage), or a fleet controller autoscales a "
    "tenant that declares no SLO (the burn-rate evaluator is blind to "
    "it: it can neither grow the tenant when it suffers nor trust it as "
    "a donor). Attach a FleetController with TenantPolicy(quota_qps=/"
    "priority=) per model, and give every autoscaled tenant a "
    "ModelConfig(slo_p99_ms=) objective.")
register_rule(
    "MXL-T218", "warning", "unbudgeted-hbm-overcommit",
    "The serving process overcommits (or cannot account) its HBM: either "
    "the sum of the models' ledger-estimated per-chip footprints "
    "(memwatch.model_footprint) exceeds the per-chip HBM budget — the "
    "next cold bucket bind or traffic spike OOMs the device although the "
    "overcommit was computable up front — or multiple models serve with "
    "memory-footprint evidence on file but NO budget configured "
    "(MXNET_HBM_BYTES unset on an unknown device), leaving every "
    "memory-aware refusal path (model-load budget check, fleet "
    "no_memory refusals, tuner predicted-OOM gate) blind. Set "
    "MXNET_HBM_BYTES (or serve on a device with a known capacity) and "
    "shed a model/shrink a ladder until the placement fits.")
register_rule(
    "MXL-T219", "warning", "no-retry-budget",
    "A serving model enables retries (retries>0) and/or hedged requests "
    "(hedge=True) but configures no retry budget (retry_budget=0): under "
    "a correlated failure (a sick chip, a flaky interconnect) every "
    "request retries and every hedge duplicates, multiplying offered "
    "load onto the already-degraded backend exactly when it can least "
    "absorb it — the classic retry-storm amplification. Cap duplicate "
    "work to a fraction of admitted traffic with "
    "ModelConfig(retry_budget=) or MXNET_SERVE_RETRY_BUDGET (the "
    "default 0.1 ≈ 10%; the budget is shared by retries and hedges and "
    "denials are counted, not silent).")
register_rule(
    "MXL-T220", "warning", "ungated-rollout",
    "A live model rollout ramps toward 100% of traffic with one or more "
    "of its safety gates off: automatic rollback disabled "
    "(rollback=False — the gate evaluates but only logs; a failing "
    "canary keeps its traffic share), shadow agreement sampling off "
    "(shadow_sample=0 — a silently-wrong canary that meets its latency "
    "SLO ramps to 100% unchallenged), or a canary version that declares "
    "no SLO (slo_p99_ms=0 — the burn-rate gate is blind; only the "
    "coarser p99-vs-incumbent delta remains). The whole point of a "
    "staged rollout is that evidence can stop it; every disabled gate "
    "is a class of regression that ships. Keep MXNET_ROLLOUT_ROLLBACK "
    "and MXNET_ROLLOUT_SHADOW_SAMPLE on, and give the candidate config "
    "a slo_p99_ms objective.")
register_rule(
    "MXL-T211", "warning", "untuned-hot-loop",
    "The trainer runs with all-default perf levers while the autotuner "
    "cache holds a measured best config for the same model/device "
    "signature that differs from them: the run pays the default-config "
    "step time although a faster, already-measured configuration is one "
    "ctor kwarg away (tuner.best_cached / tools/mxtune.py).")

_HOST_SYNC_METHODS = ("item", "asscalar", "asnumpy", "wait_to_read")
_NP_NAMES = ("np", "numpy", "onp")


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / 2**20:.1f} MiB"
    if n >= 1 << 10:
        return f"{n / 2**10:.1f} KiB"
    return f"{n} B"


def _f64(aval) -> bool:
    try:
        return np.dtype(aval.dtype) in (np.dtype(np.float64),
                                        np.dtype(np.complex128))
    except TypeError:
        return False


def _iter_eqns(jaxpr):
    """All eqns, recursing into call/control-flow sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)
            if sub is not None:
                yield from _iter_eqns(sub)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    s = getattr(x, "jaxpr", None)
                    if s is not None:
                        yield from _iter_eqns(s)


def _source_info(fn):
    """(source_lines, first_lineno, filename) or None when source is
    unavailable (builtins, exec'd code, C extensions)."""
    try:
        lines, start = inspect.getsourcelines(fn)
        filename = inspect.getsourcefile(fn) or "<unknown>"
        return lines, start, filename
    except (OSError, TypeError):
        return None


class _HostSyncVisitor(ast.NodeVisitor):
    def __init__(self):
        self.hits = []   # (lineno, description)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_METHODS:
                self.hits.append((node.lineno, f".{f.attr}()"))
            elif f.attr in ("asarray", "array") and \
                    isinstance(f.value, ast.Name) and f.value.id in _NP_NAMES:
                self.hits.append((node.lineno, f"{f.value.id}.{f.attr}(...)"))
            elif f.attr == "device_get":
                self.hits.append((node.lineno, "device_get(...)"))
        elif isinstance(f, ast.Name) and f.id == "device_get":
            self.hits.append((node.lineno, "device_get(...)"))
        self.generic_visit(node)


def _def_line(lines):
    """Index of the actual ``def``/``async def`` line — decorated functions'
    source starts at the first decorator, and the suppression contract puts
    the disable comment on the def line, not the decorator."""
    for i, l in enumerate(lines):
        if l.lstrip().startswith(("def ", "async def ")):
            return i
    return 0


def _scan_source(inner, report: Report) -> Tuple[str, int, str]:
    """AST host-sync scan + returns (filename, def_lineno, def_line_text)
    for locating whole-function findings."""
    si = _source_info(inner)
    if si is None:
        return "<unknown>", 0, ""
    lines, start, filename = si
    d = _def_line(lines)
    src = textwrap.dedent("".join(lines))
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return filename, start + d, lines[d] if lines else ""
    v = _HostSyncVisitor()
    v.visit(tree)
    for rel_line, desc in v.hits:
        abs_line = start + rel_line - 1
        text = lines[rel_line - 1] if rel_line - 1 < len(lines) else ""
        report.add(Diagnostic(
            "MXL-T201", f"host sync {desc} inside the step function",
            location=f"{filename}:{abs_line}",
            hint="move host readbacks out of the step; for logging, read "
                 "asynchronously every N steps (the value is a future)"),
            inline_disables=parse_disable_comment(text))
    return filename, start + d, lines[d] if lines else ""


def lint_step(fn, args: Sequence[Any] = (), kwargs: Optional[Dict] = None,
              *, donate_argnums: Optional[Sequence[int]] = None,
              static_argnums: Sequence[int] = (),
              const_bytes_threshold: int = 1 << 20,
              donate_bytes_threshold: int = 1024,
              suppress: Sequence[str] = (),
              subject: str = "") -> Report:
    """Trace-lint a step function against sample arguments.

    ``fn`` may be a plain function or a ``jax.jit``-wrapped one; for jitted
    functions donation is read off the AOT lowering, otherwise pass the
    intended ``donate_argnums``. Sample args are abstract-evaluated only —
    nothing runs on device, so full-size production shapes are cheap.
    """
    kwargs = dict(kwargs or {})
    inner = inspect.unwrap(fn)
    jitted = fn is not inner or type(fn).__name__ in (
        "PjitFunction", "CompiledFunction", "Wrapped")
    name = getattr(inner, "__qualname__", getattr(inner, "__name__", "step"))
    report = Report(subject or f"step {name!r}", "trace")
    report.set_suppressions(suppress)

    filename, def_line, def_text = _scan_source(inner, report)
    fn_loc = f"{filename}:{def_line}"
    def_disables = parse_disable_comment(def_text)

    # ---- closure-captured Python scalars (MXL-T202). Module-global
    # scalars bake in identically but are far more often deliberate
    # constants, so they report at info severity instead of warning.
    try:
        cv = inspect.getclosurevars(inner)
        scalar_cells = {k: v for k, v in cv.nonlocals.items()
                        if isinstance(v, (bool, int, float))}
        scalar_globals = {k: v for k, v in cv.globals.items()
                          if isinstance(v, (bool, int, float))}
    except (TypeError, ValueError):
        scalar_cells, scalar_globals = {}, {}
    for k, v in sorted(scalar_cells.items()):
        report.add(Diagnostic(
            "MXL-T202", f"closure captures Python scalar {k}={v!r}; jit "
            "bakes it into the compiled program",
            location=fn_loc,
            hint="pass it as a traced argument (or static_argnums if it "
                 "selects code paths), or wrap in jnp.asarray"),
            inline_disables=def_disables)
    for k, v in sorted(scalar_globals.items()):
        report.add(Diagnostic(
            "MXL-T202", f"module-global Python scalar {k}={v!r} is baked "
            "into the compiled program; rebinding the global after jit is "
            "silently ignored", location=fn_loc, severity="info",
            hint="fine for a true constant; pass as an argument if it is "
                 "ever meant to change"),
            inline_disables=def_disables)

    # ---- static-argument hygiene (MXL-T204)
    static_argnums = tuple(static_argnums or ())
    for i in static_argnums:
        if i >= len(args):
            continue
        a = args[i]
        bad = isinstance(a, (np.ndarray, jax.Array))
        if not bad:
            try:
                hash(a)
            except TypeError:
                bad = True
        if bad:
            report.add(Diagnostic(
                "MXL-T204", f"static arg {i} is "
                f"{type(a).__name__} — unhashable/array-valued static "
                "args retrace per value (or raise TypeError)",
                location=fn_loc,
                hint="make it a traced argument, or reduce it to a "
                     "hashable config (shape tuple, enum)"),
                inline_disables=def_disables)

    # ---- abstract eval. Jitted fns go through their own .trace(), which
    # honors the jit's static_argnums/donate_argnums and treats kwargs as
    # real inputs; raw fns are traced with user-supplied static args fixed
    # and kwargs as a traced input tree (NOT closed over — a closed-over
    # batch would masquerade as a baked constant).
    dyn_idx = [i for i in range(len(args)) if i not in static_argnums]
    dyn_args = [args[i] for i in dyn_idx]
    donated_flags = None
    try:
        if jitted and hasattr(fn, "trace"):
            traced = fn.trace(*args, **kwargs)
            closed = traced.jaxpr
            donated_flags = [bool(a.donated) for a in
                             jax.tree_util.tree_leaves(traced.args_info)]
        else:
            fixed = {i: args[i] for i in static_argnums if i < len(args)}

            def traceable(dyn, kw):
                full = list(fixed.items()) + list(zip(dyn_idx, dyn))
                return inner(*(v for _, v in sorted(full)), **kw)

            closed = jax.make_jaxpr(traceable)(tuple(dyn_args), kwargs)
    except Exception as e:
        hint = "jit of this step will fail identically; fix the trace " \
               "error first — remaining trace rules were skipped"
        disables = def_disables
        concretization = "Tracer" in type(e).__name__ \
            or "Concretization" in type(e).__name__
        if concretization and report.by_rule("MXL-T201"):
            hint = "likely caused by the host sync(s) flagged above " \
                   "(MXL-T201): a traced array cannot be read back on host"
        elif concretization and any(d.rule_id == "MXL-T201"
                                    for d in report.suppressed):
            # every host sync was explicitly acknowledged with a disable
            # comment — the consequent trace failure is the same root
            # cause, so it rides along as suppressed (eager-only steps)
            disables = ("all",)
        msg = str(e).split("\n")[0]
        report.add(Diagnostic(
            "MXL-T200", f"abstract evaluation failed: "
            f"{type(e).__name__}: {msg}", location=fn_loc, hint=hint),
            inline_disables=disables)
        return report

    # the trace succeeded, so no flagged host sync ran on a *traced* value
    # (that would have raised above) — each is a trace-time constant or a
    # per-call sync only on the eager path; hazard stands, but not provably
    # per-step, so the finding rides as warning instead of error
    for d in report.by_rule("MXL-T201"):
        d.severity = "warning"
        d.hint += " (trace succeeded: this sync is not on a traced value — "\
                  "likely a baked constant; still per-call if run eagerly)"

    in_avals = [v.aval for v in closed.jaxpr.invars]

    # ---- weak types (MXL-T203): read off the traced avals, so statically
    # consumed Python scalars (a jit's own static_argnums) never
    # false-positive — only values that actually trace weak are flagged
    weak = [i for i, av in enumerate(in_avals)
            if getattr(av, "weak_type", False)]
    if weak:
        report.add(Diagnostic(
            "MXL-T203", f"{len(weak)} input leaf/leaves trace weak-typed "
            f"(flat arg indices {weak[:8]}) — Python scalars; alternating "
            "weak/strong types for the same parameter retraces per flip",
            location=fn_loc,
            hint="pass jnp.asarray(x, dtype) so the committed dtype is "
                 "stable across steps"),
            inline_disables=def_disables)

    # ---- float64 (MXL-T207): args first, then introducing primitives
    f64_args = [i for i, av in enumerate(in_avals) if _f64(av)]
    if f64_args:
        report.add(Diagnostic(
            "MXL-T207", f"{len(f64_args)} input leaf/leaves are float64 "
            f"(flat arg indices {f64_args[:8]})", location=fn_loc,
            hint="cast inputs to float32 before the step; np arrays "
                 "default to f64 under jax_enable_x64"),
            inline_disables=def_disables)
    introducers = []
    for eqn in _iter_eqns(closed.jaxpr):
        outs_f64 = any(_f64(v.aval) for v in eqn.outvars)
        ins_f64 = [_f64(v.aval) for v in eqn.invars
                   if hasattr(v, "aval")]
        if outs_f64 and not (ins_f64 and all(ins_f64)):
            introducers.append(str(eqn.primitive))
    if introducers:
        shown = ", ".join(sorted(set(introducers))[:5])
        report.add(Diagnostic(
            "MXL-T207", f"{len(introducers)} primitive(s) introduce "
            f"float64 into the trace ({shown})", location=fn_loc,
            hint="look for np.float64 scalars, python floats in "
                 "jnp.array(..., dtype=None), or explicit astype('float64')"),
            inline_disables=def_disables)

    # ---- large baked constants (MXL-T206)
    for c in closed.consts:
        nbytes = getattr(c, "nbytes", 0)
        if nbytes >= const_bytes_threshold:
            report.add(Diagnostic(
                "MXL-T206", f"constant of shape "
                f"{tuple(getattr(c, 'shape', ()))} "
                f"{getattr(c, 'dtype', '?')} ({_fmt_bytes(nbytes)}) is "
                "baked into the trace and replicated per device",
                location=fn_loc,
                hint="pass it as an argument (sharded/replicated "
                     "explicitly) instead of closing over it"),
                inline_disables=def_disables)

    # ---- donation (MXL-T205): per-buffer. Donated inputs consume their
    # matching output slots first (they genuinely alias); any leftover
    # non-donated input matching a remaining output is a missed donation —
    # partial donation (opt_state donated, params forgotten) still fires.
    if donated_flags is None:
        donate_set = set(donate_argnums or ())
        flags_tree = (tuple(jax.tree_util.tree_map(
                          lambda _, _i=i: _i in donate_set, args[i])
                          for i in dyn_idx),
                      jax.tree_util.tree_map(lambda _: False, kwargs))
        donated_flags = jax.tree_util.tree_leaves(flags_tree)
    if len(donated_flags) != len(in_avals):
        # structure drifted (exotic pytree); fail open rather than misreport
        donated_flags = [True] * len(in_avals)
    out_pool: Dict[Tuple, int] = {}
    for v in closed.jaxpr.outvars:
        if hasattr(v, "aval"):
            k = (tuple(v.aval.shape), str(v.aval.dtype))
            out_pool[k] = out_pool.get(k, 0) + 1

    def _nbytes(av):
        n = int(np.prod(av.shape, dtype=np.int64)) if av.shape else 1
        return n * np.dtype(av.dtype).itemsize

    for av, donated in zip(in_avals, donated_flags):
        if donated:
            k = (tuple(av.shape), str(av.dtype))
            if out_pool.get(k, 0) > 0:
                out_pool[k] -= 1
    cand_bytes = 0
    cand_leaves = 0
    for av, donated in zip(in_avals, donated_flags):
        k = (tuple(av.shape), str(av.dtype))
        if not donated and out_pool.get(k, 0) > 0 \
                and _nbytes(av) >= donate_bytes_threshold:
            out_pool[k] -= 1
            cand_bytes += _nbytes(av)
            cand_leaves += 1
    if cand_leaves:
        report.add(Diagnostic(
            "MXL-T205", f"{cand_leaves} input buffer(s) totalling "
            f"{_fmt_bytes(cand_bytes)} match output shapes/dtypes "
            "but are not donated",
            location=fn_loc,
            hint="jit(fn, donate_argnums=...) on the params/optimizer-"
                 "state arguments halves their HBM footprint"),
            inline_disables=def_disables)
    return report


def lint_data_iter(data_iter, *, suppress: Sequence[str] = (),
                   subject: str = "") -> Report:
    """Lint a data iterator for resilience-readiness (MXL-T208).

    A resilient training loop (``ResilientTrainer.attach_data``,
    ``resilient_fit``) can only resume **exactly mid-epoch** when its
    iterator implements the checkpointable-iterator state protocol —
    ``state() -> dict`` / ``set_state(dict)`` covering epoch, cursor and
    shuffle-RNG seed (``mxnet_tpu.io.has_state``). This check also
    *exercises* ``state()``: composite iterators (``PrefetchingIter``,
    ``DeviceFeedIter``, ``ResilientDataIter``) expose the protocol but
    raise when a wrapped base cannot deliver it, which is the same silent
    epoch restart one layer down."""
    from ..io.io import has_state
    name = type(data_iter).__name__
    report = Report(subject or f"data iterator {name}", "trace")
    report.set_suppressions(suppress)
    hint = ("use a built-in iterator (NDArrayIter, ImageRecordIter, "
            "DeviceFeedIter, ...) or implement state()/set_state() "
            "(epoch, cursor, shuffle-RNG seed) — see docs/resilience.md")
    if not has_state(data_iter):
        report.add(Diagnostic(
            "MXL-T208",
            f"{name} has no state()/set_state(): a ResilientTrainer/"
            "resilient_fit resume restarts its epoch from batch 0 "
            "(duplicated batches, skewed convergence)",
            location=name, hint=hint))
        return report
    try:
        data_iter.state()
    except Exception as e:
        report.add(Diagnostic(
            "MXL-T208",
            f"{name}.state() raises {type(e).__name__} ({e}) — the "
            "protocol is advertised but cannot capture a resume point, so "
            "resume still restarts the epoch",
            location=name, hint=hint))
    return report


def lint_server(server_or_config, *, suppress: Sequence[str] = (),
                subject: str = "") -> Report:
    """Lint a serving configuration for overload-safety, observability,
    tenant isolation, memory budgeting, retry hygiene and rollout
    gating (MXL-T214 / MXL-T215 / MXL-T216 / MXL-T217 / MXL-T218 /
    MXL-T219 / MXL-T220).

    Accepts a :class:`~mxnet_tpu.serving.server.ModelServer` (every model
    is checked), a :class:`~mxnet_tpu.serving.fleet.FleetController`
    (its server is checked, with the fleet's policies in view), or a
    single :class:`~mxnet_tpu.serving.server.ModelConfig`. A pure config
    check — nothing is started or dispatched. Fires once per hazard per
    model:

    - ``max_queue`` unset/0 → unbounded queue: overload becomes unbounded
      memory + latency instead of a typed ``Overloaded``;
    - ``deadline_ms`` unset/0 → no default deadline: a request no client
      is waiting for anymore still occupies the chip.
    """
    configs = []
    if hasattr(server_or_config, "policy") \
            and hasattr(server_or_config, "server"):
        # a FleetController: lint its server with the policies in view
        server_or_config = server_or_config.server
    if hasattr(server_or_config, "models") \
            and hasattr(server_or_config, "config"):
        configs = [server_or_config.config(m)
                   for m in server_or_config.models()]
        name = type(server_or_config).__name__
        fleet = getattr(server_or_config, "_fleet", None)
    elif hasattr(server_or_config, "max_queue"):
        configs = [server_or_config]
        name = "ModelConfig"
        fleet = None
    else:
        raise TypeError("lint_server expects a ModelServer, "
                        "FleetController or ModelConfig, got %r"
                        % type(server_or_config).__name__)
    report = Report(subject or f"serving config ({name})", "trace")
    report.set_suppressions(suppress)
    # ---- unisolated multi-tenant fleet (MXL-T217), server-level half:
    # >= 2 models share the process but nothing separates their traffic —
    # no fleet attached, or a fleet whose policies declare no quota and
    # one single priority class (nothing to shed, nothing to preempt).
    # A single-model server, or a fleet with a quota or mixed priorities,
    # stays silent.
    if len(configs) >= 2:
        pols = (list(fleet._policies.values())
                if fleet is not None else [])
        isolated = any(p.quota_qps > 0 for p in pols) \
            or len({p.priority for p in pols}) > 1
        if not isolated:
            how = ("no fleet controller attached" if fleet is None else
                   "the attached fleet declares no per-tenant quota and "
                   "a single priority class")
            report.add(Diagnostic(
                "MXL-T217",
                "%d models share this serving process with no tenant "
                "isolation (%s): one tenant's request storm consumes "
                "the shared queue/worker capacity and becomes every "
                "tenant's outage" % (len(configs), how),
                location="server",
                hint="attach a FleetController with per-tenant "
                     "TenantPolicy(quota_qps=, priority=) — "
                     "docs/serving.md, 'Multi-tenant fleet'"))
    for cfg in configs:
        loc = f"model {cfg.name!r}"
        # ---- MXL-T217, tenant-level half: the fleet may autoscale this
        # tenant (its floor/ceiling leave room to move) but the tenant
        # declares no SLO — the burn-rate evaluator is blind to it
        if fleet is not None:
            pol = fleet._policies.get(cfg.name)
            autoscaled = pol is not None and (
                pol.ceiling_chips is None
                or pol.ceiling_chips > pol.floor_chips)
            if autoscaled and not float(
                    getattr(cfg, "slo_p99_ms", 0.0) or 0.0):
                report.add(Diagnostic(
                    "MXL-T217",
                    "tenant %r is autoscaled (floor %d, ceiling %r) but "
                    "declares no SLO: the burn-rate evaluator can "
                    "neither detect its excursions nor safely use it as "
                    "a donor" % (cfg.name, pol.floor_chips,
                                 pol.ceiling_chips),
                    location=loc,
                    hint="declare ModelConfig(slo_p99_ms=) for every "
                         "autoscaled tenant, or pin ceiling_chips == "
                         "floor_chips — docs/serving.md, 'Multi-tenant "
                         "fleet'"))
    for cfg in configs:
        loc = f"model {cfg.name!r}"
        if not int(getattr(cfg, "max_queue", 0) or 0):
            report.add(Diagnostic(
                "MXL-T214",
                "model %r serves with an UNBOUNDED request queue: under "
                "overload every request is accepted and answered "
                "arbitrarily late (queue memory grows without limit) "
                "instead of fast typed Overloaded rejections"
                % cfg.name,
                location=loc,
                hint="set ModelConfig(max_queue=N) (or "
                     "MXNET_SERVE_MAX_QUEUE) — docs/serving.md, "
                     "'Admission control'"))
        if not float(getattr(cfg, "deadline_ms", 0.0) or 0.0):
            report.add(Diagnostic(
                "MXL-T214",
                "model %r serves with no default per-request deadline: "
                "requests whose clients have long timed out are still "
                "queued and dispatched to the device, and the load-"
                "shedding policy (drop expired work before dispatch) "
                "never engages" % cfg.name,
                location=loc,
                hint="set ModelConfig(deadline_ms=D) (or "
                     "MXNET_SERVE_DEADLINE_MS) — clients can still "
                     "override per request; docs/serving.md, 'Deadlines'"))
        # ---- fp32 serving with a measured int8 win on file (MXL-T215):
        # the quant twin of T211/T212 — fires only on evidence (a MEASURED
        # label="quant" ledger row for this model on this device where
        # int8 actually won); an int8 tier, no row, or a different device
        # signature all stay silent
        if getattr(cfg, "tier", "f32") != "int8":
            win = None
            try:
                from ..quant import best_int8_cached
                from ..serving.executors import _device_kind
                win = best_int8_cached(device_kind=_device_kind()[0],
                                       model=cfg.name)
            except Exception:
                win = None
            if win:
                report.add(Diagnostic(
                    "MXL-T215",
                    "model %r serves on the f32 tier, but the cost ledger "
                    "holds a measured int8 win for it on %s: %.2fx faster "
                    "(%s %.3f ms -> int8 %.3f ms per forward) — every "
                    "request pays the non-quantized latency although the "
                    "cheaper executable is already measured"
                    % (cfg.name, win.get("device_kind"),
                       float(win.get("int8_vs_f32") or 0.0),
                       win.get("baseline_dtype") or "f32",
                       float(win.get("f32_ms") or 0.0),
                       float(win.get("int8_ms") or 0.0)),
                    location=loc,
                    hint="serve the int8 tier (ModelConfig(tier='int8') "
                         "or MXNET_SERVE_TIER=int8); calibrate first with "
                         "tools/mxquant.py for calibrated ranges — "
                         "docs/quantization.md, 'Serving tier'"))
        # ---- untraced serving path (MXL-T216): latency objectives are
        # declared (a default deadline and/or an SLO) but request tracing
        # is off or sampled at 0 — a breach produces no per-request span
        # timeline to attribute. Same fires/silent discipline as T214/
        # T215: a config without objectives, or with tracing on at a
        # nonzero rate, stays silent; old-style configs without the trace
        # attributes default to traced and stay silent too.
        declared = []
        if float(getattr(cfg, "deadline_ms", 0.0) or 0.0) > 0:
            declared.append("deadline_ms=%g" % cfg.deadline_ms)
        if float(getattr(cfg, "slo_p99_ms", 0.0) or 0.0) > 0:
            declared.append("slo_p99_ms=%g" % cfg.slo_p99_ms)
        try:
            from ..base import get_env
            ring_off = int(get_env("MXNET_TRACE_RING", 512) or 0) <= 0
        except Exception:
            ring_off = False
        untraced = (not bool(getattr(cfg, "trace", True))
                    or float(getattr(cfg, "trace_sample", 1.0) or 0.0)
                    <= 0.0
                    or ring_off)
        if declared and untraced:
            how = ("disabled" if not getattr(cfg, "trace", True)
                   else "disabled process-wide (MXNET_TRACE_RING=0)"
                   if ring_off else "sampled at 0")
            report.add(Diagnostic(
                "MXL-T216",
                "model %r declares latency objectives (%s) but serves "
                "with request tracing %s: a deadline/SLO breach leaves "
                "no per-request span timeline to attribute the miss to "
                "queue wait vs batch assembly vs device time"
                % (cfg.name, ", ".join(declared), how),
                location=loc,
                hint="enable tracing (ModelConfig(trace=True) / "
                     "MXNET_SERVE_TRACE=1) with a nonzero "
                     "MXNET_TRACE_SAMPLE — tail/error traces are always "
                     "retained; docs/observability.md, 'Request "
                     "tracing'"))
        # ---- no retry budget (MXL-T219): duplicate work (retries and/or
        # hedges) is enabled but uncapped — a correlated failure turns
        # every request into several, amplifying offered load onto the
        # already-degraded backend. Fires/silent discipline: retries=0
        # and hedge off stays silent, any nonzero retry_budget stays
        # silent, old-style configs without the attributes stay silent.
        dup = []
        if int(getattr(cfg, "retries", 0) or 0) > 0:
            dup.append("retries=%d" % cfg.retries)
        if bool(getattr(cfg, "hedge", False)):
            dup.append("hedge=True")
        if dup and float(getattr(cfg, "retry_budget", 1.0) or 0.0) <= 0.0:
            report.add(Diagnostic(
                "MXL-T219",
                "model %r duplicates work (%s) with NO retry budget "
                "(retry_budget=0): under a correlated failure every "
                "request retries and every hedge duplicates, multiplying "
                "offered load onto the degraded backend exactly when it "
                "can least absorb it (retry-storm amplification)"
                % (cfg.name, ", ".join(dup)),
                location=loc,
                hint="cap duplicate work with ModelConfig(retry_budget=) "
                     "or MXNET_SERVE_RETRY_BUDGET (default 0.1 = 10%% of "
                     "admitted traffic, shared by retries and hedges; "
                     "denials are counted in "
                     "mxtpu_retry_budget_denied_total) — docs/serving.md, "
                     "'Self-healing & tail tolerance'"))
    # ---- unbudgeted HBM overcommit (MXL-T218): needs the live server
    # (footprints come off its executor caches) — a bare ModelConfig has
    # no cache and stays silent. Fires on evidence only: a budget the
    # summed per-chip footprints exceed, or footprint rows on file for a
    # multi-model server with NO budget to check them against. A fitting
    # placement, a single model without a budget, or a server with no
    # memory evidence at all stay silent.
    srv = (server_or_config if hasattr(server_or_config, "_models")
           else None)
    if srv is not None:
        needs: Dict[str, int] = {}
        any_ledger = False
        budget = None
        try:
            from ..observability import memwatch as _memwatch
            budget = _memwatch.hbm_budget_bytes()
            for m, st in srv._models.items():
                fp = _memwatch.model_footprint(st.cache, model=m)
                needs[m] = _memwatch.per_chip_bytes(
                    fp, getattr(st.cache, "chips", 1) or 1)
                any_ledger = any_ledger or any(
                    b.get("source") == "ledger"
                    for b in (fp.get("buckets") or {}).values())
        except Exception:
            needs = {}
        if needs and budget is not None:
            avail = int(budget) - int(_memwatch.pressure()["ballast_bytes"])
            total_need = sum(needs.values())
            if total_need > avail:
                ranked = sorted(needs.items(), key=lambda kv: -kv[1])
                report.add(Diagnostic(
                    "MXL-T218",
                    "the %d served model(s) need ~%s/chip combined but "
                    "the per-chip HBM budget is %s — the placement is "
                    "overcommitted before any traffic arrives (largest: "
                    "%s)" % (len(needs), _fmt_bytes(total_need),
                             _fmt_bytes(max(0, avail)),
                             ", ".join("%s ~%s" % (m, _fmt_bytes(n))
                                       for m, n in ranked[:3])),
                    location="server",
                    hint="shed a model, shrink a bucket ladder, or raise "
                         "MXNET_HBM_BYTES — docs/observability.md, "
                         "'Memory observability'"))
        elif len(needs) >= 2 and budget is None and any_ledger:
            report.add(Diagnostic(
                "MXL-T218",
                "%d models serve with memory-footprint evidence on file "
                "(label='memory' ledger rows) but no per-chip HBM budget "
                "is configured: the memory-aware refusal paths (load "
                "budget check, fleet no_memory refusals) are blind and "
                "the first overcommit surfaces as a device OOM"
                % len(needs),
                location="server",
                hint="set MXNET_HBM_BYTES to the chip's capacity (or "
                     "serve on a device kind memwatch knows) — "
                     "docs/observability.md, 'Memory observability'"))
    # ---- ungated rollout (MXL-T220): needs the live server (rollouts
    # hang off server._rollout) — a bare ModelConfig, a server with no
    # rollout manager, or a manager with only terminal rollouts stays
    # silent. Fires once per disabled gate per in-flight rollout.
    mgr = getattr(srv, "_rollout", None) if srv is not None else None
    if mgr is not None:
        for ro in list(getattr(mgr, "_rollouts", {}).values()):
            if ro.state not in ("loading", "serving"):
                continue
            loc = "rollout %s@%s" % (ro.model, ro.version)
            if not ro.knobs.get("rollback", True):
                report.add(Diagnostic(
                    "MXL-T220",
                    "rollout of %r version %r ramps with automatic "
                    "rollback DISABLED (rollback=False): the gate "
                    "evaluates but only records gate_failed events — a "
                    "failing canary keeps its traffic share until an "
                    "operator notices" % (ro.model, ro.version),
                    location=loc,
                    hint="leave MXNET_ROLLOUT_ROLLBACK=1 (or drop the "
                         "rollback=False override) — docs/serving.md, "
                         "'Safe rollout'"))
            if float(ro.knobs.get("shadow_sample", 0.0) or 0.0) <= 0.0:
                report.add(Diagnostic(
                    "MXL-T220",
                    "rollout of %r version %r ramps with shadow "
                    "agreement sampling OFF (shadow_sample=0): a canary "
                    "that answers quickly but wrongly sails through the "
                    "latency/error gates and ships an accuracy "
                    "regression" % (ro.model, ro.version),
                    location=loc,
                    hint="set MXNET_ROLLOUT_SHADOW_SAMPLE (default "
                         "0.25) or the shadow_sample= start knob — "
                         "docs/serving.md, 'Safe rollout'"))
            if not float(getattr(ro.cfg, "slo_p99_ms", 0.0) or 0.0):
                report.add(Diagnostic(
                    "MXL-T220",
                    "the canary version %r of model %r declares no SLO "
                    "(slo_p99_ms=0): the burn-rate gate is blind to it; "
                    "only the coarser p99-vs-incumbent delta can catch "
                    "a latency regression" % (ro.version, ro.model),
                    location=loc,
                    hint="give the candidate ModelConfig(slo_p99_ms=) "
                         "an objective — docs/serving.md, 'Safe "
                         "rollout'"))
    return report


def lint_trainer(trainer, *data, suppress: Sequence[str] = (),
                 const_bytes_threshold: int = 1 << 20,
                 donate_bytes_threshold: int = 1024,
                 subject: str = "") -> Report:
    """Trace-lint a :class:`~mxnet_tpu.parallel.DataParallelTrainer`'s fused
    step against a sample batch, running :func:`lint_step` over the exact
    jitted step (donation read off the lowering, f64/const/source checks
    over the real trace). On an uncaptured trainer this captures the net
    first (one tiny host forward for deferred init); the lint itself is
    abstract evaluation only. A batch whose arity differs from an
    already-captured step is refused — recapturing from a diagnostics entry
    point would silently reset params/opt-state and drop any loaded AOT
    executable."""
    import jax.numpy as jnp
    from ..base import MXNetError
    from ..ndarray import NDArray
    from ..ndarray.ndarray import _unwrap

    # a ResilientTrainer lints as its inner DataParallelTrainer, plus the
    # resilience-config checks (MXL-T213) only the wrapper can answer
    resilient = None
    if hasattr(trainer, "trainer") and hasattr(trainer, "checkpointer"):
        resilient, trainer = trainer, trainer.trainer

    arrays = [_unwrap(d) if isinstance(d, NDArray) else jnp.asarray(d)
              for d in data]
    if trainer._step_fn is None:
        trainer._capture(len(arrays), sample_arrays=arrays)
    elif trainer._n_inputs != len(arrays):
        raise MXNetError(
            f"lint_trainer: sample batch has {len(arrays)} array(s) but the "
            f"captured step takes {trainer._n_inputs}; pass a batch of the "
            "training arity (lint never recaptures a live trainer)")
    rng = jax.random.PRNGKey(0)
    step_args = (trainer._params, trainer._aux, trainer._opt_state,
                 trainer._guard_state, rng) + tuple(arrays)
    report = lint_step(trainer._step_fn, step_args,
                       const_bytes_threshold=const_bytes_threshold,
                       donate_bytes_threshold=donate_bytes_threshold,
                       suppress=suppress,
                       subject=subject or "DataParallelTrainer fused step")

    # ---- layout propagation missed (MXL-G107): the trainer counted the
    # captured graph's NCHW convs at capture time — if any exist and the
    # pipeline it ran lacks the layout pass, the measured NHWC win was
    # left on the table (a graph-rule finding surfaced through the trace
    # front end because the capture context lives on the trainer)
    pinfo = getattr(trainer, "_pass_info", None) or {}
    if pinfo.get("nchw_convs") and not pinfo.get("layout_enabled"):
        report.add(Diagnostic(
            "MXL-G107",
            "%d NCHW conv(s) captured with the layout pass disabled — "
            "each pays per-step relayouts the automatic NCHW→NHWC "
            "propagation removes" % pinfo["nchw_convs"],
            location=type(trainer).__name__,
            hint="drop passes=False (or add 'layout' to MXNET_PASSES); "
                 "re-homed weights are handled transparently by the "
                 "capture path"))

    # ---- unscaled low-precision loss (MXL-T209): read off the trainer's
    # own config, not the trace — the hazard is the ABSENCE of scaler state
    cdtype = trainer._compute_dtype
    lowprec = cdtype is not None and str(np.dtype(cdtype)) in (
        "bfloat16", "float16")
    if lowprec and trainer._scaler_cfg is None:
        amp_on = False
        try:
            from ..contrib import amp as _amp
            amp_on = _amp.is_enabled()
        except Exception:
            pass
        report.add(Diagnostic(
            "MXL-T209",
            f"compute_dtype={np.dtype(cdtype)} step has no loss-scale "
            "state: gradients below the low-precision normal range "
            "underflow to zero silently (no NaN, no guard skip — just "
            "stalled convergence)"
            + (" — contrib.amp is enabled but its LossScaler is not wired "
               "into this fused step (and is not checkpointed here)"
               if amp_on else ""),
            location=type(trainer).__name__,
            hint="construct with loss_scaling=True (in-trace dynamic "
                 "scaling: overflow halves, growth_interval clean steps "
                 "double, zero per-step host syncs) — state rides in "
                 "checkpoints automatically"))

    # ---- uninstrumented hot loop (MXL-T210): also a config check — the
    # hazard is telemetry saying "the run is slow" with attribution unable
    # to say WHERE. Attribution is on by default with telemetry, so this
    # only fires on an explicit step_attribution=False / env off pairing.
    from ..observability import metrics as _obs_metrics
    if _obs_metrics.enabled() \
            and getattr(trainer, "_attr_cfg", "absent") is None:
        report.add(Diagnostic(
            "MXL-T210",
            "telemetry is enabled but step-time attribution is disabled: "
            "the hot loop publishes no step-breakdown / device-util / MFU "
            "gauges, so a regression cannot be attributed to device "
            "compute vs host dispatch vs data-feed stall",
            location=type(trainer).__name__,
            hint="drop step_attribution=False (or MXNET_PERF_ATTRIBUTION="
                 "0) — the bookkeeping is host-side only and never enters "
                 "the compiled step; or disable telemetry entirely if this "
                 "run truly must not measure itself"))

    # ---- untuned hot loop (MXL-T211): a config check against the tuner's
    # warm-start cache. Fires only when (a) the trainer runs all-DEFAULT
    # perf levers (no remat, no compute_dtype override, default donation),
    # (b) the cache holds a measured best config for the same model/device
    # signature, and (c) that config actually differs — on a lever the
    # trainer owns (remat/donate) or on the batch size the sample batch
    # shows. A user already running the tuned config is never nagged.
    all_default = (not getattr(trainer, "_remat", False)
                   and trainer._compute_dtype is None
                   and getattr(trainer, "_donate", True))
    if all_default:
        tuned = None
        try:
            from ..tuner import best_cached
            dev = trainer._mesh.devices.ravel()[0]
            # keyed by net_class (the built net's class name — the only
            # model signature a live trainer can derive about itself; the
            # tuner stamps it on every row next to the caller's label)
            # and the trainer's own chip count: a config measured on a
            # 32-chip slice is no recommendation for this mesh
            tuned = best_cached(device_kind=dev.device_kind,
                                net_class=type(trainer._net).__name__,
                                n_devices=int(trainer._mesh.devices.size))
        except Exception:
            tuned = None
        cfg = (tuned or {}).get("tuner_config") or {}
        sample_batch = int(arrays[0].shape[0]) if (
            arrays and getattr(arrays[0], "ndim", 0)) else None
        differs = cfg and (
            cfg.get("remat") is not None
            or cfg.get("donate") is False
            or (sample_batch is not None and cfg.get("batch") is not None
                and int(cfg["batch"]) != sample_batch))
        if differs:
            tput = tuned.get("throughput_img_s_per_chip")
            report.add(Diagnostic(
                "MXL-T211",
                "trainer runs all-default perf levers, but the tuner cache "
                "holds a measured best config for %s on %s: %s%s"
                % (type(trainer._net).__name__, tuned.get("device_kind"),
                   json.dumps(cfg, sort_keys=True),
                   " (%.1f img/s/chip measured)" % tput if tput else ""),
                location=type(trainer).__name__,
                hint="apply it (Candidate.from_dict(cfg).build_trainer(...)"
                     " or the matching DataParallelTrainer kwargs/batch), "
                     "or re-tune with tools/mxtune.py if the workload "
                     "changed"))

    # ---- replicated optimizer at scale (MXL-T212): another cache-backed
    # config check — the trainer spans >1 device on the default all-reduce
    # path (params AND optimizer state replicated on every chip) while the
    # tuner cache holds a MEASURED reduce_scatter win for the same
    # model/device/chip-count signature. Fires only on evidence: no cache
    # row, a single-device mesh, or a trainer already sharding its
    # optimizer all stay silent. The gate (and the ~Nx claim) use the DATA
    # axis extent — the divisor the recommended ZeRO sharding actually
    # shards by — not the total device count, so a dp=1 x tp=N mesh
    # (where reduce_scatter would shard nothing) never false-fires.
    try:
        n_mesh = int(trainer._mesh.shape[trainer._axis])
    except (KeyError, TypeError):
        n_mesh = int(trainer._mesh.devices.size)
    if n_mesh > 1 and \
            getattr(trainer, "_grad_reduce", "all_reduce") == "all_reduce":
        tuned = None
        try:
            from ..tuner import best_cached
            dev = trainer._mesh.devices.ravel()[0]
            tuned = best_cached(device_kind=dev.device_kind,
                                net_class=type(trainer._net).__name__,
                                n_devices=n_mesh)
        except Exception:
            tuned = None
        cfg = (tuned or {}).get("tuner_config") or {}
        if cfg.get("grad_reduce") == "reduce_scatter":
            tput = tuned.get("throughput_img_s_per_chip")
            opt_b = {}
            try:
                opt_b = trainer.opt_state_bytes()
            except Exception:
                pass
            report.add(Diagnostic(
                "MXL-T212",
                "trainer replicates its optimizer state on every one of %d "
                "devices (default grad_reduce='all_reduce'%s), but the "
                "tuner cache holds a measured reduce_scatter win for %s on "
                "%s%s — the ZeRO-1 sharded optimizer would cut per-chip "
                "opt-state HBM ~%dx and swap the all-reduce for the "
                "cheaper reduce-scatter + all-gather pair"
                % (n_mesh,
                   ", %d opt-state bytes per chip"
                   % opt_b["per_chip_bytes"]
                   if opt_b.get("per_chip_bytes") else "",
                   type(trainer._net).__name__, tuned.get("device_kind"),
                   " (%.1f img/s/chip measured)" % tput if tput else "",
                   n_mesh),
                location=type(trainer).__name__,
                hint="construct with grad_reduce='reduce_scatter' (step-"
                     "equivalent to the replicated baseline; checkpoints "
                     "round-trip the sharded state bitwise — see "
                     "docs/performance.md 'Scale-out performance'), or "
                     "re-tune with tools/mxtune.py if the workload changed"))

    # ---- inelastic restore (MXL-T213): a ResilientTrainer pointed at a
    # checkpoint directory whose newest manifest records a DIFFERENT mesh
    # topology, without elastic adoption enabled — its first auto-resume
    # raises TopologyMismatch instead of training. Purely a config check:
    # nothing is restored here, only the manifest is read. resume=False
    # never restores, so it is never flagged.
    if resilient is not None \
            and getattr(resilient, "_elastic_cfg", None) is None \
            and getattr(resilient, "resume", True):
        from ..resilience import elastic as _elastic
        saved = None
        try:
            latest = resilient.checkpointer.latest_step()
            if latest is not None:
                saved = resilient.checkpointer.read_manifest(
                    latest).get("user", {}).get("topology")
        except Exception:
            saved = None
        if saved:
            live = trainer.topology()
            # the runtime guard's own mismatch test — the lint verdict
            # and the TopologyMismatch it predicts cannot drift
            if _elastic._mismatch(saved, live):
                saved_dp = _elastic._dp_of(saved)
                report.add(Diagnostic(
                    "MXL-T213",
                    "checkpoint step %s in %s was saved on a %s-device "
                    "mesh (dp=%s) but this trainer runs %d devices "
                    "(dp=%d) without elastic adoption: the first "
                    "auto-resume raises TopologyMismatch instead of "
                    "training"
                    % (latest, resilient.checkpointer.directory,
                       saved.get("n_devices"), saved_dp,
                       live["n_devices"], live["dp"]),
                    location=type(resilient).__name__,
                    hint="construct with elastic=True (or MXNET_ELASTIC=1"
                         ", or use resilience.ElasticTrainer) so the "
                         "ZeRO-1 optimizer state re-shards N→M and the "
                         "global batch re-splits over the live mesh — "
                         "docs/resilience.md 'Elastic data parallelism'"))
    return report
