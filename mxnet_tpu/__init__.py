"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

A brand-new framework (not a port): the reference's C++ dependency engine,
CUDA/mshadow kernels, NNVM memory planning and NCCL/ps-lite communication are
replaced by XLA async dispatch, jax/Pallas compute, whole-graph XLA lowering
and ICI/DCN collectives. See SURVEY.md at the repo root for the blueprint and
per-module docstrings for reference file:line parity citations.

Public surface (mirrors ``python/mxnet``):
    mx.nd        imperative arrays       mx.sym      symbolic graphs
    mx.autograd  tape autograd           mx.gluon    imperative models + JIT
    mx.mod       Module API              mx.kv       KVStore (XLA collectives)
    mx.io        data iterators          mx.optimizer / mx.metric / mx.init
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# MXNet exposes float64/int64 dtypes on request; jax hides them by default.
# Default creation paths still produce float32 (MXNET_DEFAULT_DTYPE).
_jax.config.update("jax_enable_x64", True)

from . import base
from .base import (MXNetError, TransientKVError, TransientIOError,
                   CorruptRecordError)
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus, gpu_memory_info
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray
from .attribute import AttrScope
from . import name
from . import attribute

# Under tools/launch.py the coordinator env trio is set: join the
# jax.distributed cluster NOW, before anything can initialize the XLA
# backend (the reference's ps-lite StartAsync happens equally early via the
# tracker env). No-op outside a launched job.
import os as _os
if _os.environ.get("JAX_COORDINATOR_ADDRESS") \
        or _os.environ.get("MXNET_COORDINATOR_ADDRESS"):
    from .kvstore import _maybe_join_cluster as _join
    _join()
    del _join

# Submodules imported lazily to keep import light and avoid cycles.
import importlib as _importlib

_lazy = {
    "symbol": ".symbol", "sym": ".symbol",
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "metric": ".metric",
    "initializer": ".initializer", "init": ".initializer",
    "lr_scheduler": ".lr_scheduler",
    "io": ".io",
    "recordio": ".recordio",
    "image": ".image",
    "kvstore": ".kvstore", "kv": ".kvstore",
    "module": ".module", "mod": ".module",
    "model": ".model",
    "callback": ".callback",
    "monitor": ".monitor",
    "profiler": ".profiler",
    "parallel": ".parallel",
    "rnn": ".rnn",
    "visualization": ".visualization", "viz": ".visualization",
    "rtc": ".rtc",
    "operator": ".operator",
    "registry": ".registry",
    "kvstore_server": ".kvstore_server",
    "engine": ".engine",
    "executor": ".executor",
    "test_utils": ".test_utils",
    "util": ".util",
    "interop": ".interop",
    "contrib": ".contrib",
    "checkpoint": ".checkpoint",
    "gradient_compression": ".gradient_compression",
    "resilience": ".resilience",
    "analysis": ".analysis",
    "observability": ".observability",
    "tuner": ".tuner",
    "passes": ".passes",
    "serving": ".serving",
    "quant": ".quant",
}


def __getattr__(name):
    if name in _lazy:
        mod = _importlib.import_module(_lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
