"""JIT compile/retrace visibility via ``jax.monitoring``.

Retraces are the silent TPU performance killer (a closure scalar, a weak
dtype, a fresh shape — and suddenly every "cached" step recompiles). mxlint
catches the static cases before running; this hook measures the dynamic
truth: every jaxpr trace and every backend (XLA) compile the process
actually performs, counted and timed into the metrics registry.

jax emits named duration events through ``jax.monitoring``; we subscribe one
process-wide listener (idempotent install) and translate:

- ``/jax/core/compile/jaxpr_trace_duration``   → ``mxtpu_jit_traces_total``
- ``/jax/core/compile/backend_compile_duration`` →
  ``mxtpu_jit_backend_compiles_total`` + ``mxtpu_jit_compile_ms`` histogram
- ``/jax/compilation_cache/cache_hits``        → ``mxtpu_jit_cache_hits_total``

The listener respects the live ``MXNET_TELEMETRY`` switch, and registration
itself costs nothing between compiles.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = ["install", "installed", "last_compile_ms",
           "recent_compile_events", "JIT_TRACES", "JIT_COMPILES",
           "JIT_COMPILE_MS", "JIT_CACHE_HITS"]

JIT_TRACES = _metrics.counter(
    "mxtpu_jit_traces_total",
    "jaxpr traces performed (a growing count under a steady workload means "
    "the step function is retracing).")
JIT_COMPILES = _metrics.counter(
    "mxtpu_jit_backend_compiles_total", "XLA backend compiles performed.")
JIT_COMPILE_MS = _metrics.histogram(
    "mxtpu_jit_compile_ms", "XLA backend compile wall time.",
    buckets=(10, 50, 100, 500, 1000, 5000, 15000, 60000, 300000))
JIT_CACHE_HITS = _metrics.counter(
    "mxtpu_jit_cache_hits_total",
    "persistent compilation-cache hits (compiles avoided).")

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_lock = threading.Lock()
_installed = False
_last_compile_ms = None

# timestamped ring of recent trace/compile events (perf_counter seconds):
# the shared-clock lane Tracer.chrome_trace merges next to serving spans,
# so the compile that delayed a request lines up with its queue span
_COMPILE_EVENTS: deque = deque(maxlen=64)


def last_compile_ms():
    """Wall time of the most recent XLA backend compile this process
    performed (None before the first one) — the cost ledger attaches it to
    the row of the executable captured right after a compile event."""
    return _last_compile_ms


def recent_compile_events():
    """Recent jaxpr-trace / backend-compile events as ``{"event",
    "t0", "dur_s"}`` dicts, ``t0`` in ``time.perf_counter`` seconds —
    the clock the profiler and the trace ring export against."""
    return list(_COMPILE_EVENTS)


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    if not _metrics.enabled():
        return
    if event == _TRACE_EVENT:
        JIT_TRACES.inc()
        _COMPILE_EVENTS.append({"event": "jaxpr_trace",
                                "t0": time.perf_counter() - duration_secs,
                                "dur_s": duration_secs})
    elif event == _COMPILE_EVENT:
        global _last_compile_ms
        _last_compile_ms = duration_secs * 1000.0
        JIT_COMPILES.inc()
        JIT_COMPILE_MS.observe(duration_secs * 1000.0)
        _COMPILE_EVENTS.append({"event": "backend_compile",
                                "t0": time.perf_counter() - duration_secs,
                                "dur_s": duration_secs})


def _on_event(event: str, **kwargs) -> None:
    if not _metrics.enabled():
        return
    if event == _CACHE_HIT_EVENT:
        JIT_CACHE_HITS.inc()


def install() -> bool:
    """Register the jax.monitoring listeners once per process. Returns True
    when listeners are active (now or from an earlier call)."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:
            return False
        _installed = True
        return True


def installed() -> bool:
    return _installed
