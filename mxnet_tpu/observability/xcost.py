"""XLA cost ledger — per-executable compile-time performance facts.

Every compiled train step carries a free, exact self-description: XLA's
``cost_analysis()`` knows the FLOPs, the bytes moved through HBM and the
transcendental count of the whole fused program. Until now that data was
extracted once, in ``bench.py``, printed to stderr and lost. This module
makes it a first-class, persistent artifact:

- :func:`analyze_cost` turns a raw ``cost_analysis()`` dict into a row with
  derived quantities — arithmetic intensity (FLOPs/byte), the device's
  roofline ridge point (peak FLOPs ÷ peak HBM bandwidth) and a
  **compute-bound / memory-bound** classification, plus the optimal step
  time on each roof;
- :class:`CostLedger` persists rows to an **append-only JSON-lines ledger**
  (one row per line, corrupt lines skipped on read) keyed by the trainer's
  ``aot_key`` and the executable's StableHLO digest — the same fingerprint
  ``aot_save``/``aot_load`` trust, so a ledger row provably describes a
  specific compiled program;
- :func:`capture` is the one-call tap the trainer and ``bench.py`` use at
  compile time: lowered computation in, analyzed + persisted row out.

The ledger is the feature store the ROADMAP-1 autotuner reads ("A Learned
Performance Model for TPUs" builds its feature vectors from exactly these
per-program cost fields), and ``tools/perfwatch.py`` compares fresh rows
against cached bench baselines.

Everything here is host-side metadata extraction: with the ledger disabled
(``MXNET_PERF_LEDGER`` empty) nothing is lowered, written or counted, and
the jitted step's HLO is bitwise identical either way (tier-1 guards it).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis.lockwatch import make_lock
from ..base import get_env, logger, register_config
from . import metrics as _metrics

__all__ = ["DEVICE_PEAKS", "peak_flops", "peak_hbm_bw", "analyze_cost",
           "CostLedger", "ledger_path", "enabled", "get_ledger", "capture",
           "cost_of", "merge_costs", "memory_of"]

register_config("MXNET_PERF_LEDGER", "", str,
                "Path of the append-only JSON-lines cost ledger. Non-empty "
                "enables the perf layer's compile-time cost capture (one "
                "extra host-side lowering per executable, nothing in the "
                "compiled HLO); empty disables capture entirely.")
register_config("MXNET_PERF_PEAK_FLOPS", 0.0, float,
                "Per-chip peak FLOP/s override for roofline/MFU math. 0 = "
                "use the built-in device_kind table (required for devices "
                "the table does not know, e.g. the CPU backend).")
register_config("MXNET_PERF_PEAK_HBM_GBPS", 0.0, float,
                "Per-chip peak HBM bandwidth override in GB/s for the "
                "roofline ridge point. 0 = use the built-in table.")

# (device_kind substring, bf16 peak FLOP/s, HBM bytes/s) per chip — public
# TPU specs. Substring match, most-specific first ("v5 lite"/"v5e" before
# "v5"). The env overrides above win over the table.
DEVICE_PEAKS = (
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v5", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)


def _table_lookup(device_kind: Optional[str]):
    kind = (device_kind or "").lower()
    for sub, pf, bw in DEVICE_PEAKS:
        if sub in kind:
            return pf, bw
    return None, None


def peak_flops(device_kind: Optional[str]) -> Optional[float]:
    """Per-chip peak FLOP/s (env override wins over the table; None when
    neither knows the device)."""
    ov = float(get_env("MXNET_PERF_PEAK_FLOPS", 0.0))
    if ov > 0:
        return ov
    return _table_lookup(device_kind)[0]


def peak_hbm_bw(device_kind: Optional[str]) -> Optional[float]:
    """Per-chip peak HBM bandwidth in bytes/s (env override in GB/s wins)."""
    ov = float(get_env("MXNET_PERF_PEAK_HBM_GBPS", 0.0))
    if ov > 0:
        return ov * 1e9
    return _table_lookup(device_kind)[1]


def analyze_cost(cost: Dict[str, Any], device_kind: Optional[str] = None,
                 n_devices: int = 1) -> Dict[str, Any]:
    """Derive the roofline row from a raw ``cost_analysis()`` dict.

    Keys always present: ``flops``, ``bytes_accessed``, ``transcendentals``
    (None when XLA did not report them), ``arithmetic_intensity``,
    ``roofline`` (``compute-bound`` / ``memory-bound`` / ``unknown``),
    ``device_kind``, ``n_devices``. When the device's peaks are known
    (table or override) the row also carries ``peak_flops``,
    ``peak_hbm_bw``, ``ridge_intensity`` and the two roof times
    ``optimal_ms_compute`` / ``optimal_ms_memory`` — the step time a
    perfectly efficient execution would take on the compute or memory roof.
    """
    cost = cost or {}
    flops = float(cost.get("flops", 0.0) or 0.0) or None
    bytes_a = float(cost.get("bytes accessed", 0.0) or 0.0) or None
    trans = cost.get("transcendentals")
    row: Dict[str, Any] = {
        "flops": flops,
        "bytes_accessed": bytes_a,
        "transcendentals": float(trans) if trans else None,
        "device_kind": device_kind,
        "n_devices": int(n_devices),
    }
    intensity = (flops / bytes_a) if flops and bytes_a else None
    row["arithmetic_intensity"] = intensity
    pf = peak_flops(device_kind)
    bw = peak_hbm_bw(device_kind)
    if pf:
        row["peak_flops"] = pf
        if flops:
            row["optimal_ms_compute"] = flops / (pf * n_devices) * 1e3
    if bw:
        row["peak_hbm_bw"] = bw
        if bytes_a:
            row["optimal_ms_memory"] = bytes_a / (bw * n_devices) * 1e3
    ridge = (pf / bw) if pf and bw else None
    if ridge is not None:
        row["ridge_intensity"] = ridge
    if intensity is not None and ridge is not None:
        row["roofline"] = ("compute-bound" if intensity >= ridge
                           else "memory-bound")
    else:
        row["roofline"] = "unknown"
    return row


class CostLedger:
    """Append-only JSON-lines ledger of cost rows.

    One row per line keeps appends atomic enough for concurrent writers
    (single ``write`` of a short line in ``O_APPEND`` mode) and makes the
    file greppable/streamable; :meth:`rows` skips corrupt lines instead of
    failing, so a torn tail write can never poison the history.
    """

    def __init__(self, path: str):
        if not path:
            raise ValueError("CostLedger needs a path")
        self.path = str(path)
        self._lock = make_lock("observability.xcost.CostLedger._lock")

    def append(self, row: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp and append one row; returns the stamped row."""
        row = dict(row)
        row.setdefault("version", 1)
        row.setdefault("time", time.time())
        row.setdefault("pid", os.getpid())
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        line = json.dumps(row, sort_keys=True, default=_json_default) + "\n"
        with self._lock, open(self.path, "a") as f:
            f.write(line)
        if _metrics.enabled():
            from . import catalog as _catalog
            _catalog.COST_LEDGER_ROWS.inc()
        return row

    def rows(self, fingerprint: Optional[str] = None) -> List[Dict[str, Any]]:
        """Every parseable row, oldest first (optionally filtered by
        executable fingerprint). A missing file is an empty ledger."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue                    # torn/corrupt line: skip, keep rest
            if isinstance(row, dict) and (
                    fingerprint is None
                    or row.get("fingerprint") == fingerprint):
                out.append(row)
        return out

    def last(self, fingerprint: Optional[str] = None) -> Optional[Dict[str, Any]]:
        rows = self.rows(fingerprint=fingerprint)
        return rows[-1] if rows else None

    def __len__(self) -> int:
        return len(self.rows())


def _json_default(o):
    try:
        return float(o)
    except Exception:
        return repr(o)


def ledger_path() -> str:
    return str(get_env("MXNET_PERF_LEDGER", "") or "")


def enabled() -> bool:
    """The cost-capture gate: a configured ledger path (and the telemetry
    master switch, checked by callers via ``metrics.enabled``)."""
    return bool(ledger_path())


def get_ledger() -> Optional[CostLedger]:
    path = ledger_path()
    return CostLedger(path) if path else None


def cost_of(lowered) -> Optional[Dict[str, Any]]:
    """Raw ``cost_analysis()`` dict of one lowered computation, or None
    when the backend reports nothing. Compile-free where supported — a
    compile is never triggered here (minutes on remote-compile tunnels)."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca or None


def merge_costs(*costs) -> Optional[Dict[str, Any]]:
    """Sum the additive cost fields of several programs that together make
    one logical step (the kv path's grad + apply programs). ALL parts must
    be present — a partial sum would silently understate the step and
    poison every MFU computed from it."""
    if not costs or any(not c for c in costs):
        return None
    out: Dict[str, Any] = {}
    for ca in costs:
        for k in ("flops", "bytes accessed", "transcendentals"):
            v = ca.get(k)
            if v:
                out[k] = out.get(k, 0.0) + float(v)
    return out or None


def memory_of(compiled) -> Optional[Dict[str, int]]:
    """XLA ``memory_analysis()`` of one compiled executable as a plain
    byte dict, or None when the backend reports nothing. The shared
    extraction for every memory row (here and in ``memwatch``)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    return {
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
        "generated_code_bytes": int(
            getattr(mem, "generated_code_size_in_bytes", 0)),
    }


def capture(lowered=None, *, cost: Optional[Dict[str, Any]] = None,
            key: Optional[Dict[str, Any]] = None,
            fingerprint: Optional[str] = None, label: str = "",
            device_kind: Optional[str] = None, platform: Optional[str] = None,
            n_devices: int = 1, compiled=None, compile_for_memory: bool = False,
            extra: Optional[Dict[str, Any]] = None,
            ledger: Optional[CostLedger] = None) -> Optional[Dict[str, Any]]:
    """Analyze one logical step and persist the row.

    Pass ``lowered`` (a ``jax.stages.Lowered``) for a single-program step,
    or a precomputed ``cost`` dict (e.g. :func:`merge_costs` over the kv
    path's grad+apply programs) for multi-program steps. ``compiled`` may
    pass the already-compiled executable (the ``aot_save`` path) to enrich
    the row with XLA's memory analysis; ``compile_for_memory=True`` closes
    the lazy-path gap instead — an analysis compile of ``lowered`` is
    performed here solely for ``memory_analysis`` (the program actually
    dispatched is untouched; callers gate this on
    ``memwatch.capture_enabled()``). Returns the persisted row, or None
    when telemetry is off or the backend reports no costs. Never raises:
    the perf layer must not be able to kill training.
    """
    if not _metrics.enabled():
        return None
    try:
        ca = cost if cost is not None else cost_of(lowered)
        if not ca:
            logger.warning("cost ledger: backend reported no cost analysis "
                           "for %s", label or "executable")
            return None
        row = analyze_cost(ca, device_kind=device_kind, n_devices=n_devices)
        row.update({"label": label, "fingerprint": fingerprint,
                    "aot_key": key, "platform": platform})
        if compiled is not None:
            # only the aot_save-style path, where the compile just happened
            # inside this call, may claim the jit_hooks compile duration —
            # the lazy pre-dispatch step capture runs BEFORE its program
            # compiles, when last_compile_ms still names an earlier one
            from . import jit_hooks as _jit
            last_ms = _jit.last_compile_ms()
            if last_ms is not None:
                row["last_compile_ms"] = last_ms
        elif compile_for_memory and lowered is not None:
            try:
                compiled = lowered.compile()
            except Exception:
                compiled = None
        if compiled is not None:
            mem = memory_of(compiled)
            if mem:
                row["memory"] = mem
                row["peak_memory_bytes"] = (mem["temp_bytes"]
                                            + mem["argument_bytes"]
                                            + mem["output_bytes"])
        if extra:
            row.update(extra)
        led = ledger if ledger is not None else get_ledger()
        if led is not None:
            led.append(row)
        return row
    except Exception as e:  # pragma: no cover - defensive: never kill a run
        logger.warning("cost ledger capture failed: %r", e)
        return None
