"""HBM memory observability — footprint ledger, live accounting, OOM forensics.

The perf spine (``xcost``/``perfwatch``) explains *time*; this module is
its byte-side twin. Four surfaces, all strictly host-side (the compiled
HLO is bitwise identical with memwatch on or off — tier-1 guards it):

- **Memory ledger** — per-executable memory rows (argument/output/temp/
  generated-code bytes from XLA's ``memory_analysis``), persisted as
  ``label="memory"`` rows in the same append-only :class:`~.xcost.CostLedger`
  the roofline rows live in, keyed by the StableHLO fingerprint +
  device_kind/n_devices the AOT cache trusts. :func:`record_executable`
  is the one-call tap; ``BucketExecutorCache`` records one row per bound
  serving bucket and ``xcost.capture(compile_for_memory=True)`` closes the
  lazy train-step gap.
- **Live accounting** — :func:`poll_hbm` reads ``device.memory_stats()``
  into the ``mxtpu_hbm_*`` gauges with watermark history. Backends without
  memory_stats (the CPU tier-1 backend) degrade to a synthetic live-set
  sum over trees registered via :func:`track`, so the full path runs in
  every test tier.
- **OOM forensics** — :func:`to_hbm_exhausted` classifies a raw XLA
  RESOURCE_EXHAUSTED at a dispatch boundary, writes an ``mxtpu_oom.json``
  postmortem (:func:`write_postmortem`: footprints, resident bucket
  ladders, top-N largest executables, watermark tail, blame ranking,
  active trace_id) and returns a typed :class:`HBMExhausted` to re-raise.
- **Budget math** — per-chip HBM capacity table + ``MXNET_HBM_BYTES``
  override feed :func:`placement_check`/:func:`fleet_memory_check`, which
  the FleetController and ModelServer consult before binding executables
  a chip cannot hold (refusal reason ``no_memory`` /
  ``MemoryBudgetExceeded`` instead of a device OOM mid-traffic).

``serving.chaos.hbm_pressure`` drives all of this deterministically by
installing a shrunken budget + ballast through :func:`set_pressure`.
"""
from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis.lockwatch import make_lock
from ..base import MXNetError, get_env, logger, register_config
from . import metrics as _metrics
from . import xcost as _xcost

__all__ = [
    "DEVICE_HBM", "hbm_capacity_bytes", "hbm_budget_bytes",
    "capture_enabled", "HBMExhausted", "is_oom", "to_hbm_exhausted",
    "tree_bytes", "track", "untrack", "live_set_bytes", "poll_hbm",
    "watermark_history", "record_executable", "memory_rows",
    "model_footprint", "trainer_footprint", "placement_check",
    "fleet_memory_check", "set_pressure", "pressure",
    "write_postmortem", "postmortem_path", "top_executables", "blame_table",
]

register_config("MXNET_HBM_BYTES", 0, int,
                "Per-chip HBM budget override in bytes for memory-aware "
                "placement. 0 = use the built-in device_kind capacity "
                "table; devices the table does not know (e.g. the CPU "
                "backend) then have NO budget and memory refusals are "
                "off.")
register_config("MXNET_MEM_CAPTURE", True, bool,
                "Attach XLA memory_analysis to lazy-path cost-ledger rows. "
                "Costs one extra host-side analysis compile per executable "
                "signature (the compiled program actually dispatched is "
                "untouched); set 0 on remote-compile tunnels where a "
                "second compile is minutes, not milliseconds.")
register_config("MXNET_OOM_DIR", "", str,
                "Directory the mxtpu_oom.json OOM postmortem artifact is "
                "written to. Empty = current working directory.")

GiB = 1024 ** 3

# (device_kind substring, HBM bytes per chip) — public TPU specs, matched
# most-specific first like xcost.DEVICE_PEAKS. MXNET_HBM_BYTES wins.
DEVICE_HBM = (
    ("v6", 32 * GiB),
    ("v5p", 95 * GiB),
    ("v5e", 16 * GiB),
    ("v5 lite", 16 * GiB),
    ("v5", 95 * GiB),
    ("v4", 32 * GiB),
    ("v3", 32 * GiB),
    ("v2", 16 * GiB),
)

_WATERMARK_KEEP = 256

_lock = make_lock("observability.memwatch._lock")
_LIVE_SETS: Dict[str, Any] = {}        # name -> tree or () -> bytes callable
_WATERMARKS: "collections.deque" = collections.deque(maxlen=_WATERMARK_KEEP)
_SYNTH_PEAK = [0]                      # running peak of the synthetic path
# chaos hook (serving.chaos.hbm_pressure): a shrunken budget and/or a
# ballast reserve, installed/removed atomically via set_pressure()
_PRESSURE: Dict[str, Any] = {"budget_bytes": None, "ballast_bytes": 0}


# --------------------------------------------------------------- budget math
def _device_kind() -> Optional[str]:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return None


def hbm_capacity_bytes(device_kind: Optional[str]) -> Optional[int]:
    """Physical per-chip HBM from the table; None for unknown devices."""
    kind = (device_kind or "").lower()
    for sub, cap in DEVICE_HBM:
        if sub in kind:
            return int(cap)
    return None


def hbm_budget_bytes(device_kind: Optional[str] = None) -> Optional[int]:
    """The per-chip byte budget placement math works against.

    Priority: chaos pressure override > ``MXNET_HBM_BYTES`` > capacity
    table. None = unbudgeted (unknown device, nothing configured):
    memory-aware refusals are off, never guessed.
    """
    with _lock:
        ov = _PRESSURE.get("budget_bytes")
    if ov:
        return int(ov)
    env = int(get_env("MXNET_HBM_BYTES", 0) or 0)
    if env > 0:
        return env
    if device_kind is None:
        device_kind = _device_kind()
    return hbm_capacity_bytes(device_kind)


def capture_enabled() -> bool:
    """Gate for the lazy-path memory_analysis attach (one extra analysis
    compile per executable signature)."""
    return bool(get_env("MXNET_MEM_CAPTURE", True))


def set_pressure(budget_bytes: Optional[int] = None,
                 ballast_bytes: int = 0) -> None:
    """Install (or with defaults, clear) synthetic memory pressure — the
    deterministic lever ``serving.chaos.hbm_pressure`` pulls: an override
    budget and/or a ballast reserve subtracted from every chip's budget."""
    with _lock:
        _PRESSURE["budget_bytes"] = (int(budget_bytes)
                                     if budget_bytes else None)
        _PRESSURE["ballast_bytes"] = max(0, int(ballast_bytes))


def pressure() -> Dict[str, Any]:
    with _lock:
        return dict(_PRESSURE)


# ------------------------------------------------------------- typed errors
class HBMExhausted(MXNetError):
    """A device RESOURCE_EXHAUSTED, re-raised typed at a dispatch boundary
    after the postmortem artifact was written. ``.postmortem`` holds the
    artifact path (None if the write itself failed)."""

    def __init__(self, msg: str, postmortem: Optional[str] = None):
        super().__init__(msg)
        self.postmortem = postmortem


_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory",
                "allocation failure", "oom")


def is_oom(exc: BaseException) -> bool:
    """True when ``exc`` (or anything on its cause/context chain) is an
    XLA RESOURCE_EXHAUSTED-style allocation failure."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, HBMExhausted):
            return True
        txt = ("%s: %s" % (type(exc).__name__, exc)).lower()
        if any(m in txt for m in _OOM_MARKERS):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def to_hbm_exhausted(exc: BaseException, *, context: str,
                     server=None, trainer=None,
                     model: Optional[str] = None) -> Optional["HBMExhausted"]:
    """Classify ``exc`` at a dispatch boundary.

    Returns a typed :class:`HBMExhausted` (postmortem already written,
    counter bumped) for allocation failures, None for everything else —
    callers re-raise the returned error and leave other exceptions alone.
    Never raises: forensics must not mask the original failure.

    An exception that is ALREADY an :class:`HBMExhausted` (or carries one
    in its cause chain) returns None: an inner boundary wrote the
    postmortem; a second one at an outer layer would overwrite its blame
    table with the outer (less specific) context.
    """
    seen = exc
    for _ in range(16):                     # bounded: cycles can't hang us
        if seen is None:
            break
        if isinstance(seen, HBMExhausted):
            return None
        seen = seen.__cause__ or seen.__context__
    if not is_oom(exc):
        return None
    path = None
    try:
        path = write_postmortem(context, exc=exc, server=server,
                                trainer=trainer, model=model)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("OOM postmortem write failed: %r", e)
    if _metrics.enabled():
        from . import catalog as _c
        _c.OOM_TOTAL.inc(context=context)
    return HBMExhausted(
        "HBM exhausted during %s%s: %r (postmortem: %s)"
        % (context, (" [model=%s]" % model) if model else "", exc,
           path or "unavailable"),
        postmortem=path)


# ---------------------------------------------------------- live accounting
def tree_bytes(tree) -> int:
    """Total buffer bytes across a pytree of arrays (anything exposing
    ``nbytes``; other leaves count 0)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = tree if isinstance(tree, (list, tuple)) else [tree]
    return sum(int(getattr(leaf, "nbytes", 0) or 0) for leaf in leaves)


def track(name: str, tree_or_fn) -> None:
    """Register a live set for the synthetic (no memory_stats) path:
    either a pytree of arrays or a zero-arg callable returning bytes.
    Re-registering a name replaces it."""
    with _lock:
        _LIVE_SETS[str(name)] = tree_or_fn


def untrack(name: str) -> None:
    with _lock:
        _LIVE_SETS.pop(str(name), None)


def live_set_bytes() -> Dict[str, int]:
    """name -> current bytes of every registered live set (a provider that
    raises reports 0 — accounting must never take a process down)."""
    with _lock:
        items = list(_LIVE_SETS.items())
    out: Dict[str, int] = {}
    for name, src in items:
        try:
            out[name] = int(src()) if callable(src) else tree_bytes(src)
        except Exception:
            out[name] = 0
    return out


def poll_hbm(devices: Optional[Sequence] = None) -> Dict[str, Any]:
    """One live-memory sample: per-device in-use/peak/largest published to
    the ``mxtpu_hbm_*`` gauges, a watermark appended to the ring.

    Devices with ``memory_stats()`` report real allocator numbers; the
    rest (CPU) degrade to the synthetic live-set sum (registered trees +
    chaos ballast), with a running synthetic peak — so tier-1 exercises
    gauges, watermarks and budget math end to end.
    """
    if devices is None:
        try:
            import jax
            devices = jax.devices()
        except Exception:
            devices = []
    per_dev: List[Dict[str, Any]] = []
    synthetic = False
    live = None
    for i, d in enumerate(devices):
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            row = {"device": str(getattr(d, "id", i)),
                   "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
                   "peak_bytes": int(stats.get("peak_bytes_in_use", 0) or 0),
                   "largest_alloc_bytes": int(
                       stats.get("largest_alloc_size", 0) or 0),
                   "bytes_limit": int(stats.get("bytes_limit", 0) or 0),
                   "synthetic": False}
        else:
            synthetic = True
            if live is None:
                live = live_set_bytes()
                live["ballast"] = int(pressure()["ballast_bytes"])
            in_use = sum(live.values())
            with _lock:
                _SYNTH_PEAK[0] = max(_SYNTH_PEAK[0], in_use)
                peak = _SYNTH_PEAK[0]
            row = {"device": str(getattr(d, "id", i)),
                   "bytes_in_use": in_use, "peak_bytes": peak,
                   "largest_alloc_bytes": max(live.values()) if live else 0,
                   "bytes_limit": int(hbm_budget_bytes() or 0),
                   "synthetic": True}
        per_dev.append(row)
    total = sum(r["bytes_in_use"] for r in per_dev)
    peak = max([r["peak_bytes"] for r in per_dev] or [0])
    largest = max([r["largest_alloc_bytes"] for r in per_dev] or [0])
    if _metrics.enabled():
        from . import catalog as _c
        for r in per_dev:
            _c.HBM_BYTES_IN_USE.set(r["bytes_in_use"], device=r["device"])
        _c.HBM_PEAK_BYTES.set(peak)
        _c.HBM_LARGEST_ALLOC_BYTES.set(largest)
    with _lock:
        _WATERMARKS.append({"time": time.time(), "bytes_in_use": total,
                            "peak_bytes": peak})
    return {"devices": per_dev, "total_bytes_in_use": total,
            "peak_bytes": peak, "largest_alloc_bytes": largest,
            "synthetic": synthetic,
            "budget_bytes": hbm_budget_bytes(),
            "live_sets": live if live is not None else None}


def watermark_history(n: int = _WATERMARK_KEEP) -> List[Dict[str, Any]]:
    """The most recent ``n`` watermark samples, oldest first."""
    with _lock:
        hist = list(_WATERMARKS)
    return hist[-int(n):]


# ------------------------------------------------------------ memory ledger
def record_executable(lowered=None, *, compiled=None,
                      label: str = "", fingerprint: Optional[str] = None,
                      device_kind: Optional[str] = None,
                      platform: Optional[str] = None, n_devices: int = 1,
                      extra: Optional[Dict[str, Any]] = None,
                      ledger=None) -> Optional[Dict[str, Any]]:
    """Persist one ``label="memory"`` ledger row for a compiled program.

    Pass ``lowered`` to have the fingerprint derived (sha256 of the
    StableHLO text — the AOT-cache fingerprint) and, with ``compiled``
    absent and :func:`capture_enabled`, an analysis compile performed.
    Returns the persisted row; None when the ledger/telemetry is off or
    the backend reports nothing. Never raises.
    """
    if not (_metrics.enabled() and _xcost.enabled()) and ledger is None:
        return None
    try:
        if fingerprint is None and lowered is not None:
            import hashlib
            fingerprint = hashlib.sha256(
                lowered.as_text().encode()).hexdigest()
        if compiled is None and lowered is not None and capture_enabled():
            compiled = lowered.compile()
        if compiled is None:
            return None
        mem = _xcost.memory_of(compiled)
        if not mem:
            return None
        row: Dict[str, Any] = {
            "label": "memory", "mem_label": label,
            "fingerprint": fingerprint,
            "device_kind": device_kind, "platform": platform,
            "n_devices": int(n_devices),
            "memory": mem,
            "peak_memory_bytes": (mem["temp_bytes"] + mem["argument_bytes"]
                                  + mem["output_bytes"]),
        }
        if extra:
            row.update(extra)
        led = ledger if ledger is not None else _xcost.get_ledger()
        if led is not None:
            led.append(row)
        return row
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("memory ledger capture failed: %r", e)
        return None


def memory_rows(ledger=None, model: Optional[str] = None
                ) -> List[Dict[str, Any]]:
    """Every memory row in the ledger (rows with an attached ``memory``
    dict: the dedicated ``label="memory"`` rows AND step rows enriched by
    ``xcost.capture``), optionally filtered by serving model name."""
    led = ledger if ledger is not None else _xcost.get_ledger()
    if led is None:
        return []
    out = []
    for r in led.rows():
        if not isinstance(r.get("memory"), dict):
            continue
        if model is not None and r.get("model") != model:
            continue
        out.append(r)
    return out


def top_executables(n: int = 5, ledger=None) -> List[Dict[str, Any]]:
    """The ``n`` largest executables the ledger knows, by peak bytes —
    latest row per fingerprint wins (stale binds must not double-count)."""
    latest: Dict[Any, Dict[str, Any]] = {}
    for r in memory_rows(ledger=ledger):
        latest[r.get("fingerprint") or id(r)] = r
    rows = sorted(latest.values(),
                  key=lambda r: -(r.get("peak_memory_bytes") or 0))
    return rows[:int(n)]


# ----------------------------------------------------------------- footprints
def model_footprint(cache, model: Optional[str] = None,
                    ledger=None) -> Dict[str, Any]:
    """Estimated resident HBM of one serving model's executor cache.

    Params are counted ONCE (every bucket after the first shares them via
    ``Predictor.reshape``); each bucket then adds its incremental bytes —
    temp + output from this model's memory ledger rows when one was
    recorded, else the analytic padded-batch bytes (flagged
    ``estimated``)."""
    params_bytes = len(getattr(cache, "_param_bytes", b"") or b"")
    feat = tuple(getattr(cache, "feature_shape", ()) or ())
    feat_elems = 1
    for x in feat:
        feat_elems *= int(x)
    by_bucket: Dict[int, Dict[str, Any]] = {}
    for r in memory_rows(ledger=ledger, model=model):
        b = r.get("bucket")
        if b is not None:
            by_bucket[int(b)] = r
    buckets: Dict[str, Dict[str, Any]] = {}
    estimated = False
    total = params_bytes
    for b in getattr(cache, "buckets", ()) or ():
        b = int(b)
        row = by_bucket.get(b)
        batch_bytes = b * feat_elems * 4        # float32 padded batch
        if row:
            mem = row["memory"]
            inc = (int(mem.get("temp_bytes", 0))
                   + int(mem.get("output_bytes", 0)) + batch_bytes)
            src = "ledger"
        else:
            inc = batch_bytes
            src = "estimate"
            estimated = True
        buckets[str(b)] = {"bytes": inc, "source": src}
        total += inc
    return {"model": model, "params_bytes": params_bytes,
            "buckets": buckets, "total_bytes": total,
            "chips": int(getattr(cache, "chips", 1) or 1),
            "estimated": estimated}


def trainer_footprint(trainer) -> Dict[str, Any]:
    """Estimated resident HBM of one trainer — delegates to the trainer's
    own ``footprint()`` when it has one (DataParallelTrainer does), else
    falls back to tree sums over conventional attrs."""
    fp = getattr(trainer, "footprint", None)
    if callable(fp):
        try:
            return fp()
        except Exception as e:
            logger.warning("trainer footprint failed: %r", e)
    return {"params_bytes": tree_bytes(getattr(trainer, "_params", None)),
            "total_bytes": tree_bytes(getattr(trainer, "_params", None))}


def per_chip_bytes(footprint: Dict[str, Any], chips: int) -> int:
    """What ONE chip holds when this footprint serves on ``chips`` chips:
    params are replicated per chip; per-bucket batch/temp bytes split
    row-wise across the chips (the rebind contract)."""
    chips = max(1, int(chips))
    params = int(footprint.get("params_bytes", 0) or 0)
    total = int(footprint.get("total_bytes", 0) or 0)
    return params + (total - params + chips - 1) // chips


# ------------------------------------------------------- placement decisions
def placement_check(footprint: Dict[str, Any], chips: int,
                    device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Would this footprint fit on ``chips`` chips? Returns a verdict dict:
    ``ok`` (True when unbudgeted — refusals need a configured budget),
    ``need_bytes`` (per chip), ``budget_bytes`` (per chip, ballast
    already subtracted), ``reason`` (``no_memory`` when it does not fit)."""
    budget = hbm_budget_bytes(device_kind)
    need = per_chip_bytes(footprint, chips)
    if budget is None:
        return {"ok": True, "need_bytes": need, "budget_bytes": None,
                "reason": None}
    avail = int(budget) - int(pressure()["ballast_bytes"])
    ok = need <= avail
    return {"ok": ok, "need_bytes": need, "budget_bytes": avail,
            "reason": None if ok else "no_memory"}


def fleet_memory_check(assignments: Dict[str, Tuple[Dict[str, Any], int]],
                       device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Check a whole placement: ``assignments`` maps model name ->
    (footprint dict, chip count). Returns ``ok`` plus per-model
    violations — the FleetController refuses a resize/grow whose
    post-state has any."""
    violations = []
    for name, (fp, chips) in assignments.items():
        v = placement_check(fp, chips, device_kind=device_kind)
        if not v["ok"]:
            violations.append({"model": name, "chips": int(chips),
                               "need_bytes": v["need_bytes"],
                               "budget_bytes": v["budget_bytes"]})
    return {"ok": not violations, "violations": violations}


# -------------------------------------------------------------- postmortem
def postmortem_path() -> str:
    d = str(get_env("MXNET_OOM_DIR", "") or "") or "."
    return os.path.join(d, "mxtpu_oom.json")


def blame_table(server=None, trainer=None, ledger=None) -> List[Dict[str, Any]]:
    """Ranked HBM holders, largest first: per-model serving footprints,
    the trainer footprint, registered live sets and chaos ballast."""
    holders: List[Dict[str, Any]] = []
    if server is not None:
        for name, st in getattr(server, "_models", {}).items():
            try:
                fp = model_footprint(st.cache, model=name, ledger=ledger)
                holders.append({"holder": "model:%s" % name,
                                "bytes": int(fp["total_bytes"]),
                                "footprint": fp})
            except Exception:
                continue
    if trainer is not None:
        fp = trainer_footprint(trainer)
        holders.append({"holder": "trainer",
                        "bytes": int(fp.get("total_bytes", 0) or 0),
                        "footprint": fp})
    for name, nbytes in live_set_bytes().items():
        holders.append({"holder": "live:%s" % name, "bytes": int(nbytes)})
    ball = int(pressure()["ballast_bytes"])
    if ball:
        holders.append({"holder": "ballast", "bytes": ball})
    holders.sort(key=lambda h: -h["bytes"])
    return holders


def write_postmortem(context: str, *, exc: Optional[BaseException] = None,
                     server=None, trainer=None, model: Optional[str] = None,
                     path: Optional[str] = None, top_n: int = 5) -> str:
    """Write the flight-recorder-style ``mxtpu_oom.json`` artifact and
    return its path. The artifact must stand alone: everything a human
    needs to answer \"who held the HBM\" without the process that died."""
    from . import tracing as _tracing
    doc: Dict[str, Any] = {
        "version": 1,
        "kind": "mxtpu_oom",
        "time": time.time(),
        "context": context,
        "model": model,
        "exception": repr(exc) if exc is not None else None,
        "trace_id": _tracing.current_trace_id(),
        "budget_bytes": hbm_budget_bytes(),
        "pressure": pressure(),
        "live": poll_hbm(),
        "watermarks": watermark_history(32),
        "blame": blame_table(server=server, trainer=trainer),
        "top_executables": [
            {"mem_label": r.get("mem_label") or r.get("label"),
             "fingerprint": r.get("fingerprint"),
             "model": r.get("model"), "bucket": r.get("bucket"),
             "peak_memory_bytes": r.get("peak_memory_bytes"),
             "memory": r.get("memory")}
            for r in top_executables(top_n)],
    }
    if server is not None:
        ladders = {}
        for name, st in getattr(server, "_models", {}).items():
            try:
                cache = st.cache
                fp = model_footprint(cache, model=name)
                ladders[name] = {
                    "ladder": list(cache.buckets),
                    "resident": cache.compiled_buckets(),
                    "chips": int(getattr(cache, "chips", 1) or 1),
                    "per_bucket_bytes": fp["buckets"],
                    "params_bytes": fp["params_bytes"],
                    "total_bytes": fp["total_bytes"],
                }
            except Exception:
                continue
        doc["buckets"] = ladders
    if trainer is not None:
        doc["trainer_footprint"] = trainer_footprint(trainer)
    out = path or postmortem_path()
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = out + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=repr)
    os.replace(tmp, out)
    top = doc["blame"][0]["holder"] if doc["blame"] else "unknown"
    logger.error("HBM exhausted during %s — postmortem written to %s "
                 "(top holder: %s)", context, out, top)
    return out
