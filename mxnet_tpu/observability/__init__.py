"""mxnet_tpu.observability — unified runtime telemetry.

One metrics model for everything the framework previously measured through
disconnected islands (profiler chrome-trace, Monitor stat queue,
Speedometer log lines, anomaly_stats, the resilience watchdog):

==================  ======================================================
piece                what it gives you
==================  ======================================================
metrics             thread-safe labeled counters / gauges / histograms,
                    JSON + Prometheus text exposition, periodic background
                    exporter (``MXNET_TELEMETRY_EXPORT``)
spans               ``with span("name"):`` / ``@span("name")`` — one timed
                    region feeding BOTH the span histogram and the chrome-
                    trace profiler stream
catalog             every built-in family (trainer step time, kv publish
                    latency, checkpoint save duration, ...), pre-declared
                    so snapshots are schema-stable
flight_recorder     ring buffer of recent step records; dumped to a JSON
                    artifact on watchdog timeout / preemption / unhandled
                    trainer exception (crash forensics)
jit_hooks           jax.monitoring taps: trace/compile counts + compile
                    time (the dynamic retrace truth)
xcost               XLA cost ledger: per-executable FLOPs/bytes/roofline
                    rows persisted append-only (``MXNET_PERF_LEDGER``)
memwatch            HBM memory observability: per-executable memory
                    ledger rows, live ``mxtpu_hbm_*`` accounting with a
                    CPU-synthetic fallback, OOM postmortems
                    (``mxtpu_oom.json`` + typed ``HBMExhausted``) and the
                    per-chip budget math fleet placement consults
                    (``tools/mxmem.py`` is its CLI)
attribution         step-time decomposition + live MFU/device-util gauges
perfwatch           perf-regression watchdog vs bench baselines
                    (library + ``tools/perfwatch.py`` CLI)
tracing             end-to-end request tracing: W3C traceparent contexts,
                    per-request stage-span timelines in a tail-sampled
                    ring, latency-histogram exemplars, SLO burn-rate
                    gauges (``tools/mxtrace.py`` pretty-prints the ring)
tools/mxtop.py      pretty-printer for live or dumped snapshots
                    (``perf`` view: ledger rows + perf gauges)
==================  ======================================================

Everything is host-side: with ``MXNET_TELEMETRY=0`` instrumentation points
no-op and the jitted step's compiled HLO is bitwise identical (guarded by
``tests/test_observability.py``). Docs: ``docs/observability.md``.
"""
from __future__ import annotations

from ..base import get_env
from . import metrics
from . import catalog
from . import spans
from . import flight_recorder
from . import jit_hooks
from . import xcost
from . import memwatch
from . import attribution
from . import perfwatch
from . import tracing
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                      counter, gauge, histogram, enabled, snapshot,
                      render_json, render_prometheus, write_snapshot,
                      start_exporter, stop_exporter)
from .spans import span, active_spans
from .flight_recorder import FlightRecorder, get_recorder, record_step
from .xcost import CostLedger, analyze_cost
from .memwatch import HBMExhausted
from .attribution import StepAttribution
from .perfwatch import PerfWatch
from .tracing import TraceContext, Tracer, SLOTracker, get_tracer

__all__ = ["metrics", "catalog", "spans", "flight_recorder", "jit_hooks",
           "xcost", "memwatch", "attribution", "perfwatch", "tracing",
           "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "enabled", "snapshot",
           "render_json", "render_prometheus", "write_snapshot",
           "start_exporter", "stop_exporter", "span", "active_spans",
           "FlightRecorder", "get_recorder", "record_step",
           "CostLedger", "analyze_cost", "HBMExhausted", "StepAttribution",
           "PerfWatch", "TraceContext", "Tracer", "SLOTracker", "get_tracer"]

# jax.monitoring listeners are cheap (no work between compile events) and
# honor the live MXNET_TELEMETRY switch themselves, so install eagerly —
# the first compile after import is already counted.
jit_hooks.install()

# Exporter autostart: opt-in by env, so `MXNET_TELEMETRY_EXPORT=/run/m.json
# python train.py` needs no code change.
if get_env("MXNET_TELEMETRY_EXPORT", ""):
    start_exporter()
