"""Step-time attribution — where does the training cadence actually go?

A wall-clock step number alone cannot say whether a slow run is
device-bound, host-bound or starving on input. This module decomposes the
steady-state step cadence into host-observable buckets, entirely outside
the jitted program (nothing here can change the compiled HLO, and nothing
ever syncs the device):

=============  ===========================================================
bucket          meaning
=============  ===========================================================
dispatch        time inside the jitted-step call. Under async dispatch
                this is enqueue cost — until the device queue fills, at
                which point XLA's backpressure blocks here and the bucket
                converges to true device compute time.
h2d_transfer    time blocked in ``jax.device_put`` staging the batch.
host_prep       the rest of ``step()``'s body (unwrap, rng fold-in).
feed_stall      time the data pipeline blocked the consumer in ``next()``
                between our steps — the delta of the PR-4
                ``mxtpu_io_feed_stall_ms`` histogram, attributed to the
                step that waited for it.
host_other      remaining time between the previous step's return and this
                step's entry (user code, metric reads, logging).
=============  ===========================================================

Published as rolling means into ``mxtpu_step_breakdown_ms{bucket=}``, plus:

- ``mxtpu_device_util`` — a lag-1 saturation probe: the fraction of recent
  steps whose *previous* result was still not ready (``is_ready()``, a
  non-blocking host call) when the next dispatch completed. ~1.0 means the
  device never drains (compute-bound pipeline); ~0.0 means the device idles
  waiting on the host.
- ``mxtpu_mfu`` — live model-FLOPs utilization: the executable's
  cost-ledger FLOPs (``xcost``) over mean cadence x peak FLOP/s x chips.
  MFU stops being a bench-day artifact and becomes a per-run gauge.

Enabled by default whenever telemetry is on (``MXNET_PERF_ATTRIBUTION=0``
or ``DataParallelTrainer(step_attribution=False)`` turns it off — mxlint
MXL-T210 flags that pairing, because a hot loop with telemetry but no
attribution is exactly the blind spot this module exists to close).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional

from ..base import get_env, register_config
from . import catalog as _catalog
from . import metrics as _metrics
from . import xcost as _xcost

__all__ = ["BUCKETS", "attribution_config", "StepAttribution"]

register_config("MXNET_PERF_ATTRIBUTION", True, bool,
                "Default for DataParallelTrainer step-time attribution "
                "(mxtpu_step_breakdown_ms / mxtpu_device_util / mxtpu_mfu "
                "gauges). Host-side only; 0 disables the bookkeeping "
                "(mxlint MXL-T210 flags telemetry-on/attribution-off).")

BUCKETS = ("dispatch", "h2d_transfer", "host_prep", "feed_stall",
           "host_other")

# Process-wide claim cursor over the io feed-stall histogram sum: each new
# stall millisecond is attributed to exactly ONE attribution instance (the
# next one to observe a step), so two live trainers never double-count the
# same stall. The cursor starts at the current total the first time any
# instance claims, so pre-training stalls are charged to nobody. Stalls
# from an unrelated iterator (e.g. an eval loop) still land on whichever
# trainer steps next — the gauge is a per-process attribution, exact only
# in the common one-training-loop case (documented in
# docs/observability.md).
_stall_lock = threading.Lock()
_stall_claimed: Optional[float] = None


def _claim_feed_stall_ms() -> float:
    global _stall_claimed
    _, s = _catalog.IO_FEED_STALL_MS.totals()
    with _stall_lock:
        if _stall_claimed is None:
            _stall_claimed = s
            return 0.0
        d = max(0.0, s - _stall_claimed)
        _stall_claimed = s
        return d


def attribution_config(arg) -> Optional[Dict[str, Any]]:
    """Normalize the ``step_attribution`` ctor arg. None = the
    MXNET_PERF_ATTRIBUTION env default; any explicit falsy spelling
    (False/0/{}) = off; True/dict = on, dict may override ``window``
    (rolling steps the published means average over)."""
    if arg is None:
        if not get_env("MXNET_PERF_ATTRIBUTION", True):
            return None
        arg = True
    if not arg:
        return None
    cfg = dict(arg) if isinstance(arg, dict) else {}
    return {"window": max(2, int(cfg.get("window", 32)))}


class StepAttribution:
    """Rolling-window step decomposition for one trainer.

    ``observe()`` is called by ``DataParallelTrainer.step`` after each
    dispatch with the step's own timing marks; everything else (feed-stall
    delta, previous-loss readiness, gauge publication) happens here. All
    reads are non-blocking host calls — the device is never synced.
    """

    def __init__(self, cfg: Dict[str, Any], device_kind: Optional[str] = None,
                 n_devices: int = 1):
        self.window = int(cfg["window"])
        self.device_kind = device_kind
        self.n_devices = max(1, int(n_devices))
        self._win: deque = deque(maxlen=self.window)       # bucket tuples
        self._cadence: deque = deque(maxlen=self.window)   # seconds
        self._busy: deque = deque(maxlen=self.window)      # bools
        self._prev_entry: Optional[float] = None
        self._prev_exit: Optional[float] = None
        self._prev_loss = None
        self.steps = 0

    # ------------------------------------------------------------- feeding
    def _feed_stall_delta_ms(self) -> float:
        """New io feed-stall milliseconds since any attribution's last
        claim (whole-family sum of ``mxtpu_io_feed_stall_ms`` — the PR-4
        instrumentation point in ResilientDataIter/prefetchers — behind the
        shared claim cursor so concurrent trainers never double-count)."""
        return _claim_feed_stall_ms()

    def observe(self, t_entry: float, t_exit: float, *, transfer_ms: float,
                dispatch_ms: float, loss_ref=None,
                flops_per_step: Optional[float] = None) -> None:
        """Record one step: perf_counter entry/exit marks plus the measured
        transfer and dispatch segments; ``loss_ref`` is the step's async
        device scalar (kept one step, polled non-blocking, never synced)."""
        total_ms = max(0.0, (t_exit - t_entry) * 1e3)
        host_prep = max(0.0, total_ms - transfer_ms - dispatch_ms)
        feed = self._feed_stall_delta_ms()
        if self._prev_exit is not None:
            between = max(0.0, (t_entry - self._prev_exit) * 1e3 - feed)
        else:
            between = 0.0
        busy = None
        prev = self._prev_loss
        if prev is not None and hasattr(prev, "is_ready"):
            try:
                busy = not prev.is_ready()
            except Exception:       # deleted buffer on a retry path
                busy = None
        self._prev_loss = loss_ref
        if self._prev_entry is not None:
            self._cadence.append(max(1e-9, t_entry - self._prev_entry))
        self._prev_entry = t_entry
        self._prev_exit = t_exit
        self._win.append((dispatch_ms, transfer_ms, host_prep, feed, between))
        if busy is not None:
            self._busy.append(busy)
        self.steps += 1
        self._publish(flops_per_step)

    # ----------------------------------------------------------- publishing
    def _means(self) -> Dict[str, float]:
        n = len(self._win)
        if not n:
            return {b: 0.0 for b in BUCKETS}
        sums = [0.0] * len(BUCKETS)
        for rec in self._win:
            for i, v in enumerate(rec):
                sums[i] += v
        return {b: sums[i] / n for i, b in enumerate(BUCKETS)}

    def _publish(self, flops_per_step: Optional[float]) -> None:
        for bucket, mean in self._means().items():
            _catalog.STEP_BREAKDOWN.set(mean, bucket=bucket)
        if self._busy:
            _catalog.DEVICE_UTIL.set(
                sum(1.0 for b in self._busy if b) / len(self._busy))
        mfu = self.mfu(flops_per_step)
        if mfu is not None:
            _catalog.MFU.set(mfu)

    def mfu(self, flops_per_step: Optional[float]) -> Optional[float]:
        """Model-FLOPs utilization over the window, or None when the flops
        (cost ledger) or the device peak (table/override) is unknown."""
        if not flops_per_step or not self._cadence:
            return None
        peak = _xcost.peak_flops(self.device_kind)
        if not peak:
            return None
        cad = sum(self._cadence) / len(self._cadence)
        return flops_per_step / (cad * peak * self.n_devices)

    def stats(self) -> Dict[str, Any]:
        """Point-in-time view of the window (for tools/tests): bucket
        means, device_util, mean cadence ms, steps observed."""
        out: Dict[str, Any] = {"buckets_ms": self._means(),
                               "steps": self.steps}
        out["device_util"] = (
            sum(1.0 for b in self._busy if b) / len(self._busy)
            if self._busy else None)
        out["cadence_ms"] = (
            sum(self._cadence) / len(self._cadence) * 1e3
            if self._cadence else None)
        return out
