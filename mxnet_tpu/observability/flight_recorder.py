"""Flight recorder — the last N step records, dumped when the run dies.

A hung collective, a preemption, or an unhandled trainer exception leaves
nothing behind but a stack trace; the question that actually matters —
*what was the run doing in the steps leading up to it* — needs data that was
being recorded BEFORE the failure. The flight recorder is a bounded ring
buffer of per-step records (step index, loss, wall step time, active spans)
appended by the instrumented trainers at effectively zero cost:

- no host sync: the loss is stored as whatever reference the trainer already
  holds (an async XLA scalar); it is resolved to a float only at dump time,
  on the crash path, where a blocking read costs nothing that matters.
- bounded memory: a ``deque(maxlen=N)``; N scalars worth of device buffers
  pinned at most (outputs, never donated inputs).

Dump triggers (all write the same artifact):

- :class:`~mxnet_tpu.resilience.watchdog.Watchdog` timeout — the dump path
  also appends the recorder tail to the thread-stack dump on stderr;
- preemption (``ResilientTrainer``'s final save before raising Preempted);
- any unhandled exception escaping ``ResilientTrainer.step``.

Artifact schema (``docs/observability.md``): ``{"version": 1, "reason":
str, "time": float, "pid": int, "extra": {...}, "records": [{"step": int,
"time": float, "loss": float|None, "step_ms": float|None, "spans": [...],
...}]}`` — newest record last.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.lockwatch import make_lock
from ..base import get_env, logger, register_config
from . import metrics as _metrics
from . import spans as _spans

__all__ = ["FlightRecorder", "get_recorder", "record_step", "dump",
           "tail_lines"]

register_config("MXNET_TELEMETRY_FLIGHT_RECORDS", 256, int,
                "Flight-recorder ring size (per-step records kept for crash "
                "forensics). 0 disables the recorder.")
register_config("MXNET_TELEMETRY_FLIGHT_PATH", "mxtpu_flight_recorder.json",
                str, "Where crash-triggered flight-recorder dumps land.")


class FlightRecorder:
    """Bounded ring of per-step records with a crash-dump serializer."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(get_env("MXNET_TELEMETRY_FLIGHT_RECORDS", 256))
        self.capacity = max(0, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity or 1)
        self._lock = make_lock("observability.flight_recorder.FlightRecorder._lock")

    @property
    def enabled(self) -> bool:
        return self.capacity > 0 and _metrics.enabled()

    def record(self, step: int, loss: Any = None,
               step_ms: Optional[float] = None, **extra) -> None:
        """Append one step record. ``loss`` may be a live device scalar —
        it is NOT synced here; resolution happens at dump time."""
        if not self.enabled:
            return
        rec = {"step": int(step), "time": time.time(), "loss": loss,
               "step_ms": step_ms, "spans": list(_spans.active_spans())}
        try:
            # cross-link to the request-trace ring: a record made under
            # tracing.use(ctx) carries the trace_id, so a watchdog dump
            # resolves straight to a timeline in tools/mxtrace.py
            from . import tracing as _tracing
            tid = _tracing.current_trace_id()
            if tid:
                rec["trace_id"] = tid
        except Exception:
            pass
        if extra:
            rec.update(extra)
        with self._lock:
            self._ring.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------- readout
    def records(self) -> List[Dict[str, Any]]:
        """Resolved copies of every record, oldest first. Lazy values (device
        scalars) are materialized here; a deleted/unreadable buffer becomes
        None rather than failing the dump."""
        with self._lock:
            raw = list(self._ring)
        return [self._resolve(r) for r in raw]

    def tail(self, n: int = 8) -> List[Dict[str, Any]]:
        with self._lock:
            raw = list(self._ring)[-n:]
        return [self._resolve(r) for r in raw]

    @staticmethod
    def _resolve(rec: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rec)
        loss = out.get("loss")
        if loss is not None and not isinstance(loss, (int, float)):
            try:
                # NEVER block here: the dump runs on crash paths — on a
                # watchdog timeout the device program is by definition
                # stuck, and a float() of a value queued behind it would
                # hang the watchdog thread itself. An unready value reads
                # as None ('not resolved before the crash' is signal too).
                if hasattr(loss, "is_ready") and not loss.is_ready():
                    out["loss"] = None
                else:
                    out["loss"] = float(loss)
            except Exception:
                out["loss"] = None
        return out

    # ---------------------------------------------------------------- dump
    def dump(self, path: Optional[str] = None, reason: str = "",
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the artifact; returns its path (None when the recorder is
        empty AND disabled — an empty artifact from an enabled run is still
        written: 'recorder was on but nothing completed' is itself signal).
        Never raises: this runs on crash paths."""
        if self.capacity <= 0 or not _metrics.enabled():
            return None
        try:
            path = path or str(get_env("MXNET_TELEMETRY_FLIGHT_PATH",
                                       "mxtpu_flight_recorder.json"))
            doc = {"version": 1, "reason": reason, "time": time.time(),
                   "pid": os.getpid(), "extra": extra or {},
                   "records": self.records()}
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True, default=_json_default)
            os.replace(tmp, path)
            return path
        except Exception as e:  # pragma: no cover - crash-path best effort
            try:
                logger.warning("flight recorder dump failed: %r", e)
            except Exception:
                pass
            return None

    def tail_lines(self, n: int = 8) -> List[str]:
        """Human-oriented one-liners of the newest records (appended to the
        watchdog's thread-stack dump)."""
        out = []
        for r in self.tail(n):
            loss = r.get("loss")
            ms = r.get("step_ms")
            out.append("step %6d  loss %-12s step_ms %-10s spans %s" % (
                r.get("step", -1),
                ("%.6f" % loss) if isinstance(loss, float) else "n/a",
                ("%.1f" % ms) if isinstance(ms, (int, float)) else "n/a",
                ",".join(r.get("spans") or ()) or "-"))
        return out


def _json_default(o):
    try:
        return float(o)
    except Exception:
        return repr(o)


# ---- process-wide default recorder -----------------------------------------
_default_lock = threading.Lock()
_default: Optional[FlightRecorder] = None


def get_recorder() -> FlightRecorder:
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def record_step(step: int, loss: Any = None,
                step_ms: Optional[float] = None, **extra) -> None:
    get_recorder().record(step, loss=loss, step_ms=step_ms, **extra)


def dump(reason: str = "", path: Optional[str] = None,
         extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    return get_recorder().dump(path=path, reason=reason, extra=extra)


def tail_lines(n: int = 8) -> List[str]:
    return get_recorder().tail_lines(n)
