"""Low-overhead metrics registry — counters, gauges, histograms with labels.

The reference framework measured itself through three disconnected channels
(profiler chrome-trace, Monitor stat queue, Speedometer log lines); this
module is the shared metrics model they all publish into. Design constraints,
in order:

1. **Never enter the XLA trace.** Every observation is host-side Python on
   concrete floats; instrumented code gates on :func:`enabled` so a disabled
   run does no registry work at all and the jitted step's HLO is bitwise
   unchanged (tier-1 guards this).
2. **Cheap when on.** An observation is one lock acquire + a dict update;
   label series are keyed by a pre-sorted tuple. No string formatting until
   exposition.
3. **Exposition-agnostic.** ``snapshot()`` is the canonical plain-dict form;
   ``render_json``/``render_prometheus`` serialize it. A background exporter
   thread (``MXNET_TELEMETRY_EXPORT``) writes either format periodically so
   a sidecar/scraper can watch a training run without touching the loop.

Env knobs (registered in ``base.config``): ``MXNET_TELEMETRY`` master switch,
``MXNET_TELEMETRY_EXPORT`` snapshot path (``.prom``/``.txt`` → Prometheus
text format, else JSON), ``MXNET_TELEMETRY_EXPORT_INTERVAL`` seconds.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..analysis.lockwatch import make_rlock
from ..base import MXNetError, get_env, logger, register_config

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "enabled", "counter", "gauge", "histogram", "snapshot",
           "render_json", "render_prometheus", "write_snapshot",
           "start_exporter", "stop_exporter", "DEFAULT_BUCKETS_MS"]

register_config("MXNET_TELEMETRY", True, bool,
                "Master switch for the runtime telemetry registry. 0 turns "
                "every instrumentation point into a no-op; the jitted step's "
                "HLO is identical either way (telemetry is host-side only).")
register_config("MXNET_TELEMETRY_EXPORT", "", str,
                "Path the background exporter periodically writes metric "
                "snapshots to (.prom/.txt = Prometheus text format, "
                "anything else = JSON). Empty = no exporter thread.")
register_config("MXNET_TELEMETRY_EXPORT_INTERVAL", 10.0, float,
                "Seconds between background exporter snapshots.")

# Histogram default: latency-in-ms oriented, exponential-ish. +Inf is
# implicit — every histogram gets a catch-all bucket.
DEFAULT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def enabled() -> bool:
    """Live read of the master switch (env wins over programmatic set) —
    cheap enough for per-step gates, and monkeypatch/setenv takes effect
    immediately, no process restart."""
    return bool(get_env("MXNET_TELEMETRY", True))


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Shared series bookkeeping; subclasses define the per-series value."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry=None):
        self.name = name
        self.help = help
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        self._lock = threading.Lock() if registry is None else registry._lock

    # -- exposition ---------------------------------------------------------
    def series(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._series.items())
        return [dict(labels=dict(k), **self._series_dict(v))
                for k, v in items]

    def _series_dict(self, value) -> Dict[str, Any]:
        return {"value": value}


class Counter(_Metric):
    """Monotonically increasing count (events, bytes, retries)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Point-in-time value (samples/sec, queue depth, last norm)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = value

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._series.get(_label_key(labels))


class Histogram(_Metric):
    """Bucketed distribution (latencies, sizes): cumulative-style buckets at
    exposition, per-bucket counts internally."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS_MS, registry=None):
        super().__init__(name, help, registry=registry)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise MXNetError(f"histogram {name!r} needs at least one bucket")
        self.buckets = bs

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation. ``exemplar`` optionally attaches a
        trace_id to the bucket the value lands in (OpenMetrics-style
        exemplars: a bad percentile links to a concrete request
        timeline in the trace ring — docs/observability.md,
        'Request tracing')."""
        key = _label_key(labels)
        value = float(value)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"counts": [0] * (len(self.buckets) + 1),
                      "sum": 0.0, "count": 0, "max": -math.inf}
                self._series[key] = st
            i = 0
            for b in self.buckets:
                if value <= b:
                    break
                i += 1
            st["counts"][i] += 1
            st["sum"] += value
            st["count"] += 1
            if value > st["max"]:
                st["max"] = value
            if exemplar is not None:
                st.setdefault("exemplars", {})[i] = {
                    "value": value, "trace_id": str(exemplar),
                    "time": time.time()}

    def _bucket_label(self, i: int) -> str:
        if i >= len(self.buckets):
            return "+Inf"
        b = self.buckets[i]
        return repr(b) if b != int(b) else str(int(b))

    def _series_dict(self, st) -> Dict[str, Any]:
        # cumulative counts per upper bound (prometheus 'le' semantics);
        # keys come from _bucket_label so they always match the
        # exemplars dict a reader correlates them with
        cum, total = {}, 0
        for i, c in enumerate(st["counts"][:-1]):
            total += c
            cum[self._bucket_label(i)] = total
        cum["+Inf"] = total + st["counts"][-1]
        out = {"sum": st["sum"], "count": st["count"],
               "max": (st["max"] if st["count"] else 0.0), "buckets": cum}
        ex = st.get("exemplars")
        if ex:
            out["exemplars"] = {self._bucket_label(i): dict(e)
                                for i, e in sorted(ex.items())}
        return out

    def exemplars(self, **labels) -> Dict[str, Dict[str, Any]]:
        """Per-bucket exemplars of one label series: ``{bucket_le:
        {"value", "trace_id", "time"}}`` (the newest observation that
        carried an exemplar per bucket)."""
        with self._lock:
            st = self._series.get(_label_key(labels))
            if not st or "exemplars" not in st:
                return {}
            return {self._bucket_label(i): dict(e)
                    for i, e in sorted(st["exemplars"].items())}

    def count(self, **labels) -> int:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return st["count"] if st else 0

    def totals(self) -> Tuple[int, float]:
        """(count, sum) aggregated across every label series — the cheap
        whole-family read the step-attribution layer diffs per step."""
        with self._lock:
            return (sum(st["count"] for st in self._series.values()),
                    sum(st["sum"] for st in self._series.values()))


class MetricsRegistry:
    """Thread-safe name → metric map with get-or-create semantics: a metric
    family is declared once (module import time at the instrumentation
    site or in ``catalog.py``) and re-requests return the same object, so
    declaration order never matters. Re-declaring under a different type is
    a programming error and raises."""

    def __init__(self):
        self._lock = make_rlock("observability.metrics.MetricsRegistry._lock")
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise MXNetError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"cannot re-register as {cls.kind}")
                return m
            m = cls(name, help, registry=self, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS_MS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def clear_values(self) -> None:
        """Reset every series (families stay declared) — test isolation."""
        with self._lock:
            for m in self._metrics.values():
                m._series.clear()

    # ---------------------------------------------------------- exposition
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            metrics = dict(self._metrics)
        return {
            "version": 1,
            "time": time.time(),
            "pid": os.getpid(),
            "metrics": {
                name: {"type": m.kind, "help": m.help,
                       **({"buckets": list(m.buckets)}
                          if isinstance(m, Histogram) else {}),
                       "series": m.series()}
                for name, m in sorted(metrics.items())},
        }

    def render_json(self, indent: Optional[int] = 1) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        snap = self.snapshot()
        for name, m in snap["metrics"].items():
            if m["help"]:
                out.append(f"# HELP {name} {_esc_help(m['help'])}")
            out.append(f"# TYPE {name} {m['type']}")
            for s in m["series"]:
                lbl = s["labels"]
                if m["type"] == "histogram":
                    for le, c in s["buckets"].items():
                        out.append("%s_bucket%s %s" % (
                            name, _fmt_labels(dict(lbl, le=le)), c))
                    out.append("%s_sum%s %s" % (name, _fmt_labels(lbl),
                                                _fmt_val(s["sum"])))
                    out.append("%s_count%s %s" % (name, _fmt_labels(lbl),
                                                  s["count"]))
                else:
                    out.append("%s%s %s" % (name, _fmt_labels(lbl),
                                            _fmt_val(s["value"])))
        return "\n".join(out) + "\n"


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join('%s="%s"' % (k, _esc_label(str(v)))
                     for k, v in sorted(labels.items()))
    return "{%s}" % inner


def _fmt_val(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# ---- default registry + module-level conveniences --------------------------
REGISTRY = MetricsRegistry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
render_json = REGISTRY.render_json
render_prometheus = REGISTRY.render_prometheus


def write_snapshot(path: str, registry: Optional[MetricsRegistry] = None) -> str:
    """Write one snapshot to ``path`` (atomic rename; format by extension:
    .prom/.txt → Prometheus text, else JSON). Returns the path."""
    reg = registry or REGISTRY
    text = (reg.render_prometheus()
            if path.endswith((".prom", ".txt")) else reg.render_json())
    # temp name must be unique per WRITER, not just per process: the
    # exporter thread and a direct write_snapshot call may race on the
    # same path (e.g. the final-on-stop write vs the periodic one)
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), threading.get_ident())
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


# ---- background exporter ----------------------------------------------------
_exporter_lock = threading.Lock()
_exporter_stop: Optional[threading.Event] = None
_exporter_thread: Optional[threading.Thread] = None
_atexit_registered = False


def start_exporter(path: Optional[str] = None,
                   interval: Optional[float] = None) -> bool:
    """Start the periodic snapshot writer (idempotent). Returns True if a
    thread is running after the call. Arguments default to the
    MXNET_TELEMETRY_EXPORT / _EXPORT_INTERVAL knobs; no path = no-op.
    The MXNET_TELEMETRY master switch wins: disabled telemetry means no
    exporter thread and no files on disk."""
    global _exporter_stop, _exporter_thread
    if not enabled():
        return False
    path = path or str(get_env("MXNET_TELEMETRY_EXPORT", "") or "")
    if not path:
        return False
    interval = float(interval if interval is not None
                     else get_env("MXNET_TELEMETRY_EXPORT_INTERVAL", 10.0))
    with _exporter_lock:
        if _exporter_thread is not None and _exporter_thread.is_alive():
            return True
        stop = threading.Event()

        def loop():
            while not stop.wait(interval):
                try:
                    write_snapshot(path)
                except Exception as e:  # never kill the host program
                    logger.warning("telemetry exporter write failed: %r", e)
            try:       # final snapshot on clean stop so short runs export
                write_snapshot(path)
            except Exception:
                pass

        t = threading.Thread(target=loop, daemon=True,
                             name="mxtpu-telemetry-exporter")
        t.start()
        _exporter_stop, _exporter_thread = stop, t
        # a daemon thread dies silently at interpreter exit — without this
        # hook a run shorter than the interval would export NOTHING, and
        # any run would lose its final partial interval
        global _atexit_registered
        if not _atexit_registered:
            import atexit
            atexit.register(stop_exporter)
            _atexit_registered = True
        return True


def stop_exporter() -> None:
    """Stop the exporter thread (it writes one final snapshot on the way
    out, so even a run shorter than the interval exports something)."""
    global _exporter_stop, _exporter_thread
    with _exporter_lock:
        stop, t = _exporter_stop, _exporter_thread
        _exporter_stop = _exporter_thread = None
    if stop is None:
        return
    stop.set()
    if t is not None:
        t.join(timeout=2.0)
