"""Perf-regression watchdog — compare live/fresh perf facts to baselines.

The bench history (``bench_cache.json``, ``BENCH_*.json``) is the repo's
measured ground truth; this module turns it into an *enforced* floor
instead of a number nobody re-reads. Three inputs normalize into one
comparable shape:

- a **bench row** (``{"metric": ..., "value": ...}``) → throughput, mfu,
  flops_per_step;
- a **telemetry snapshot** (``{"metrics": {...}}``) → the live
  ``mxtpu_mfu`` / ``mxtpu_trainer_samples_per_sec`` gauges of a running
  or finished run;
- a **cost-ledger row / JSONL ledger** (``xcost``) → flops_per_step and
  the roof times (a fatter step program is a regression before a single
  wall-clock second is measured).

:func:`compare` checks every metric present on BOTH sides against a
per-metric threshold (percent), honoring direction (throughput/mfu: lower
is worse; flops/step-time: higher is worse). :class:`PerfWatch` attaches
the same comparison to a live run (``ResilientTrainer(perfwatch=...)``):
every ``check_every`` steps it reads the live gauges, and a regression
logs a loud warning + ``mxtpu_perf_regressions_total{metric=}`` — warn,
never kill: a perf regression is a bug, not an emergency stop.

CLI: ``tools/perfwatch.py`` (mxlint exit convention — 0 pass, 1
regression, 2 missing/unloadable artifact).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..base import get_env, logger, register_config
from . import catalog as _catalog
from . import metrics as _metrics

__all__ = ["METRIC_DIRECTIONS", "DEFAULT_THRESHOLD_PCT", "normalize",
           "load_artifact", "compare", "PerfWatch"]

register_config("MXNET_PERF_BASELINE", "", str,
                "Default baseline artifact for the perf watchdog (a bench "
                "row / BENCH_*.json / ledger row). Empty = the repo's "
                "bench_cache.json.")

# metric -> +1 (higher is better) / -1 (lower is better)
METRIC_DIRECTIONS: Dict[str, int] = {
    "throughput": +1,          # img/s/chip from a bench row
    "mfu": +1,
    "samples_per_sec": +1,     # live trainer gauge (global, not per-chip)
    "flops_per_step": -1,      # a fatter compiled step is a regression
    "step_ms": -1,
    "qps": +1,                 # serving ledger row (label="serving")
    "p50_ms": -1,              # serving accepted-request latency
    "p99_ms": -1,
    "int8_ms": -1,             # quant ledger row (label="quant")
    "f32_ms": -1,
    "int8_vs_f32": +1,         # int8 speedup eroding is a regression
    "int8_acc": +1,            # and so is int8 accuracy drifting down
    "slo_burn_rate": -1,       # serving SLO error-budget burn (max over
                               # model/window series of mxtpu_slo_burn_rate)
    "degraded_rung": -1,       # self-healing ladder position (max over
                               # models of mxtpu_serve_degraded_rung):
                               # any rung above 0 is degraded service
    "budget_denied": -1,       # retry/hedge duplicates refused by the
                               # retry budget (sum over model/kind of
                               # mxtpu_retry_budget_denied_total)
    "peak_bytes": -1,          # memory ledger row (label="memory"): a
                               # fatter executable is a regression
    "footprint_bytes": -1,     # estimated resident bytes/chip (tuner
                               # trial / memwatch footprint)
    "rollout_agreement": +1,   # shadow top-1 agreement (worst model of
                               # mxtpu_rollout_shadow_agreement, or a
                               # loadgen --during-rollout ledger row):
                               # canary answers drifting from the
                               # incumbent is a regression
    "rollout_rollbacks": -1,   # sum over reasons of
                               # mxtpu_rollout_rollbacks_total: gate
                               # rollbacks trending up is a regression
}

DEFAULT_THRESHOLD_PCT = 10.0


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return str(get_env("MXNET_PERF_BASELINE", "") or
               os.path.join(_repo_root(), "bench_cache.json"))


def normalize(doc: Any, source: str = "") -> Optional[Dict[str, Any]]:
    """Map any supported artifact to ``{"metrics": {name: value}, "kind",
    "source"}`` — or None when the document is not one of them."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        # BENCH_rNN.json wrapper: the driver's parsed final row
        return normalize(doc["parsed"], source=source)
    if "metrics" in doc and isinstance(doc["metrics"], dict):
        vals: Dict[str, float] = {}
        fams = doc["metrics"]

        def gauge(name):
            m = fams.get(name) or {}
            for s in m.get("series", []):
                if not s.get("labels"):
                    return s.get("value")
            return None

        mfu = gauge("mxtpu_mfu")
        sps = gauge("mxtpu_trainer_samples_per_sec")
        if mfu is not None:
            vals["mfu"] = float(mfu)
        if sps is not None:
            vals["samples_per_sec"] = float(sps)
        # SLO burn: worst series wins (labeled model=/window=, so the
        # unlabeled-gauge helper above never sees it)
        burn = None
        for s in (fams.get("mxtpu_slo_burn_rate") or {}).get("series", []):
            v = s.get("value")
            if v is not None:
                burn = float(v) if burn is None else max(burn, float(v))
        if burn is not None:
            vals["slo_burn_rate"] = burn
        # degraded rung: worst model wins (labeled model=)
        rung = None
        for s in (fams.get("mxtpu_serve_degraded_rung") or {}) \
                .get("series", []):
            v = s.get("value")
            if v is not None:
                rung = float(v) if rung is None else max(rung, float(v))
        if rung is not None:
            vals["degraded_rung"] = rung
        # budget denials: total duplicate work refused (model=/kind=)
        denied = None
        for s in (fams.get("mxtpu_retry_budget_denied_total") or {}) \
                .get("series", []):
            v = s.get("value")
            if v is not None:
                denied = (denied or 0.0) + float(v)
        if denied is not None:
            vals["budget_denied"] = denied
        # rollout gate health: worst model's shadow agreement (labeled
        # model=, up-is-good so the MIN is the worst), total rollbacks
        agree = None
        for s in (fams.get("mxtpu_rollout_shadow_agreement") or {}) \
                .get("series", []):
            v = s.get("value")
            if v is not None:
                agree = float(v) if agree is None else min(agree, float(v))
        if agree is not None:
            vals["rollout_agreement"] = agree
        rb = None
        for s in (fams.get("mxtpu_rollout_rollbacks_total") or {}) \
                .get("series", []):
            v = s.get("value")
            if v is not None:
                rb = (rb or 0.0) + float(v)
        if rb is not None:
            vals["rollout_rollbacks"] = rb
        return {"kind": "snapshot", "source": source, "metrics": vals}
    if "metric" in doc and "value" in doc:
        vals = {"throughput": float(doc["value"])}
        if doc.get("mfu") is not None:
            vals["mfu"] = float(doc["mfu"])
        if doc.get("flops_per_step") is not None:
            vals["flops_per_step"] = float(doc["flops_per_step"])
        return {"kind": "bench_row", "source": source, "metrics": vals,
                "provenance": doc.get("provenance"),
                "unit": doc.get("unit")}
    if doc.get("label") == "serving" and (
            doc.get("qps") is not None or doc.get("p99_ms") is not None):
        # serving ledger row (serving/load.py ledger_row): qps up-is-good,
        # accepted-latency percentiles down-is-good
        vals = {}
        for k in ("qps", "p50_ms", "p99_ms"):
            if doc.get(k) is not None:
                vals[k] = float(doc[k])
        ro = doc.get("rollout")
        if isinstance(ro, dict) and ro.get("agreement") is not None:
            # loadgen --during-rollout evidence riding the serving row
            vals["rollout_agreement"] = float(ro["agreement"])
        return {"kind": "serving_row", "source": source, "metrics": vals,
                "model": doc.get("model"),
                "provenance": doc.get("provenance")}
    if doc.get("label") == "fleet" and doc.get("qps") is not None:
        # mixed-tenant fleet ledger row (serving/load.py fleet_row):
        # aggregate qps plus bracketed per-tenant metrics — `p99_ms[a]`
        # compares with `p99_ms`'s direction (down-is-good), so tenants
        # come and go without touching METRIC_DIRECTIONS
        vals = {}
        for k, v in doc.items():
            base_name = k.split("[", 1)[0]
            if base_name in METRIC_DIRECTIONS and v is not None \
                    and isinstance(v, (int, float)):
                vals[k] = float(v)
        return {"kind": "fleet_row", "source": source, "metrics": vals,
                "tenants": doc.get("tenants"),
                "provenance": doc.get("provenance")}
    if doc.get("label") == "quant" and (
            doc.get("int8_ms") is not None or doc.get("f32_ms") is not None):
        # quantization ledger row (quant.compare_latency / bench.py int8
        # diagnostic): latencies down-is-good, speedup and int8 accuracy
        # up-is-good — int8 regressions guard exactly like serving ones
        vals = {}
        for k in ("int8_ms", "f32_ms", "int8_vs_f32", "int8_acc"):
            if doc.get(k) is not None:
                vals[k] = float(doc[k])
        return {"kind": "quant_row", "source": source, "metrics": vals,
                "model": doc.get("model"),
                "provenance": doc.get("provenance")}
    if doc.get("label") == "memory" and isinstance(doc.get("memory"), dict):
        # memwatch memory ledger row: per-executable byte accounting —
        # peak down-is-good, so a step/bucket growing its HBM appetite
        # guards exactly like a latency regression
        vals = {}
        if doc.get("peak_memory_bytes") is not None:
            vals["peak_bytes"] = float(doc["peak_memory_bytes"])
        return {"kind": "memory_row", "source": source, "metrics": vals,
                "model": doc.get("model"), "bucket": doc.get("bucket"),
                "mem_label": doc.get("mem_label"),
                "provenance": doc.get("provenance")}
    if "roofline" in doc or "arithmetic_intensity" in doc:
        vals = {}
        if doc.get("flops") is not None:
            vals["flops_per_step"] = float(doc["flops"])
        if doc.get("optimal_ms_compute") is not None:
            vals["step_ms"] = float(doc["optimal_ms_compute"])
        # measured rows (bench windows, tuner trials) carry wall-clock
        # facts next to the compile-time ones — those win over the
        # optimal-roof step time and make the row a full baseline
        # (throughput/mfu/step_ms), e.g. `mxtune --emit-best` output
        if doc.get("measured_step_ms") is not None:
            vals["step_ms"] = float(doc["measured_step_ms"])
        if doc.get("throughput_img_s_per_chip") is not None:
            vals["throughput"] = float(doc["throughput_img_s_per_chip"])
        if doc.get("mfu") is not None:
            vals["mfu"] = float(doc["mfu"])
        if doc.get("footprint_bytes") is not None:
            # tuner trial rows carry the estimated resident bytes/chip:
            # a config whose memory appetite grew guards like step_ms
            vals["footprint_bytes"] = float(doc["footprint_bytes"])
        return {"kind": "ledger_row", "source": source, "metrics": vals,
                "roofline": doc.get("roofline"),
                "provenance": doc.get("provenance")}
    return None


def load_artifact(path: str) -> Tuple[Optional[Dict[str, Any]], str]:
    """Load + normalize one artifact file. JSONL ledgers take their LAST
    row (the freshest executable). Returns (normalized, error) — exactly
    one of the two is truthy."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return None, "cannot read %s: %s" % (path, e)
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        # JSON-lines ledger: last parseable row wins
        for ln in reversed(text.splitlines()):
            ln = ln.strip()
            if not ln:
                continue
            try:
                doc = json.loads(ln)
                break
            except ValueError:
                continue
    if doc is None:
        return None, "%s is not JSON or JSON-lines" % path
    norm = normalize(doc, source=path)
    if norm is None:
        return None, ("%s is not a bench row, telemetry snapshot or cost-"
                      "ledger row" % path)
    return norm, ""


def compare(current: Dict[str, Any], baseline: Dict[str, Any],
            thresholds: Optional[Dict[str, float]] = None,
            default_pct: float = DEFAULT_THRESHOLD_PCT) -> Dict[str, Any]:
    """Check every metric present on both sides. Returns ``{"status":
    "ok"|"regression"|"incomparable", "checks": [...]}`` where each check
    carries metric, baseline, current, delta_pct (signed, current vs
    baseline) and regressed."""
    thresholds = dict(thresholds or {})
    cur = current.get("metrics", current) or {}
    base = baseline.get("metrics", baseline) or {}
    checks: List[Dict[str, Any]] = []
    # iterate the union of both sides' metric names (sorted for stable
    # report order): bracketed per-tenant names — `p99_ms[a]` from fleet
    # rows — inherit the base metric's direction, unknown names skip
    for metric in sorted(set(base) | set(cur)):
        direction = METRIC_DIRECTIONS.get(metric)
        if direction is None:
            direction = METRIC_DIRECTIONS.get(metric.split("[", 1)[0])
        if direction is None:
            continue
        b, c = base.get(metric), cur.get(metric)
        if b is None or c is None or float(b) == 0.0:
            continue
        b, c = float(b), float(c)
        delta_pct = (c - b) / abs(b) * 100.0
        worse_pct = -delta_pct if direction > 0 else delta_pct
        thr = float(thresholds.get(metric,
                                   thresholds.get(metric.split("[", 1)[0],
                                                  default_pct)))
        checks.append({"metric": metric, "baseline": b, "current": c,
                       "delta_pct": round(delta_pct, 3),
                       "threshold_pct": thr,
                       "regressed": worse_pct >= thr})
    if not checks:
        status = "incomparable"
    elif any(ch["regressed"] for ch in checks):
        status = "regression"
    else:
        status = "ok"
    return {"status": status, "checks": checks,
            "baseline_source": baseline.get("source"),
            "current_source": current.get("source")}


class PerfWatch:
    """Warn-on-regression hook for a live run.

    >>> rt = ResilientTrainer(..., perfwatch={"check_every": 200})
    # every 200 steps the live mxtpu_mfu / samples_per_sec gauges are
    # compared against bench_cache.json; a breach logs a warning and
    # bumps mxtpu_perf_regressions_total{metric=}.

    ``baseline`` may be a path (bench row / BENCH_*.json / ledger), an
    already-normalized dict, or None for the default
    (``MXNET_PERF_BASELINE`` env, else the repo's bench_cache.json). A
    missing baseline disarms the watch with one warning — never an error:
    a fresh clone without bench history must still train.
    """

    def __init__(self, baseline=None, thresholds: Optional[Dict[str, float]] = None,
                 default_pct: float = DEFAULT_THRESHOLD_PCT,
                 check_every: int = 100):
        self.thresholds = dict(thresholds or {})
        self.default_pct = float(default_pct)
        self.check_every = max(1, int(check_every))
        self.last_result: Optional[Dict[str, Any]] = None
        self.events: List[Dict[str, Any]] = []
        self._warned_incomparable = False
        if isinstance(baseline, dict):
            self.baseline = (baseline if "metrics" in baseline
                             else {"kind": "inline", "source": "<dict>",
                                   "metrics": dict(baseline)})
            self.baseline_error = ""
        else:
            path = baseline or default_baseline_path()
            self.baseline, self.baseline_error = load_artifact(path)
            if self.baseline is None:
                logger.warning(
                    "perfwatch disarmed: no usable baseline (%s)",
                    self.baseline_error)

    def disarm(self, reason: str) -> None:
        """Drop the baseline so the watch stops checking — ONE warning, no
        regression spam. Called when the workload's signature changes out
        from under the baseline (e.g. an elastic reshard moved the run to
        a different device count: the old throughput/MFU floor describes a
        mesh that no longer exists). Idempotent."""
        if self.baseline is None:
            return
        self.baseline = None
        self.baseline_error = reason
        logger.warning("perfwatch disarmed: %s", reason)

    # ------------------------------------------------------------ checking
    def live_metrics(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        mfu = _catalog.MFU.value()
        sps = _catalog.SAMPLES_PER_SEC.value()
        if mfu is not None:
            out["mfu"] = float(mfu)
        if sps is not None:
            out["samples_per_sec"] = float(sps)
        burn = None
        for s in _catalog.SLO_BURN.series():
            v = s.get("value")
            if v is not None:
                burn = float(v) if burn is None else max(burn, float(v))
        if burn is not None:
            out["slo_burn_rate"] = burn
        return out

    def check(self, current: Optional[Dict[str, Any]] = None,
              step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Compare ``current`` (default: the live gauges) to the baseline.
        Returns the comparison result, or None when disarmed."""
        if self.baseline is None:
            return None
        if current is None:
            current = {"kind": "live", "source": "<registry>",
                       "metrics": self.live_metrics()}
        res = compare(current, self.baseline, thresholds=self.thresholds,
                      default_pct=self.default_pct)
        if step is not None:
            res["step"] = int(step)
        self.last_result = res
        if res["status"] == "incomparable" and not self._warned_incomparable:
            # an armed watch that can never fire is worse than a disarmed
            # one — say so ONCE (e.g. a bare-core bench row with only
            # throughput vs live gauges that only carry mfu/samples_per_sec)
            self._warned_incomparable = True
            logger.warning(
                "perfwatch: baseline %s shares no metric with the current "
                "artifact (baseline has %s, current has %s) — the watch "
                "cannot fire; enable the cost ledger so live MFU is "
                "published, or choose a baseline with mfu/samples_per_sec",
                res.get("baseline_source"),
                sorted((self.baseline.get("metrics") or {})),
                sorted((current.get("metrics") or {})))
        for ch in res["checks"]:
            if not ch["regressed"]:
                continue
            self.events.append(dict(ch, step=step))
            if _metrics.enabled():
                _catalog.PERF_REGRESSIONS.inc(metric=ch["metric"])
            logger.warning(
                "perf regression: %s %.4g vs baseline %.4g (%+.1f%%, "
                "threshold %.1f%%, baseline %s)", ch["metric"],
                ch["current"], ch["baseline"], ch["delta_pct"],
                ch["threshold_pct"], res.get("baseline_source"))
        return res

    def on_step(self, step: int) -> Optional[Dict[str, Any]]:
        """The ResilientTrainer cadence hook: a real check every
        ``check_every`` steps, a no-op otherwise."""
        if self.baseline is None or step % self.check_every != 0:
            return None
        return self.check(step=step)
