"""Spans — one instrumentation point, two backends.

A span is a named timed region (context manager or decorator). On exit it

- observes its duration into the ``mxtpu_span_ms`` histogram (labeled by
  span name, plus any user labels), and
- emits a chrome-trace event into :mod:`mxnet_tpu.profiler` when a profiling
  session is recording,

so the same ``with span("data_load"):`` lights up the Prometheus/JSON
exposition AND the chrome://tracing timeline. The flight recorder reads the
thread's active-span stack to note what was in flight at each step record
(and therefore at crash time).

Both gates (telemetry switch, profiler session) are evaluated at ``__enter__``
time, so a span created at import/decoration time tracks runtime toggles; a
fully-disabled span does nothing but two boolean checks.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Tuple

from . import metrics as _metrics

__all__ = ["span", "active_spans", "SPAN_MS"]

SPAN_MS = _metrics.histogram(
    "mxtpu_span_ms", "Duration of instrumented spans, by span name.")

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def active_spans() -> Tuple[str, ...]:
    """Names of spans currently open on THIS thread, outermost first."""
    return tuple(_stack())


def _profiler_recording() -> bool:
    try:
        from .. import profiler
        return profiler.recording()
    except Exception:
        return False


class span:
    """Timed region: ``with span("kv_publish", key=k): ...`` or
    ``@span("evaluate")`` on a function (a fresh region per call). Feeds the
    span histogram and — when a profiler session is recording — the
    chrome-trace stream."""

    __slots__ = ("name", "category", "labels", "_t0", "_us0", "_tel",
                 "_prof")

    def __init__(self, name: str, category: str = "span", **labels):
        self.name = name
        self.category = category
        self.labels = labels

    def __enter__(self):
        self._tel = _metrics.enabled()
        self._prof = _profiler_recording()
        if self._tel or self._prof:
            _stack().append(self.name)
            self._t0 = time.perf_counter()
            if self._prof:
                from .. import profiler
                self._us0 = profiler._prof.us()
        return self

    def __exit__(self, *exc):
        if not (self._tel or self._prof):
            return False
        dt = time.perf_counter() - self._t0
        st = _stack()
        if st and st[-1] == self.name:
            st.pop()
        if self._tel:
            SPAN_MS.observe(dt * 1000.0, span=self.name, **self.labels)
        if self._prof:
            from .. import profiler
            args = dict(self.labels) if self.labels else {}
            try:
                # merged-timeline cross-link: a span opened under
                # tracing.use(ctx) carries its trace_id into the
                # chrome-trace stream (never into metric labels — a
                # per-trace label would explode series cardinality)
                from . import tracing as _tracing
                tid = _tracing.current_trace_id()
                if tid:
                    args["trace_id"] = tid
            except Exception:
                pass
            profiler.record_event(self.name, self.category, self._us0,
                                  dt * 1e6, args or None)
        return False

    def __call__(self, fn):
        name, category, labels = self.name, self.category, self.labels

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, category=category, **labels):
                return fn(*args, **kwargs)

        return wrapper
