"""Built-in metric catalog — every family the framework itself publishes.

Declared centrally (not at each instrumentation site) so a snapshot always
contains the full catalog regardless of which subsystems a given run
imported: a dashboard scraping ``mxtpu_kv_publish_ms`` sees the family (with
zero series) even in a run that never created a dist kvstore, instead of a
404-shaped absence. Instrumentation sites import their family objects from
here; user code can mint additional metrics via ``observability.counter``/
``gauge``/``histogram`` freely.

The human-oriented catalog with semantics lives in ``docs/observability.md``
— keep the two in sync.
"""
from __future__ import annotations

from . import metrics as _m

# --------------------------------------------------------------- trainer
STEP_MS = _m.histogram(
    "mxtpu_trainer_step_ms",
    "Wall time of DataParallelTrainer.step (host dispatch + any sync the "
    "caller's loop forces).")
STEPS_TOTAL = _m.counter(
    "mxtpu_trainer_steps_total", "Fused train steps dispatched.")
SAMPLES_TOTAL = _m.counter(
    "mxtpu_trainer_samples_total",
    "Training samples consumed (leading batch dim of the first input).")
SAMPLES_PER_SEC = _m.gauge(
    "mxtpu_trainer_samples_per_sec",
    "Throughput of the most recent step (samples / step wall time).")
CAPTURES_TOTAL = _m.counter(
    "mxtpu_trainer_captures_total",
    "Net captures (graph trace + jit rebuild). More than one per input "
    "signature means something is forcing re-capture.")
GRAD_SKIPPED = _m.gauge(
    "mxtpu_trainer_grad_skipped_steps",
    "Grad-guard skip-step count (published when anomaly_stats()/Monitor "
    "drains the device counters — never synced per step).")
GRAD_NORM_EMA = _m.gauge(
    "mxtpu_trainer_grad_norm_ema", "Grad-guard gradient-norm EMA.")
GRAD_LAST_NORM = _m.gauge(
    "mxtpu_trainer_last_grad_norm", "Gradient norm of the last guarded step.")
STEP_RETRIES = _m.counter(
    "mxtpu_trainer_step_retries_total",
    "Transient step failures retried by ResilientTrainer.")

# -------------------------------------------------------------------- io
IO_BATCHES = _m.counter(
    "mxtpu_io_batches_total",
    "Batches delivered by ResilientDataIter, labeled iter= (base iterator "
    "class).")
IO_READ_RETRIES = _m.counter(
    "mxtpu_io_read_retries_total",
    "Transient data-read failures retried with backoff "
    "(ResilientDataIter, MXNET_IO_RETRY_*).")
IO_SKIPPED_BATCHES = _m.counter(
    "mxtpu_io_corrupt_skipped_total",
    "Corrupt batches skipped under MXNET_IO_SKIP_BUDGET (past the budget "
    "the run fails loudly instead).")
IO_QUEUE_DEPTH = _m.gauge(
    "mxtpu_io_queue_depth",
    "Staged batches in a prefetch queue at last delivery, labeled iter=. "
    "Persistently 0 under load = the producer can't keep up.")
IO_FEED_STALL_MS = _m.histogram(
    "mxtpu_io_feed_stall_ms",
    "Time the consumer blocked in next() waiting for data — the host-feed "
    "stall XLA cannot hide.",
    buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 5000, 30000))

# ---------------------------------------------------------------- module
FIT_EPOCH_MS = _m.histogram(
    "mxtpu_fit_epoch_ms", "Module.fit wall time per epoch.",
    buckets=(100, 500, 1000, 5000, 15000, 60000, 300000, 1800000))
FIT_BATCHES = _m.counter(
    "mxtpu_fit_batches_total", "Batches processed by Module.fit.")

# --------------------------------------------------------------- kvstore
KV_PUBLISH_MS = _m.histogram(
    "mxtpu_kv_publish_ms",
    "dist kvstore weight-publish latency (coordination-service round "
    "trip), per attempt.")
KV_PUBLISH_RETRIES = _m.counter(
    "mxtpu_kv_publish_retries_total",
    "Publish attempts that failed transiently and backed off.")
KV_PUBLISH_FAILURES = _m.counter(
    "mxtpu_kv_publish_failures_total",
    "Publishes that exhausted their retry budget (TransientKVError).")
KV_PUSH_TOTAL = _m.counter(
    "mxtpu_kv_push_total", "kvstore push operations.")
KV_PULL_TOTAL = _m.counter(
    "mxtpu_kv_pull_total", "kvstore pull operations.")

# ------------------------------------------------------------ checkpoint
CKPT_SAVE_MS = _m.histogram(
    "mxtpu_checkpoint_save_ms",
    "ShardedCheckpointer.save wall time, labeled mode=sync|async (async "
    "measures snapshot+dispatch; serialization overlaps training).",
    buckets=(5, 25, 100, 500, 1000, 5000, 15000, 60000, 300000))
CKPT_COMMIT_MS = _m.histogram(
    "mxtpu_checkpoint_commit_ms",
    "Manifest + marker + atomic publish rename time.",
    buckets=(1, 5, 25, 100, 500, 1000, 5000, 15000))
CKPT_RESTORE_MS = _m.histogram(
    "mxtpu_checkpoint_restore_ms", "Checkpoint restore wall time.",
    buckets=(5, 25, 100, 500, 1000, 5000, 15000, 60000, 300000))
CKPT_BYTES = _m.counter(
    "mxtpu_checkpoint_bytes_total", "Bytes committed to checkpoints.")
CKPT_LAST_BYTES = _m.gauge(
    "mxtpu_checkpoint_last_bytes", "Size of the most recent checkpoint.")
CKPT_VERIFY_FAILURES = _m.counter(
    "mxtpu_checkpoint_verify_failures_total",
    "verify() calls that found a torn/uncommitted checkpoint.")

# ------------------------------------------------------------ collectives
COLL_DISPATCHES = _m.counter(
    "mxtpu_collective_dispatches_total",
    "Host-level collective dispatches, labeled op=psum|cp_allreduce|"
    "cp_alltoall|cp_allgather.")
COLL_BYTES = _m.counter(
    "mxtpu_collective_bytes_total",
    "Payload bytes entering host-level collectives, labeled op=.")
COLL_MS = _m.histogram(
    "mxtpu_collective_ms",
    "Measured wall time of one collective operation in the bandwidth lab "
    "(parallel/collbench.py), labeled op=psum|reduce_scatter|all_gather|"
    "ppermute|psum_compressed.",
    buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 500, 2000))

# ------------------------------------------------------------- resilience
WATCHDOG_FIRED = _m.counter(
    "mxtpu_watchdog_timeouts_total", "Watchdog deadline expirations.")
PREEMPTIONS = _m.counter(
    "mxtpu_preemptions_total",
    "Preemption signals honored at a step boundary (final save + exit).")
FLIGHT_DUMPS = _m.counter(
    "mxtpu_flight_recorder_dumps_total",
    "Flight-recorder artifacts written, labeled reason=.")
RECOVERY_TRIPS = _m.counter(
    "mxtpu_recovery_trips_total",
    "Recovery-ladder detector trips, labeled kind=skip_streak|"
    "loss_divergence|escalated.")
RECOVERY_ROLLBACKS = _m.counter(
    "mxtpu_recovery_rollbacks_total",
    "Recovery-ladder actions taken, labeled action=cut_scale|rollback|"
    "restore|fail|heal (rollback = in-memory snapshot, restore = durable "
    "checkpoint).")
RECOVERY_RUNG = _m.gauge(
    "mxtpu_recovery_rung",
    "Current recovery-ladder rung (0 = healthy; de-escalates after "
    "MXNET_RECOVERY_HEAL_STEPS clean steps).")
RECOVERY_SNAPSHOTS = _m.counter(
    "mxtpu_recovery_snapshots_total",
    "Rolling in-memory snapshots captured (rollback targets).")
RECOVERY_DEFERRED_SAVES = _m.counter(
    "mxtpu_recovery_deferred_saves_total",
    "Durable checkpoints deferred because guard-skipped steps were still "
    "awaiting rollback replay, labeled kind=periodic|preemption.")
LOSS_SCALE = _m.gauge(
    "mxtpu_loss_scale",
    "Live dynamic loss scale of the in-trace scaler (published when "
    "anomaly_stats()/recovery drains it — never synced per step).")
ELASTIC_RESHARDS = _m.counter(
    "mxtpu_elastic_reshards_total",
    "Elastic N→M topology adoptions completed at restore (ZeRO-1 "
    "opt-state re-tiled, global batch re-split), labeled "
    "direction=grow|shrink.")
ACTIVE_DEVICES = _m.gauge(
    "mxtpu_active_devices",
    "Devices in the live training mesh (set at capture and on every "
    "restore topology check — the number elastic resumes reconcile "
    "checkpoints against).")
ELASTIC_RESHARD_MS = _m.histogram(
    "mxtpu_elastic_reshard_ms",
    "Wall time of one elastic topology adoption: checkpoint restore of "
    "the gathered state + N→M re-tile under the new mesh + provenance.",
    buckets=(5, 25, 100, 500, 1000, 5000, 15000, 60000))

# ------------------------------------------------------------- performance
MFU = _m.gauge(
    "mxtpu_mfu",
    "Live model-FLOPs utilization over the attribution window: the "
    "executable's cost-ledger FLOPs per step divided by (mean step cadence "
    "x per-chip peak FLOP/s x chips). Needs the cost ledger enabled "
    "(MXNET_PERF_LEDGER) and a known/overridden device peak.")
DEVICE_UTIL = _m.gauge(
    "mxtpu_device_util",
    "Fraction of recent steps whose previous result was still executing "
    "when the next dispatch completed — a lag-1 saturation probe: ~1.0 = "
    "device-bound pipeline, ~0.0 = the host/input path is the bottleneck.")
STEP_BREAKDOWN = _m.gauge(
    "mxtpu_step_breakdown_ms",
    "Rolling mean of the wall step cadence decomposed host-side, labeled "
    "bucket=dispatch|h2d_transfer|host_prep|feed_stall|host_other "
    "(semantics in docs/observability.md).")
COST_LEDGER_ROWS = _m.counter(
    "mxtpu_cost_ledger_rows_total",
    "Rows appended to the XLA cost ledger (MXNET_PERF_LEDGER).")
PERF_REGRESSIONS = _m.counter(
    "mxtpu_perf_regressions_total",
    "Perf-watchdog checks that found a metric past its regression "
    "threshold vs the baseline, labeled metric=.")
TUNER_TRIALS = _m.counter(
    "mxtpu_tuner_trials_total",
    "Autotuner trials scored, labeled provenance=predicted|measured|"
    "cached (cached = warm-start ledger hit: nothing re-lowered or "
    "re-run).")
TUNER_BEST_MFU = _m.gauge(
    "mxtpu_tuner_best_mfu",
    "MFU of the best measured candidate from the most recent tuner "
    "search (tuner.tune / tools/mxtune.py).")

# --------------------------------------------------------------- serving
SERVE_REQUESTS = _m.counter(
    "mxtpu_serve_requests_total",
    "Model-server requests by final outcome, labeled model= and "
    "outcome=ok|shed|expired|error (shed = typed admission/breaker/drain "
    "rejection, expired = deadline passed before dispatch — never sent "
    "to the device, error = executor fault after retries+isolation).")
SERVE_LATENCY = _m.histogram(
    "mxtpu_serve_latency_ms",
    "End-to-end latency of OK requests (submit to completed result), "
    "labeled model=. Rejected/expired requests are counted, not timed.",
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 10000))
SERVE_BATCH = _m.histogram(
    "mxtpu_serve_batch_size",
    "Rows per dispatched batch BEFORE bucket padding, labeled model=. "
    "Persistently 1 under load = the assembly window is too short.",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
SERVE_QUEUE_DEPTH = _m.gauge(
    "mxtpu_serve_queue_depth",
    "Requests queued per model at last admission/dispatch, labeled "
    "model=. Pinned at the queue bound = shedding load.")
SERVE_HEDGES = _m.counter(
    "mxtpu_serve_hedges_total",
    "Hedged (duplicate tail-tolerance) dispatches, labeled model= and "
    "outcome=won|lost|budget_denied (won = the hedge completed the "
    "request first; lost = the primary beat it or the hedge errored — "
    "its result dropped; budget_denied = the retry budget refused to "
    "fund the hedge). won/submitted is the hedge hit rate; a high "
    "budget_denied rate means hedging wants more budget than the "
    "configured fraction allows.")
CHIP_QUARANTINES = _m.counter(
    "mxtpu_chip_quarantines_total",
    "Chips quarantined by the device sentinel after a device-fatal "
    "fault (serving/health.py), labeled reason= (device_lost|enqueue|"
    "data_loss|probe|other). Each quarantine triggers an automatic "
    "bucket-ladder re-plan onto the survivors.")
QUARANTINED_CHIPS = _m.gauge(
    "mxtpu_quarantined_chips",
    "Chips currently quarantined by the device sentinel (unlabeled). "
    "Nonzero = serving on reduced capacity; stuck nonzero past the "
    "cooldown = the half-open re-admission probe keeps failing.")
SERVE_DEGRADED_RUNG = _m.gauge(
    "mxtpu_serve_degraded_rung",
    "Current rung of the per-model degraded-mode ladder, labeled "
    "model=: 0 healthy, 1 reduced buckets (biggest dropped), 2 int8 "
    "tier fallback, 3 guaranteed-traffic-only admission, 4 static shed. "
    "Edge-triggered: transitions also land in the trace ring.")
RETRY_BUDGET_DENIED = _m.counter(
    "mxtpu_retry_budget_denied_total",
    "Retries or hedges refused because the shared retry budget (default "
    "~10% of admitted traffic) was exhausted, labeled model= and "
    "kind=retry|hedge. Denials fail fast and typed — a climbing counter "
    "under overload is the budget doing its job (no retry storm).")

# --------------------------------------------------------------- rollout
ROLLOUT_STAGE = _m.gauge(
    "mxtpu_rollout_stage",
    "Current ramp stage of the model's live rollout, labeled model=: "
    "0 shadow, 1/2/3 the 1%/10%/50% canary stages, 4 the 100% stage "
    "(left there once promoted), -1 rolled back/aborted. Transitions "
    "are edge-triggered and also land in the trace ring as 'rollout' "
    "events.")
ROLLOUT_ROLLBACKS = _m.counter(
    "mxtpu_rollout_rollbacks_total",
    "Automatic or operator rollbacks of a canary version, labeled "
    "reason= (breaker|error_rate|slo_burn|p99_delta|agreement|operator|"
    "abort). One bump per rollback transition, never per request — "
    "perfwatch treats a climbing count as a regression signal "
    "(down-is-good).")
ROLLOUT_SHADOW_AGREEMENT = _m.gauge(
    "mxtpu_rollout_shadow_agreement",
    "Rolling top-1 agreement between the canary's shadow answers and "
    "the incumbent's served answers, labeled model= (1.0 = identical "
    "argmax on every sampled request; the gate rolls back below "
    "MXNET_ROLLOUT_MIN_AGREEMENT). Same statistic the quant "
    "evaluate_agreement harness reports for int8 tiers.")
ROLLOUT_VERSION_REQUESTS = _m.counter(
    "mxtpu_rollout_version_requests_total",
    "Model-server requests attributed to a rollout version, labeled "
    "model=, version= and outcome= (same outcomes as "
    "mxtpu_serve_requests_total). The zero-downtime proof: a retired "
    "version's counters stop moving after the swap, and the per-version "
    "sum equals the model's total while a rollout is configured.")

# ----------------------------------------------------------------- fleet
FLEET_RESIZES = _m.counter(
    "mxtpu_fleet_resizes_total",
    "Fleet chip reallocations (serving/fleet.py FleetController), "
    "labeled direction=grow|shrink — one increment per model whose chip "
    "assignment changed (a reallocation pair bumps grow once and shrink "
    "once). Hysteresis (MXNET_FLEET_DWELL_S) bounds the rate; a counter "
    "climbing faster than one per dwell window per model is thrash.")
FLEET_ACTIVE_CHIPS = _m.gauge(
    "mxtpu_fleet_active_chips",
    "Chips currently assigned to each serving tenant, labeled model=. "
    "The fleet placement map in gauge form; sums to at most the fleet's "
    "total_chips budget.")
FLEET_PREEMPTED = _m.counter(
    "mxtpu_fleet_preempted_total",
    "Best-effort requests shed with typed Preempted (admission or queue "
    "eviction) because a guaranteed tenant was in an SLO excursion, "
    "labeled tenant=. Never silent: every preempted request's future "
    "completes with the typed error.")
FLEET_QUOTA_SHEDS = _m.counter(
    "mxtpu_fleet_quota_sheds_total",
    "Requests shed with typed QuotaExceeded at fleet admission because "
    "the tenant exceeded its declared QPS quota, labeled tenant=. "
    "Attributes overload to the tenant that over-drove, not to server "
    "capacity (which lands in mxtpu_serve_requests_total{outcome=shed}).")
FLEET_RESIZE_MS = _m.histogram(
    "mxtpu_fleet_resize_ms",
    "Wall time of one fleet resize: quiesce the replica's in-flight "
    "batch + re-bind the bucket executor ladder for the new chip count "
    "(params stay placed; buckets recompile lazily on next use).",
    buckets=(0.1, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000))

# --------------------------------------------------------------- tracing
TRACE_SPANS = _m.counter(
    "mxtpu_trace_spans_total",
    "Request-trace lifecycle spans recorded, labeled stage=admission|"
    "queue|assembly|dispatch|forward|respond and outcome=ok|shed|"
    "expired|error (every finished request emits its stage spans here "
    "regardless of tail-sampling — the sampler only gates ring "
    "retention, never counting).")
TRACE_RING_DEPTH = _m.gauge(
    "mxtpu_trace_ring_depth",
    "Retained traces in the bounded trace ring (MXNET_TRACE_RING). "
    "Pinned at capacity = the tail is evicting; read it with "
    "tools/mxtrace.py before it rolls.")
TRACE_DROPPED = _m.counter(
    "mxtpu_trace_dropped_total",
    "Finished traces not retained in the ring, labeled reason="
    "sampled_out (boring bulk below MXNET_TRACE_SAMPLE — error/shed/"
    "expired/violating/slow-tail traces are never sampled out) | "
    "evicted (ring at capacity, oldest rolled off).")
SLO_BURN = _m.gauge(
    "mxtpu_slo_burn_rate",
    "Rolling SLO error-budget burn rate, labeled model= and window="
    "fast|slow: the window's SLO-bad fraction divided by the error "
    "budget (1 - availability target). 1.0 = consuming budget exactly "
    "as fast as the target allows; crossing "
    "MXNET_SERVE_SLO_BURN_THRESHOLD on the fast window warns and bumps "
    "mxtpu_perf_regressions_total{metric='slo_burn_rate'}.")

# ------------------------------------------------------------ quantization
QUANT_CALIB_BATCHES = _m.counter(
    "mxtpu_quant_calib_batches_total",
    "Calibration batches streamed through quant.collect, labeled "
    "mode=naive|entropy.")
QUANT_NODES = _m.gauge(
    "mxtpu_quant_nodes",
    "Convolution/FullyConnected nodes rewritten to int8 islands by the "
    "most recent quantize_symbol run, labeled model=.")
QUANT_ACC_DELTA = _m.gauge(
    "mxtpu_quant_acc_delta",
    "fp32-minus-int8 top-1 accuracy delta of the last "
    "quant.evaluate_agreement run (positive = the int8 model lost "
    "accuracy; the flow's ~1% acceptance bar reads this number).")
QUANT_SERVE_REQUESTS = _m.counter(
    "mxtpu_quant_serve_requests_total",
    "Model-server requests answered by an int8-tier model, labeled "
    "model= and outcome= (same outcomes as mxtpu_serve_requests_total — "
    "the int8 slice of serving traffic).")

# ----------------------------------------------------------------- memory
HBM_BYTES_IN_USE = _m.gauge(
    "mxtpu_hbm_bytes_in_use",
    "Live HBM bytes in use per device at the last memwatch.poll_hbm "
    "sample, labeled device=. Real allocator numbers where the backend "
    "has memory_stats(); the synthetic live-set sum (registered state "
    "trees + chaos ballast) on backends without (CPU).")
HBM_PEAK_BYTES = _m.gauge(
    "mxtpu_hbm_peak_bytes",
    "High-watermark HBM bytes across devices (allocator peak_bytes_in_use "
    "where available; the running synthetic peak otherwise). The number "
    "placement budgets must stay above.")
HBM_LARGEST_ALLOC_BYTES = _m.gauge(
    "mxtpu_hbm_largest_alloc_bytes",
    "Largest single live allocation (allocator largest_alloc_size where "
    "available; the largest registered live set otherwise) — the "
    "fragmentation probe: an OOM with in_use well under the limit and "
    "this number large means fragmentation, not demand.")
OOM_TOTAL = _m.counter(
    "mxtpu_oom_total",
    "Device RESOURCE_EXHAUSTED failures classified at a dispatch "
    "boundary, labeled context=serving|trainer|restore. Every increment "
    "has a matching mxtpu_oom.json postmortem artifact.")
MEM_REFUSALS = _m.counter(
    "mxtpu_mem_refusals_total",
    "Memory-aware refusals instead of a device OOM, labeled reason="
    "no_memory (fleet grow/resize whose post-state would not fit the "
    "per-chip HBM budget) | load (ModelServer refused to load a model "
    "whose estimated footprint exceeds the remaining budget) | "
    "rollout (a canary version refused at load because it would not fit "
    "next to the resident versions — the incumbent keeps serving) | "
    "predicted_oom (tuner candidate skipped because its predicted "
    "footprint exceeds the budget).")

# -------------------------------------------------------------- callbacks
SPEEDOMETER_SPS = _m.gauge(
    "mxtpu_speedometer_samples_per_sec",
    "Speedometer throughput (same number as its log line).")
MONITOR_STAT = _m.gauge(
    "mxtpu_monitor_stat",
    "Monitor layer statistics, labeled stat= (the Monitor.toc stream).")

# --------------------------------------------------------------- lockwatch
LOCK_HOLD_MS = _m.histogram(
    "mxtpu_lock_hold_ms",
    "Wall time a lockwatch-instrumented lock was held, labeled site= "
    "(the class-wide lock name, e.g. serving.queueing."
    "BoundedRequestQueue._lock). Only populated under MXNET_LOCKCHECK=1 "
    "— host-side lock telemetry never enters the XLA trace.",
    buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000))
LOCK_CONTENTION = _m.counter(
    "mxtpu_lock_contention_total",
    "Contended acquisitions of a lockwatch-instrumented lock (the "
    "uncontended fast path failed and the thread had to block), labeled "
    "site=. Only populated under MXNET_LOCKCHECK=1.")
LOCKWATCH_FINDINGS = _m.counter(
    "mxtpu_lockwatch_findings_total",
    "Deadlock-hazard findings raised by the runtime lock sanitizer, "
    "labeled rule=MXL-C300 (order inversion seen live) | MXL-C303 "
    "(re-entrant acquire of a non-reentrant lock). Any nonzero value "
    "is a bug report: tools/mxrace.py report pretty-prints the stacks.")
