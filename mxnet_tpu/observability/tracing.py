"""End-to-end request tracing — span timelines from the HTTP edge to XLA
dispatch, with tail-sampling and SLO burn-rate guarding.

The PR-3/PR-6 observability layer answers "how fast is the step" and the
serving counters answer "how many requests"; this module answers *"where
did THIS slow request spend its 87 ms"*. A :class:`TraceContext`
(W3C ``traceparent`` parse/emit) enters at the HTTP edge
(``ServingEndpoints``), rides the queued request through the batching
model server, and every lifecycle stage — admission, queue wait, batch
assembly, dispatch, executor forward, respond — lands as a child span
with outcome tags, so one request's timeline reconstructs exactly where
its deadline budget went, including which batchmates it was fused with
(the shared batch-span id).

Finished traces land in a bounded, thread-safe ring (:class:`Tracer`)
under **tail-sampling**: error/shed/expired and deadline-violating
traces are ALWAYS retained, the slowest tail (>= the rolling p99 of the
model's recent latencies) is always retained, and the boring bulk is
kept at ``MXNET_TRACE_SAMPLE``. Two export paths:

- **chrome-trace** (:meth:`Tracer.chrome_trace`): serving spans,
  ``jit_hooks`` compile events and the live profiler stream merged on
  ONE clock (the profiler's perf-counter zero), so a serving span and
  the XLA compile that delayed it line up in ``chrome://tracing``;
- **exemplars**: ``mxtpu_serve_latency_ms`` observations carry the
  trace_id of a ring-retained request, so a bad percentile links
  directly to a concrete timeline (``Histogram.exemplars``).

On top of that, the SLO layer (:class:`SLOTracker`): per-model
objectives (``MXNET_SERVE_SLO_P99_MS`` latency target + an availability
target) evaluated as rolling fast/slow **burn rates** — the fraction of
the error budget being consumed per unit time — published as
``mxtpu_slo_burn_rate{model,window}`` gauges; crossing the burn
threshold bumps the perfwatch regression counter
(``mxtpu_perf_regressions_total{metric="slo_burn_rate"}``) and warns,
never kills.

Training shares the spine for free: :func:`use` installs a thread-local
context, and flight-recorder step records (and the watchdog's crash
dump) embed the active ``trace_id`` so a hung step cross-links to the
trace ring.

Host-side only by construction: nothing here enters the XLA trace, and
the compiled forward's HLO is bitwise identical with tracing on or off
(guarded by ``tests/test_tracing.py``).
"""
from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.lockwatch import make_lock
from ..base import MXNetError, get_env, logger, register_config
from . import catalog as _catalog
from . import metrics as _metrics

__all__ = ["TraceContext", "RequestTrace", "Tracer", "SLOTracker",
           "get_tracer", "set_tracer", "current", "current_trace_id",
           "use", "new_span_id", "STAGES"]

register_config("MXNET_TRACE_RING", 512, int,
                "Trace-ring capacity: finished request traces kept for "
                "tools/mxtrace.py and exemplar resolution. 0 disables "
                "request tracing entirely (mxlint MXL-T216 flags a server "
                "with declared SLOs/deadlines serving untraced).")
register_config("MXNET_TRACE_SAMPLE", 0.05, float,
                "Tail-sampling keep probability for BORING traces (ok, "
                "within deadline, not in the slow tail). Error/shed/"
                "expired/deadline-violating traces and the rolling-p99 "
                "slow tail are always retained regardless of this rate.")

# request lifecycle stages, in timeline order
STAGES = ("admission", "queue", "assembly", "dispatch", "forward", "respond")

# monotonic->perf_counter offset, measured once: server stamps use
# time.monotonic, the profiler's clock zero is a perf_counter reading —
# on Linux both read CLOCK_MONOTONIC so the offset is ~0, but the export
# must not silently assume it
_MONO_TO_PERF = time.perf_counter() - time.monotonic()


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def new_span_id() -> str:
    """A fresh 8-byte span id (e.g. the per-dispatch batch-span id)."""
    return _new_id(8)


class TraceContext:
    """trace_id/span_id pair with W3C ``traceparent`` parse/emit.

    ``traceparent: 00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>``
    (flag bit 0 = sampled). :meth:`parse` returns None on any malformed
    header — an edge must degrade to a fresh context, never 500.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: Optional[str] = None,
                 span_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = (trace_id or _new_id(16)).lower()
        self.span_id = (span_id or _new_id(8)).lower()
        self.sampled = bool(sampled)

    @classmethod
    def new(cls) -> "TraceContext":
        return cls()

    @classmethod
    def parse(cls, traceparent) -> Optional["TraceContext"]:
        if not traceparent or not isinstance(traceparent, str):
            return None
        parts = traceparent.strip().lower().split("-")
        if len(parts) != 4:
            return None
        ver, tid, sid, flags = parts
        if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 \
                or len(flags) != 2:
            return None
        try:
            int(ver, 16)
            int(tid, 16)
            int(sid, 16)
            fl = int(flags, 16)
        except ValueError:
            return None
        # version ff is forbidden; all-zero ids are invalid per the spec
        if ver == "ff" or set(tid) == {"0"} or set(sid) == {"0"}:
            return None
        return cls(tid, sid, bool(fl & 0x01))

    def to_traceparent(self) -> str:
        return "00-%s-%s-%02x" % (self.trace_id, self.span_id,
                                  0x01 if self.sampled else 0x00)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — the server-side hop of an
        inbound context."""
        return TraceContext(self.trace_id, _new_id(8), self.sampled)

    def __repr__(self):
        return "TraceContext(%s)" % self.to_traceparent()


# ---- thread-local active context (the training/flight-recorder spine) ----
_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context installed on THIS thread by :func:`use`, or None."""
    return getattr(_tls, "ctx", None)


def current_trace_id() -> Optional[str]:
    c = current()
    return c.trace_id if c is not None else None


@contextlib.contextmanager
def use(ctx: TraceContext):
    """Install ``ctx`` as the thread's active context for the block:
    flight-recorder records and profiler-mirrored spans inside it embed
    the trace_id (the watchdog-dump → trace-ring cross-link)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


class RequestTrace:
    """One request's span timeline. Stages are appended by the serving
    path (stamps are ``time.monotonic`` seconds); :meth:`to_dict`
    renders them relative to the submit instant."""

    __slots__ = ("ctx", "model", "submitted_at", "wall_time", "spans",
                 "outcome", "reason", "latency_ms", "violated",
                 "batch_span_id", "batch_size", "deadline_ms", "sample",
                 "kept", "keep_reason")

    def __init__(self, model: str, ctx: Optional[TraceContext] = None,
                 deadline_ms: Optional[float] = None,
                 submitted_at: Optional[float] = None,
                 sample: Optional[float] = None):
        self.ctx = ctx if ctx is not None else TraceContext.new()
        self.model = str(model)
        self.submitted_at = (time.monotonic() if submitted_at is None
                             else float(submitted_at))
        self.wall_time = time.time()
        self.spans: List[Dict[str, Any]] = []
        self.outcome: Optional[str] = None
        self.reason: Optional[str] = None
        self.latency_ms: Optional[float] = None
        self.violated = False
        self.batch_span_id: Optional[str] = None
        self.batch_size: Optional[int] = None
        self.deadline_ms = deadline_ms
        self.sample = sample
        self.kept = False
        self.keep_reason: Optional[str] = None

    @property
    def trace_id(self) -> str:
        return self.ctx.trace_id

    @property
    def span_id(self) -> str:
        return self.ctx.span_id

    def span(self, stage: str, t0: float, t1: float, **tags) -> None:
        """Record one stage span (monotonic seconds; t1 clamped >= t0)."""
        self.spans.append({"stage": str(stage), "t0": float(t0),
                           "t1": max(float(t0), float(t1)),
                           "tags": dict(tags) if tags else {}})

    def stage_ms(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.spans:
            out[s["stage"]] = out.get(s["stage"], 0.0) \
                + (s["t1"] - s["t0"]) * 1e3
        return out

    def to_dict(self) -> Dict[str, Any]:
        base = self.submitted_at
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "model": self.model, "outcome": self.outcome,
            "reason": self.reason,
            "latency_ms": (round(self.latency_ms, 4)
                           if self.latency_ms is not None else None),
            "violated": bool(self.violated),
            "deadline_ms": self.deadline_ms,
            "batch_span_id": self.batch_span_id,
            "batch_size": self.batch_size,
            "time": self.wall_time,
            "keep_reason": self.keep_reason,
            "spans": [{"stage": s["stage"],
                       "t0_ms": round((s["t0"] - base) * 1e3, 4),
                       "dur_ms": round((s["t1"] - s["t0"]) * 1e3, 4),
                       "tags": s["tags"]} for s in self.spans],
        }


_TAIL_MIN_SAMPLES = 20          # latencies needed before the p99 tail arms
_TAIL_WINDOW = 512              # per-model rolling latency window
_TAIL_REFRESH = 32              # inserts between p99-threshold recomputes


class Tracer:
    """Bounded thread-safe ring of finished request traces with
    tail-sampling. One process-wide default (:func:`get_tracer`) is
    shared by every :class:`~mxnet_tpu.serving.server.ModelServer`
    unless one is passed explicitly."""

    def __init__(self, capacity: Optional[int] = None,
                 sample: Optional[float] = None):
        self.capacity = int(get_env("MXNET_TRACE_RING", 512)
                            if capacity is None else capacity)
        self.sample = float(get_env("MXNET_TRACE_SAMPLE", 0.05)
                            if sample is None else sample)
        if not (0.0 <= self.sample <= 1.0):
            raise MXNetError("trace sample rate must be in [0, 1], got %r"
                             % (self.sample,))
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._lock = make_lock("observability.tracing.Tracer._lock")
        self._lat: Dict[str, deque] = {}    # model -> recent ok latencies
        self._lat_n: Dict[str, int] = {}    # appends per model
        self._tail_thr: Dict[str, float] = {}  # cached ~p99 threshold
        self._rng = random.Random()

    def enabled(self) -> bool:
        return self.capacity > 0 and _metrics.enabled()

    # ------------------------------------------------------------ lifecycle
    def start_request(self, model: str, ctx: Optional[TraceContext] = None,
                      deadline_ms: Optional[float] = None,
                      submitted_at: Optional[float] = None,
                      sample: Optional[float] = None
                      ) -> Optional[RequestTrace]:
        if not self.enabled():
            return None
        return RequestTrace(model, ctx=ctx, deadline_ms=deadline_ms,
                            submitted_at=submitted_at, sample=sample)

    def tail_latency_ms(self, model: str) -> Optional[float]:
        """Rolling ~p99 of the model's recent OK latencies (None until
        the window has enough samples to call a tail a tail). The
        threshold is a cache refreshed every ``_TAIL_REFRESH`` inserts —
        finishing a request never pays a full-window sort."""
        with self._lock:
            window = self._lat.get(model)
            if not window or len(window) < _TAIL_MIN_SAMPLES:
                return None
            return self._tail_thr.get(model)

    def _note_latency_locked(self, model: str, latency_ms: float) -> None:
        window = self._lat.get(model)
        if window is None:
            window = self._lat[model] = deque(maxlen=_TAIL_WINDOW)
        window.append(float(latency_ms))
        n = self._lat_n[model] = self._lat_n.get(model, 0) + 1
        if len(window) >= _TAIL_MIN_SAMPLES and (
                model not in self._tail_thr or n % _TAIL_REFRESH == 0):
            arr = sorted(window)
            self._tail_thr[model] = arr[min(len(arr) - 1,
                                            int(len(arr) * 0.99))]

    def _should_keep(self, rt: RequestTrace) -> Optional[str]:
        """The tail-sampling policy: the reason this trace is retained,
        or None to drop it. Order matters — forced retention first."""
        if rt.outcome != "ok":
            return rt.outcome           # error/shed/expired: always kept
        if rt.violated:
            return "violation"
        tail = self.tail_latency_ms(rt.model)
        if tail is not None and rt.latency_ms is not None \
                and rt.latency_ms >= tail:
            return "slow"
        rate = self.sample if rt.sample is None else rt.sample
        if rate > 0.0 and self._rng.random() < rate:
            return "sampled"
        return None

    def finish(self, rt: Optional[RequestTrace], outcome: str,
               latency_ms: Optional[float] = None, violated: bool = False,
               reason: Optional[str] = None) -> bool:
        """Seal a request trace: count spans, mirror into a recording
        profiler session, tail-sample into the ring. Returns True when
        the trace was retained (the exemplar gate)."""
        if rt is None:
            return False
        rt.outcome = str(outcome)
        rt.reason = reason
        rt.latency_ms = latency_ms
        rt.violated = bool(violated)
        if _metrics.enabled():
            for s in rt.spans:
                _catalog.TRACE_SPANS.inc(stage=s["stage"], outcome=rt.outcome)
        self._mirror_profiler(rt)
        why = self._should_keep(rt)
        evicted = False
        with self._lock:
            if outcome == "ok" and latency_ms is not None:
                self._note_latency_locked(rt.model, latency_ms)
            if why is not None:
                rt.kept, rt.keep_reason = True, why
                evicted = len(self._ring) == self._ring.maxlen
                self._ring.append(rt)
            depth = len(self._ring)
        if _metrics.enabled():
            if why is None:
                _catalog.TRACE_DROPPED.inc(reason="sampled_out")
            elif evicted:
                _catalog.TRACE_DROPPED.inc(reason="evicted")
            _catalog.TRACE_RING_DEPTH.set(depth)
        return why is not None

    def record_event(self, name: str, model: str = "fleet",
                     **tags) -> Optional[RequestTrace]:
        """Record an operational event (e.g. a fleet chip resize) into
        the trace ring as a zero-length span with outcome ``"event"`` —
        always retained by the tail-sampler (non-ok outcomes are forced),
        so ``tools/mxtrace.py`` shows resizes inline with the request
        timelines they reshaped (without counting them as anomalies).
        Returns the retained trace, or None when tracing is off."""
        if not self.enabled():
            return None
        rt = RequestTrace(model)
        t = time.monotonic()
        rt.span(name, t, t, **tags)
        self.finish(rt, "event", latency_ms=0.0, reason=name)
        return rt

    def _mirror_profiler(self, rt: RequestTrace) -> None:
        """When a profiler session is recording, emit every stage span
        into its chrome-trace stream (same us clock as every other
        profiler event) — the live half of the merged-timeline story."""
        try:
            from .. import profiler
            if not profiler.recording():
                return
            zero = profiler._prof.t0
            for s in rt.spans:
                t0_us = (s["t0"] + _MONO_TO_PERF - zero) * 1e6
                args = {"trace_id": rt.trace_id, "model": rt.model}
                if s["tags"]:
                    args.update(s["tags"])
                profiler.record_event("serve:%s" % s["stage"], "serving",
                                      t0_us, (s["t1"] - s["t0"]) * 1e6,
                                      args)
        except Exception:       # pragma: no cover - never fail the server
            pass

    # -------------------------------------------------------------- readout
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def depth(self) -> int:
        return len(self)

    def traces(self, model: Optional[str] = None,
               outcome: Optional[str] = None) -> List[RequestTrace]:
        """Retained traces, oldest first, optionally filtered."""
        with self._lock:
            out = list(self._ring)
        if model is not None:
            out = [t for t in out if t.model == model]
        if outcome is not None:
            out = [t for t in out if t.outcome == outcome]
        return out

    def get(self, trace_id: str) -> Optional[RequestTrace]:
        """Resolve one trace_id (newest wins) — the exemplar lookup."""
        tid = str(trace_id).lower()
        with self._lock:
            for t in reversed(self._ring):
                if t.trace_id == tid:
                    return t
        return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._lat.clear()
            self._lat_n.clear()
            self._tail_thr.clear()
        if _metrics.enabled():
            _catalog.TRACE_RING_DEPTH.set(0)

    # --------------------------------------------------------------- export
    def to_dict(self) -> Dict[str, Any]:
        return {"version": 1, "kind": "trace_ring", "time": time.time(),
                "pid": os.getpid(),
                "traces": [t.to_dict() for t in self.traces()]}

    def write_dump(self, path: str) -> str:
        """Write the ring as a JSON artifact (atomic rename) —
        the file ``tools/mxtrace.py`` pretty-prints."""
        doc = self.to_dict()
        tmp = "%s.tmp.%d.%d" % (path, os.getpid(), threading.get_ident())
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def chrome_trace(self, include_profiler: bool = True,
                     include_compiles: bool = True) -> Dict[str, Any]:
        """Chrome-trace JSON: serving spans + jit compile events (+ the
        live profiler stream) on ONE clock — the profiler's perf-counter
        zero — so a serving span and the XLA compile that delayed it
        line up in chrome://tracing."""
        from .. import profiler
        zero = profiler._prof.t0
        events: List[Dict[str, Any]] = []
        for rt in self.traces():
            tid = int(rt.trace_id[:8], 16) % (1 << 31)
            for s in rt.spans:
                args = {"trace_id": rt.trace_id, "model": rt.model,
                        "outcome": rt.outcome}
                if s["tags"]:
                    args.update(s["tags"])
                events.append({
                    "name": s["stage"], "cat": "serving", "ph": "X",
                    "ts": (s["t0"] + _MONO_TO_PERF - zero) * 1e6,
                    "dur": (s["t1"] - s["t0"]) * 1e6,
                    "pid": os.getpid(), "tid": tid, "args": args})
        if include_compiles:
            from . import jit_hooks
            for ev in jit_hooks.recent_compile_events():
                events.append({
                    "name": ev["event"], "cat": "jit", "ph": "X",
                    "ts": (ev["t0"] - zero) * 1e6,
                    "dur": ev["dur_s"] * 1e6,
                    "pid": os.getpid(), "tid": 0,
                    "args": {"lane": "jit-compile"}})
        if include_profiler:
            with profiler._lock:
                events.extend(dict(e) for e in profiler._prof.events)
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---- SLO burn-rate guarding -------------------------------------------------

register_config("MXNET_SERVE_SLO_P99_MS", 0.0, float,
                "Per-model serving SLO: the p99 latency objective. A "
                "request is SLO-good when it completes ok within this "
                "budget. 0 = no SLO declared (no burn-rate gauges).")
register_config("MXNET_SERVE_SLO_AVAILABILITY", 0.999, float,
                "Per-model serving SLO availability target: the fraction "
                "of requests that must be SLO-good; 1-target is the error "
                "budget the burn rate is measured against.")
register_config("MXNET_SERVE_SLO_BURN_THRESHOLD", 2.0, float,
                "Fast-window burn rate above which the SLO guard fires "
                "(warn + mxtpu_perf_regressions_total{metric="
                "'slo_burn_rate'}). Burn 1.0 = consuming the error budget "
                "exactly as fast as the availability target allows.")

_SLO_MIN_EVENTS = 20            # events before the guard may fire


class SLOTracker:
    """Rolling fast/slow burn rates for one model's serving SLO.

    An event is *good* when it completed ``ok`` within the p99 objective;
    ``burn = bad_fraction / (1 - availability)`` over each window — burn
    1.0 means the error budget is being consumed exactly at the rate the
    availability target allows, burn N means N× too fast. Crossing the
    threshold on the fast window is edge-triggered: one warning + one
    ``mxtpu_perf_regressions_total{metric="slo_burn_rate"}`` bump per
    excursion, re-armed when the burn falls back under.
    """

    def __init__(self, model: str, p99_ms: float,
                 availability: Optional[float] = None,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 burn_threshold: Optional[float] = None,
                 clock=time.monotonic):
        self.model = str(model)
        self.p99_ms = float(p99_ms)
        self.availability = float(
            get_env("MXNET_SERVE_SLO_AVAILABILITY", 0.999)
            if availability is None else availability)
        if not (0.0 < self.availability < 1.0):
            raise MXNetError("SLO availability target must be in (0, 1), "
                             "got %r" % (self.availability,))
        self.budget = 1.0 - self.availability
        if slow_window_s < fast_window_s:
            raise MXNetError("slow_window_s must be >= fast_window_s")
        self.windows = {"fast": float(fast_window_s),
                        "slow": float(slow_window_s)}
        self.burn_threshold = float(
            get_env("MXNET_SERVE_SLO_BURN_THRESHOLD", 2.0)
            if burn_threshold is None else burn_threshold)
        self._clock = clock
        # incremental sliding windows: per window a deque of (t, good)
        # plus a running bad count, pruned from the left on every touch —
        # record() stays O(1) amortized at any request rate, and the
        # hard cap bounds memory if the clock stalls
        self._win: Dict[str, deque] = {n: deque() for n in self.windows}
        self._bad: Dict[str, int] = {n: 0 for n in self.windows}
        self._lock = make_lock("observability.tracing.SLOTracker._lock")
        self.breaches: List[Dict[str, Any]] = []
        self._over = False                  # edge trigger state

    _MAX_EVENTS = 100_000                  # per-window hard cap

    def good(self, outcome: str, latency_ms: Optional[float]) -> bool:
        if outcome != "ok":
            return False
        if self.p99_ms > 0 and latency_ms is not None \
                and latency_ms > self.p99_ms:
            return False
        return True

    def _prune_locked(self, name: str, now: float) -> None:
        win, width = self._win[name], self.windows[name]
        horizon = now - width
        while win and (win[0][0] < horizon
                       or len(win) > self._MAX_EVENTS):
            _, g = win.popleft()
            if not g:
                self._bad[name] -= 1

    def record(self, outcome: str,
               latency_ms: Optional[float] = None) -> None:
        t = self._clock()
        g = self.good(outcome, latency_ms)
        with self._lock:
            for name in self.windows:
                self._win[name].append((t, g))
                if not g:
                    self._bad[name] += 1
                self._prune_locked(name, t)
        rates = self.burn_rates(publish=True)
        self._check(rates)

    def burn_rates(self, publish: bool = False) -> Dict[str, float]:
        t = self._clock()
        out: Dict[str, float] = {}
        with self._lock:
            for name in self.windows:
                self._prune_locked(name, t)
                n = len(self._win[name])
                bad_frac = (self._bad[name] / float(n)) if n else 0.0
                out[name] = bad_frac / max(1e-9, self.budget)
        if publish and _metrics.enabled():
            for name, burn in out.items():
                _catalog.SLO_BURN.set(round(burn, 4), model=self.model,
                                      window=name)
        return out

    def _check(self, rates: Dict[str, float]) -> None:
        fast = rates.get("fast", 0.0)
        fire = False
        with self._lock:
            # the edge-trigger state flips under the lock: record() runs
            # concurrently from the worker thread (_complete) and caller
            # threads (admission sheds, HTTP handlers) — an unlocked
            # read-then-set would double-fire one excursion
            if len(self._win["slow"]) < _SLO_MIN_EVENTS:
                return
            if fast > self.burn_threshold:
                if not self._over:
                    self._over = True
                    fire = True
                    self.breaches.append(
                        {"model": self.model, "burn": round(fast, 3),
                         "threshold": self.burn_threshold,
                         "p99_ms": self.p99_ms,
                         "availability": self.availability,
                         "time": time.time()})
            else:
                self._over = False
        if fire:
            if _metrics.enabled():
                _catalog.PERF_REGRESSIONS.inc(metric="slo_burn_rate")
            logger.warning(
                "SLO burn for model %r: fast-window burn rate %.2f "
                "exceeds threshold %.2f (p99 objective %.1f ms, "
                "availability target %.4f) — the error budget is "
                "being consumed %.1fx faster than the target allows; "
                "see tools/mxtrace.py for retained tail traces",
                self.model, fast, self.burn_threshold, self.p99_ms,
                self.availability, fast)

    def fast_burn(self) -> float:
        """The fast-window burn rate right now — THE readout the fleet
        controller's autoscale evaluator polls (``serving/fleet.py``):
        cheap (one prune under the lock), no gauge publish, no
        edge-trigger side effects."""
        return self.burn_rates().get("fast", 0.0)

    def events(self, window: str = "fast") -> int:
        """Events currently inside one window — consumers (the fleet
        evaluator) gate on this so an almost-empty window's burn rate
        (one bad request out of two) is not mistaken for an excursion."""
        t = self._clock()
        with self._lock:
            self._prune_locked(window, t)
            return len(self._win[window])

    def snapshot(self) -> Dict[str, Any]:
        return {"p99_ms": self.p99_ms, "availability": self.availability,
                "burn": self.burn_rates(), "breaches": len(self.breaches),
                "burn_threshold": self.burn_threshold}


# ---- process-wide default tracer -------------------------------------------
_default_lock = threading.Lock()
_default: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The process-wide trace ring (created on first use)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Tracer()
        return _default


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Swap the process-wide tracer (tests install a fresh ring)."""
    global _default
    with _default_lock:
        _default = tracer
