"""Substrate: typed config registry, logging, errors, small utilities.

TPU-native replacement for the reference's dmlc-core slice: the ~60 `MXNET_*`
environment variables read via ``dmlc::GetEnv`` at point of use (reference
``docs/faq/env_var.md``) and the ``dmlc::Parameter`` declarative structs
(reference ``include/dmlc/parameter.h`` usage, e.g. ``src/imperative/cached_op.h:32``)
collapse here into one typed, env-overridable config registry (SURVEY.md 5.6).
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Type

__all__ = [
    "MXNetError",
    "TransientKVError",
    "TransientIOError",
    "CorruptRecordError",
    "config",
    "register_config",
    "get_env",
    "string_types",
    "numeric_types",
    "integer_types",
    "logger",
]

logger = logging.getLogger("mxnet_tpu")

string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)


class MXNetError(RuntimeError):
    """Framework error type (mirrors the reference's ``MXNetError`` raised
    through the C-API thread-local error string, ``src/c_api/c_api_error.cc``)."""


class TransientKVError(MXNetError):
    """A kvstore operation failed for a plausibly-transient reason (the
    coordination service was briefly unreachable, a publish lost a race)
    after its internal retry budget was exhausted. The resilience layer
    (``mxnet_tpu.resilience.retry_transient``) treats this — unlike a bare
    ``MXNetError`` — as retryable with backoff rather than fatal."""


class TransientIOError(MXNetError):
    """A data read failed for a plausibly-transient reason (torn read off a
    network filesystem, a briefly-unreachable object store). Like
    :class:`TransientKVError`, ``retry_transient`` retries it with backoff
    instead of killing the run; ``io.ResilientDataIter`` raises it through
    only after the ``MXNET_IO_RETRY_*`` budget is exhausted."""


class CorruptRecordError(MXNetError):
    """A record decoded to garbage (bad magic, truncated payload, failed
    checksum). Deliberately NOT transient — re-reading the same bytes gives
    the same garbage — but ``io.ResilientDataIter`` may *skip* the batch
    within its ``MXNET_IO_SKIP_BUDGET`` instead of failing the run."""


@dataclass
class _ConfigEntry:
    name: str
    default: Any
    typ: Type
    doc: str = ""
    validator: Optional[Callable[[Any], bool]] = None


class _ConfigRegistry:
    """Typed config registry, env-overridable.

    Every knob is registered once with a type, default and docstring; reads
    check ``os.environ`` first (so ``MXNET_ENGINE_TYPE=...`` style overrides
    keep working) and fall back to programmatic ``set()`` values, then the
    default.
    """

    def __init__(self) -> None:
        self._entries: Dict[str, _ConfigEntry] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def register(self, name: str, default: Any, typ: Type = None, doc: str = "",
                 validator: Optional[Callable[[Any], bool]] = None) -> None:
        typ = typ or type(default)
        with self._lock:
            self._entries[name] = _ConfigEntry(name, default, typ, doc, validator)

    def _coerce(self, entry: _ConfigEntry, raw: str) -> Any:
        if entry.typ is bool:
            return raw.lower() not in ("0", "false", "off", "")
        return entry.typ(raw)

    def get(self, name: str, default: Any = None) -> Any:
        env = os.environ.get(name)
        entry = self._entries.get(name)
        if env is not None:
            if entry is not None:
                return self._coerce(entry, env)
            return env
        if name in self._values:
            return self._values[name]
        if entry is not None:
            return entry.default
        return default

    def set(self, name: str, value: Any) -> None:
        entry = self._entries.get(name)
        if entry is not None and entry.validator is not None and not entry.validator(value):
            raise MXNetError(f"invalid value {value!r} for config {name}")
        with self._lock:
            self._values[name] = value

    def describe(self) -> str:
        lines = []
        for e in sorted(self._entries.values(), key=lambda e: e.name):
            lines.append(f"{e.name} (default={e.default!r}, type={e.typ.__name__}): {e.doc}")
        return "\n".join(lines)

    def entries(self) -> Dict[str, _ConfigEntry]:
        return dict(self._entries)


config = _ConfigRegistry()


def register_config(name: str, default: Any, typ: Type = None, doc: str = "",
                    validator=None) -> None:
    config.register(name, default, typ, doc, validator)


def get_env(name: str, default: Any = None) -> Any:
    return config.get(name, default)


# Core knobs (parity with reference docs/faq/env_var.md where meaningful on TPU).
register_config("MXNET_ENGINE_TYPE", "XLAAsync", str,
                "Scheduler flavor. XLAAsync rides XLA's async dispatch; "
                "Naive forces synchronous execution after every op (debug).")
register_config("MXNET_EXEC_BULK_EXEC_TRAIN", True, bool,
                "Fuse op segments into one compiled XLA program during training.")
register_config("MXNET_EXEC_BULK_EXEC_INFERENCE", True, bool,
                "Fuse op segments into one compiled XLA program during inference.")
register_config("MXNET_BACKWARD_DO_MIRROR", False, bool,
                "Trade FLOPs for memory via rematerialization (jax.checkpoint).")
register_config("MXNET_KVSTORE_BIGARRAY_BOUND", 1 << 20, int,
                "Size above which a gradient is sharded across the reduce axis.")
register_config("MXNET_KVSTORE_ASYNC_MAX_STALENESS", 0, int,
                "dist_async only: max pushes a key's owner may lag before "
                "pushers throttle. 0 = unbounded (reference async behavior).")
register_config("MXNET_KVSTORE_ASYNC_GAP_TIMEOUT", 30.0, float,
                "dist_async only: seconds the key owner waits on a missing "
                "push sequence number (a pusher that died mid-send) before "
                "skipping it.")
register_config("MXNET_UPDATE_AGGREGATION_SIZE", 4, int,
                "Number of gradient tensors aggregated per fused allreduce bucket.")
register_config("MXNET_KVSTORE_HEARTBEAT_INTERVAL", 2.0, float,
                "Seconds between liveness heartbeats a dist kvstore rank "
                "writes to the coordination service.")
register_config("MXNET_KVSTORE_BARRIER_TIMEOUT", 300.0, float,
                "Seconds a dist kvstore barrier waits before raising with a "
                "dead-peer diagnosis (num_dead_node).")
register_config("MXNET_ENFORCE_DETERMINISM", False, bool,
                "Disallow non-deterministic reductions.")
register_config("MXNET_PROFILER_AUTOSTART", False, bool,
                "Start the chrome-trace profiler at import time.")
register_config("MXNET_DEFAULT_DTYPE", "float32", str,
                "Default dtype for created arrays.")
register_config("MXNET_TPU_MATMUL_PRECISION", "default", str,
                "jax matmul precision: default|high|highest.")
register_config("MXNET_SEED", -1, int, "Global PRNG seed; -1 = nondeterministic.")
register_config("MXNET_KV_RETRY_ATTEMPTS", 5, int,
                "Max attempts for transient kvstore coordination-service "
                "operations (e.g. dist_async weight publish) before raising "
                "TransientKVError.")
register_config("MXNET_KV_RETRY_BASE", 0.05, float,
                "Initial backoff delay (seconds) between kvstore retries; "
                "doubles every attempt.")
register_config("MXNET_KV_RETRY_MAX", 2.0, float,
                "Upper bound (seconds) on a single kvstore retry backoff "
                "delay.")
register_config("MXNET_KV_RETRY_JITTER", 0.25, float,
                "Multiplicative jitter fraction on kvstore retry delays "
                "(delay *= 1 + jitter*U[0,1)) to decorrelate rank retries.")
register_config("MXNET_RESILIENCE_RETRY_ATTEMPTS", 3, int,
                "Max attempts resilience.retry_transient makes around a "
                "transiently-failing training step.")
register_config("MXNET_RESILIENCE_RETRY_BASE", 0.5, float,
                "Initial backoff delay (seconds) for resilience.retry_transient.")
register_config("MXNET_RESILIENCE_RETRY_MAX", 30.0, float,
                "Upper bound (seconds) on a single resilience retry delay.")
register_config("MXNET_RESILIENCE_SAVE_EVERY", 0, int,
                "Default ResilientTrainer checkpoint cadence in steps "
                "(0 = only explicit/preemption saves).")
register_config("MXNET_RESILIENCE_KEEP", 3, int,
                "Committed checkpoints a ResilientTrainer keeps before "
                "pruning old steps.")
register_config("MXNET_RESILIENCE_STEP_DEADLINE", 0.0, float,
                "Seconds a single ResilientTrainer step may take before the "
                "watchdog dumps all thread stacks and fails loud "
                "(0 = watchdog off).")


class classproperty:  # noqa: N801  (descriptor, lowercase by convention)
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)
