"""``mx.rnn`` — legacy symbolic RNN cell API.

Reference parity: ``python/mxnet/rnn/`` (rnn_cell.py symbolic cells,
io.py BucketSentenceIter, rnn.py checkpoint helpers). The Gluon cell API
lives separately in ``mxnet_tpu.gluon.rnn``.
"""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ModifierCell, ZoneoutCell, ResidualCell)
from .io import encode_sentences, BucketSentenceIter
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint, do_rnn_checkpoint)
