"""RNN checkpoint helpers (legacy ``mx.rnn`` API).

Reference parity: ``python/mxnet/rnn/rnn.py`` — checkpoints store UNFUSED
(per-gate) weights so that models trained with ``FusedRNNCell`` can be
reloaded into unfused cells and vice versa.
"""
from __future__ import annotations

from ..model import save_checkpoint, load_checkpoint
from .rnn_cell import BaseRNNCell


def _as_list(cells):
    if isinstance(cells, BaseRNNCell):
        return [cells]
    return cells


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save a checkpoint, unpacking fused RNN weights first."""
    cells = _as_list(cells)
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint, packing weights back for the given cells."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    cells = _as_list(cells)
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback that checkpoints with unpacked RNN weights."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
