"""Symbolic RNN cells (legacy ``mx.rnn`` API).

Reference parity: ``python/mxnet/rnn/rnn_cell.py`` — the same cell classes,
unroll semantics, parameter names, and packed-weight layout, so checkpoints
keyed by ``{prefix}i2h_weight`` / ``{prefix}{dir}{layer}_i2h{gate}_weight``
interchange with reference-trained models.

The implementation is organized differently from the reference: all gated
recurrences (vanilla/LSTM/GRU) share ONE recipe — project input and hidden
state through two stacked FullyConnected ops, split per gate, combine — in
:func:`_gate_step`, and the fused packed-vector layout is described once by
:func:`_packed_layout` and walked by both pack and unpack. ``FusedRNNCell``
maps onto the ``RNN`` op, which lowers to a single big input-projection
matmul + a ``lax.scan`` hidden recurrence (see ``ops/rnn.py``) — there is no
cuDNN descriptor machinery to mirror. ``begin_state`` emits zeros with a
leading 1 ("unknown batch") that broadcasts against the first timestep,
since XLA graphs have static shapes and cannot carry the reference's
0-meaning-unknown batch dimension.
"""
from __future__ import annotations

from .. import symbol
from ..symbol import Symbol
from ..base import MXNetError
from ..ops.rnn import rnn_packed_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]

# gate suffixes per mode, in the packed layout's order
_GATES = {"rnn_relu": ("",), "rnn_tanh": ("",),
          "lstm": ("_i", "_f", "_c", "_o"), "gru": ("_r", "_z", "_o")}


# ------------------------------------------------------------ sequence forms
def _time_axis(layout):
    ax = layout.find("T")
    if ax < 0:
        raise MXNetError(f"layout {layout!r} has no time axis")
    return ax


def _split_merged_ndarray(inputs, length, layout):
    """A merged (N,T,C)/(T,N,C) NDArray → length-T list of (N, C) arrays
    (the reference's unroll accepts the merged imperative form too)."""
    from .._imperative import invoke
    ax = _time_axis(layout)
    if length is not None and inputs.shape[ax] != length:
        raise MXNetError(f"time axis has {inputs.shape[ax]} steps, "
                         f"expected {length}")
    return [invoke("squeeze", [invoke(
        "slice_axis", [inputs],
        {"axis": ax, "begin": t, "end": t + 1})], {"axis": ax})
        for t in range(inputs.shape[ax])]


def _to_steps(inputs, length, layout):
    """Whatever form ``inputs`` is in → a length-T list of (N, C) Symbols."""
    if isinstance(inputs, Symbol):
        if len(inputs.list_outputs()) != 1:
            raise MXNetError("unroll needs a single-output symbol as input")
        return list(symbol.SliceChannel(
            inputs, axis=_time_axis(layout), num_outputs=length,
            squeeze_axis=1))
    if hasattr(inputs, "ndim") and getattr(inputs, "ndim", 0) == 3:
        return _split_merged_ndarray(inputs, length, layout)
    steps = list(inputs)
    if length is not None and len(steps) != length:
        raise MXNetError(f"got {len(steps)} step inputs, expected {length}")
    return steps


def _to_merged(inputs, length, layout):
    """Whatever form ``inputs`` is in → one (N,T,C)/(T,N,C) Symbol."""
    if isinstance(inputs, Symbol):
        return inputs
    if hasattr(inputs, "ndim") and getattr(inputs, "ndim", 0) == 3:
        return inputs
    steps = list(inputs)
    if length is not None and len(steps) != length:
        raise MXNetError(f"got {len(steps)} step inputs, expected {length}")
    ax = _time_axis(layout)
    expanded = [symbol.expand_dims(s, axis=ax) for s in steps]
    return symbol.Concat(*expanded, dim=ax)


def _shape_outputs(outputs, length, layout, merge):
    """Present per-step outputs in the caller-requested form: True → merged
    Symbol, False → step list, None → leave as produced."""
    if merge is True:
        return _to_merged(outputs, length, layout)
    if merge is False:
        return _to_steps(outputs, length, layout)
    return outputs


# -------------------------------------------------------------- shared math
def _gate_step(mode, num_hidden, proj_i, proj_h, states, name,
               activation="tanh", get_act=None):
    """One recurrence step given the two stacked projections.

    ``proj_i``/``proj_h`` are the FullyConnected outputs of shape
    (N, num_gates*H) in the gate order of ``_GATES[mode]``.
    Returns (output, new_states).
    """
    if mode in ("rnn_relu", "rnn_tanh"):
        act = activation if get_act else mode.split("_")[1]
        out = get_act(proj_i + proj_h, act, name=name + "out") if get_act \
            else symbol.Activation(proj_i + proj_h, act_type=act)
        return out, [out]

    if mode == "lstm":
        parts = list(symbol.SliceChannel(proj_i + proj_h, num_outputs=4,
                                         name=name + "slice"))
        sig = lambda s, g: symbol.Activation(s, act_type="sigmoid",
                                             name=name + g)
        write = sig(parts[0], "i") * symbol.Activation(
            parts[2], act_type="tanh", name=name + "c")
        c_next = sig(parts[1], "f") * states[1] + write
        h_next = sig(parts[3], "o") * symbol.Activation(c_next,
                                                        act_type="tanh")
        return h_next, [h_next, c_next]

    if mode == "gru":
        gi = list(symbol.SliceChannel(proj_i, num_outputs=3,
                                      name=name + "i2h_slice"))
        gh = list(symbol.SliceChannel(proj_h, num_outputs=3,
                                      name=name + "h2h_slice"))
        reset = symbol.Activation(gi[0] + gh[0], act_type="sigmoid",
                                  name=name + "r_act")
        update = symbol.Activation(gi[1] + gh[1], act_type="sigmoid",
                                   name=name + "z_act")
        cand = symbol.Activation(gi[2] + reset * gh[2], act_type="tanh",
                                 name=name + "h_act")
        h_next = update * states[0] + (1.0 - update) * cand
        return h_next, [h_next]

    raise MXNetError(f"unknown cell mode {mode!r}")


class RNNParams(object):
    """Shared variable pool: ``get`` returns the same Variable for the same
    full name, so cells constructed on one RNNParams share weights
    (reference rnn_cell.py:78)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = symbol.Variable(full, **kwargs)
        return self._params[full]


class BaseRNNCell(object):
    """Abstract symbolic cell: step with ``__call__``, loop with ``unroll``."""

    def __init__(self, prefix="", params=None):
        self._own_params = params is None
        self._prefix = prefix
        self._params = RNNParams(prefix) if params is None else params
        self._modified = False
        self.reset()

    def reset(self):
        """Reset the per-unroll step counters."""
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        """One step: (step_input, states) -> (step_output, new_states)."""
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        """List of dicts describing each state's shape/layout."""
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Initial states. With the default func the batch dim is emitted as
        1 and broadcasts against the data (XLA static shapes cannot express
        the reference's 0 == unknown batch)."""
        if self._modified:
            raise MXNetError("cell is wrapped by a modifier; ask the "
                             "modifier for begin_state instead")
        kwargs = {k: v for k, v in kwargs.items() if k != "name"}
        states = []
        for info in self.state_info:
            self._init_counter += 1
            states.append(func(
                name=f"{self._prefix}begin_state_{self._init_counter}",
                shape=tuple(d or 1 for d in info["shape"]), **kwargs))
        return states

    # ---- packed (stacked-gate) <-> per-gate weight dict conversion -------
    def _gate_slices(self, group):
        """(full_param_name per gate) for the stacked i2h/h2h weight+bias."""
        return [(f"{self._prefix}{group}{g}_weight",
                 f"{self._prefix}{group}{g}_bias")
                for g in self._gate_names]

    def unpack_weights(self, args):
        """Split stacked i2h/h2h weights into per-gate arrays."""
        if not self._gate_names:
            return args.copy()
        out = args.copy()
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            w = out.pop(f"{self._prefix}{group}_weight")
            b = out.pop(f"{self._prefix}{group}_bias")
            for j, (wname, bname) in enumerate(self._gate_slices(group)):
                out[wname] = w[j * h:(j + 1) * h].copy()
                out[bname] = b[j * h:(j + 1) * h].copy()
        return out

    def pack_weights(self, args):
        """Inverse of :meth:`unpack_weights`."""
        if not self._gate_names:
            return args.copy()
        from ..ndarray import concat
        out = args.copy()
        for group in ("i2h", "h2h"):
            ws, bs = [], []
            for wname, bname in self._gate_slices(group):
                ws.append(out.pop(wname))
                bs.append(out.pop(bname))
            out[f"{self._prefix}{group}_weight"] = concat(*ws, dim=0)
            out[f"{self._prefix}{group}_bias"] = concat(*bs, dim=0)
        return out

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll ``length`` steps; returns (outputs, final_states)."""
        self.reset()
        steps = _to_steps(inputs, length, layout)
        states = begin_state if begin_state is not None else self.begin_state()
        outputs = []
        for x in steps:
            y, states = self(x, states)
            outputs.append(y)
        return _shape_outputs(outputs, length, layout, merge_outputs), states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class _GateCell(BaseRNNCell):
    """Shared implementation of the three stepped gated cells: two stacked
    FullyConnected projections + the :func:`_gate_step` recipe."""

    _mode = None  # set by subclasses

    def __init__(self, num_hidden, prefix, params, i2h_bias_init=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        get = self.params.get
        self._weights = {
            "i2h": (get("i2h_weight"),
                    get("i2h_bias", **({"init": i2h_bias_init}
                                       if i2h_bias_init else {}))),
            "h2h": (get("h2h_weight"), get("h2h_bias")),
        }

    @property
    def _gate_names(self):
        return tuple(_GATES[self._mode])

    @property
    def state_info(self):
        slots = 2 if self._mode == "lstm" else 1
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}
                for _ in range(slots)]

    def _project(self, data, group, name):
        w, b = self._weights[group]
        return symbol.FullyConnected(
            data=data, weight=w, bias=b,
            num_hidden=self._num_hidden * len(self._gate_names),
            name=name + group)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        proj_i = self._project(inputs, "i2h", name)
        proj_h = self._project(states[0], "h2h", name)
        return _gate_step(self._mode, self._num_hidden, proj_i, proj_h,
                          states, name,
                          activation=getattr(self, "_activation", None),
                          get_act=(self._get_activation
                                   if self._mode.startswith("rnn") else None))


class RNNCell(_GateCell):
    """Vanilla RNN: h' = act(W_x x + W_h h + b)."""

    _mode = "rnn_tanh"

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(num_hidden, prefix, params)
        self._activation = activation


class LSTMCell(_GateCell):
    """LSTM; gate order [i, f, c, o] matches the reference packed layout
    (reference rnn_cell.py:408)."""

    _mode = "lstm"

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(num_hidden, prefix, params,
                         i2h_bias_init=LSTMBiasInit(forget_bias))


class GRUCell(_GateCell):
    """GRU; gate order [r, z, o] (reference rnn_cell.py:469)."""

    _mode = "gru"

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(num_hidden, prefix, params)


# ------------------------------------------------------------------- fused
def _packed_layout(mode, num_layers, directions, input_size, hidden):
    """Yield (param_name_parts, shape) over the fused packed vector, in wire
    order: all weights layer-major (i2h gates then h2h gates per direction),
    then all biases in the same order (reference rnn-inl.h packed layout)."""
    gates = _GATES[mode]
    b = len(directions)
    for kind in ("weight", "bias"):
        for layer in range(num_layers):
            for d in directions:
                for group in ("i2h", "h2h"):
                    if kind == "bias":
                        shape = (hidden,)
                    elif group == "h2h":
                        shape = (hidden, hidden)
                    else:
                        in_dim = input_size if layer == 0 else hidden * b
                        shape = (hidden, in_dim)
                    for g in gates:
                        yield (d, layer, group, g, kind), shape


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN backed by the ``RNN`` op: one packed parameter
    vector, lowered to a big matmul + lax.scan (ops/rnn.py)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        super().__init__(prefix=f"{mode}_" if prefix is None else prefix,
                         params=params)
        self._num_hidden, self._num_layers, self._mode = \
            num_hidden, num_layers, mode
        self._bidirectional, self._dropout = bidirectional, dropout
        self._get_next_state, self._forget_bias = get_next_state, forget_bias
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get("parameters")

    @property
    def state_info(self):
        layers = len(self._directions) * self._num_layers
        n_states = 2 if self._mode == "lstm" else 1
        return [{"shape": (layers, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n_states)]

    @property
    def _gate_names(self):
        return _GATES[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _infer_input_size(self, total):
        """Invert rnn_packed_param_size for the input dim (monotone in li)."""
        size_of = lambda li: rnn_packed_param_size(
            self._mode, self._num_layers, self._bidirectional, li,
            self._num_hidden)
        li = 0
        while size_of(li) < total:
            li += 1
        if size_of(li) != total:
            raise MXNetError(
                f"packed vector of {total} elements matches no input size "
                f"for mode={self._mode} layers={self._num_layers}")
        return li

    def _param_name(self, key):
        d, layer, group, gate, kind = key
        return f"{self._prefix}{d}{layer}_{group}{gate}_{kind}"

    def unpack_weights(self, args):
        out = args.copy()
        packed = out.pop(self._parameter.name)
        li = self._infer_input_size(packed.size)
        pos = 0
        for key, shape in _packed_layout(self._mode, self._num_layers,
                                         self._directions, li,
                                         self._num_hidden):
            n = 1
            for d in shape:
                n *= d
            out[self._param_name(key)] = packed[pos:pos + n].reshape(shape).copy()
            pos += n
        if pos != packed.size:
            raise MXNetError("packed parameter vector has trailing elements")
        return out

    def pack_weights(self, args):
        from ..ndarray import concat
        out = args.copy()
        pieces = [out.pop(self._param_name(key)).reshape((-1,))
                  for key, _ in _packed_layout(
                      self._mode, self._num_layers, self._directions,
                      None, self._num_hidden)
                  ]
        out[self._parameter.name] = concat(*pieces, dim=0)
        return out

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        seq = _to_merged(inputs, length, layout)
        if _time_axis(layout) != 0:
            seq = symbol.swapaxes(seq, dim1=0, dim2=1)  # RNN op wants TNC

        init = begin_state if begin_state is not None else self.begin_state()
        state_kwargs = {"state": init[0]}
        if self._mode == "lstm":
            state_kwargs["state_cell"] = init[1]

        rnn = symbol.RNN(data=seq, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional,
                         p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode,
                         name=self._prefix + "rnn", **state_kwargs)

        if self._get_next_state:
            outputs = rnn[0]
            states = [rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]]
        else:
            outputs, states = rnn, []

        if _time_axis(layout) != 0:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        return _shape_outputs(outputs, length, layout, merge_outputs), states

    def unfuse(self):
        """Equivalent unfused SequentialRNNCell (reference rnn_cell.py:714)."""
        def make(cell_prefix):
            if self._mode == "lstm":
                return LSTMCell(self._num_hidden, prefix=cell_prefix,
                                forget_bias=self._forget_bias)
            if self._mode == "gru":
                return GRUCell(self._num_hidden, prefix=cell_prefix)
            return RNNCell(self._num_hidden,
                           activation=self._mode.split("_")[1],
                           prefix=cell_prefix)

        stack = SequentialRNNCell()
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make(f"{self._prefix}l{i}_"),
                    make(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(make(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


# -------------------------------------------------------------- containers
def _adopt_params(parent, *cells):
    """Merge child cells' variable pools into the parent's shared pool."""
    for cell in cells:
        parent.params._params.update(cell.params._params)


def _split_states(states, cells):
    """Partition a flat state list back into per-cell chunks."""
    chunks, pos = [], 0
    for cell in cells:
        n = len(cell.state_info)
        chunks.append(states[pos:pos + n])
        pos += n
    return chunks


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order each step."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            if not cell._own_params:
                raise MXNetError("specify params on SequentialRNNCell or on "
                                 "child cells, not both")
            cell.params._params.update(self.params._params)
        _adopt_params(self, cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        for cell, chunk in zip(self._cells, _split_states(states, self._cells)):
            if isinstance(cell, BidirectionalCell):
                raise MXNetError("BidirectionalCell cannot be stepped "
                                 "inside a SequentialRNNCell")
            inputs, new = cell(inputs, chunk)
            next_states.extend(new)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        init = self.begin_state() if begin_state is None else begin_state
        chunks = _split_states(init, self._cells)
        final_states = []
        last = len(self._cells) - 1
        for i, (cell, chunk) in enumerate(zip(self._cells, chunks)):
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=chunk, layout=layout,
                merge_outputs=merge_outputs if i == last else None)
            final_states.extend(states)
        return inputs, final_states


class DropoutCell(BaseRNNCell):
    """Applies dropout to the input; stateless."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = float(dropout)

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if merge_outputs is True or (merge_outputs is None
                                     and isinstance(inputs, Symbol)):
            # dropout is elementwise: one Dropout node on the merged sequence
            out, _ = self(_to_merged(inputs, length, layout), [])
            return out, []
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(BaseRNNCell):
    """Base for cells that decorate another cell (Zoneout/Residual); params
    belong to the wrapped cell."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(func=func, **kwargs)
        finally:
            self.base_cell._modified = True

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout: randomly hold the previous output/state instead of the new
    one (Krueger et al. 2017)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        if isinstance(base_cell, FusedRNNCell):
            raise MXNetError("unfuse() the cell before applying zoneout")
        if isinstance(base_cell, BidirectionalCell):
            raise MXNetError("BidirectionalCell cannot be zoned out "
                             "(it cannot be stepped)")
        super().__init__(base_cell)
        self.zoneout_outputs, self.zoneout_states = \
            zoneout_outputs, zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    @staticmethod
    def _keep_mask(p, like):
        # Dropout of ones == a 0/1 keep mask scaled by 1/(1-p); where() only
        # cares about zero vs nonzero, so the scale is harmless
        return symbol.Dropout(symbol.ones_like(like), p=p)

    def __call__(self, inputs, states):
        new_out, new_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0.:
            held = self.prev_output if self.prev_output is not None \
                else symbol.zeros_like(new_out)
            new_out = symbol.where(
                self._keep_mask(self.zoneout_outputs, new_out), new_out, held)
        if self.zoneout_states > 0.:
            new_states = [
                symbol.where(self._keep_mask(self.zoneout_states, ns), ns, os)
                for ns, os in zip(new_states, states)]
        self.prev_output = new_out
        return new_out, new_states


class ResidualCell(ModifierCell):
    """Adds the step input to the wrapped cell's output."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        try:
            outputs, states = self.base_cell.unroll(
                length, inputs=inputs, begin_state=begin_state, layout=layout,
                merge_outputs=merge_outputs)
        finally:
            self.base_cell._modified = True
        if merge_outputs is None:
            merge_outputs = isinstance(outputs, Symbol)
        if merge_outputs:
            outputs = outputs + _to_merged(inputs, length, layout)
        else:
            outputs = [o + x for o, x in
                       zip(outputs, _to_steps(inputs, length, layout))]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence and
    concatenates per-step outputs on the feature axis."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            if not (l_cell._own_params and r_cell._own_params):
                raise MXNetError("specify params on BidirectionalCell or on "
                                 "child cells, not both")
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        _adopt_params(self, l_cell, r_cell)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        init = self.begin_state() if begin_state is None else begin_state
        fwd, bwd = self._cells
        fwd_chunk, bwd_chunk = _split_states(init, self._cells)
        t_ax = _time_axis(layout)

        # keep the input in its native form: a merged Symbol reverses with
        # ONE reverse node (fused children then stay O(1) in graph size),
        # a step list reverses as a list
        if isinstance(inputs, Symbol):
            bwd_in = symbol.reverse(inputs, axis=t_ax)
        else:
            bwd_in = list(reversed(list(inputs)))

        # each direction unrolls in its natural/requested form; with
        # merge_outputs=None the children's output form decides the result
        # form (stepped cells yield lists, fused cells yield one Symbol)
        fwd_out, fwd_states = fwd.unroll(length, inputs,
                                         begin_state=fwd_chunk, layout=layout,
                                         merge_outputs=merge_outputs)
        bwd_out, bwd_states = bwd.unroll(length, bwd_in,
                                         begin_state=bwd_chunk, layout=layout,
                                         merge_outputs=merge_outputs)
        if merge_outputs is None:
            merge_outputs = (isinstance(fwd_out, Symbol)
                             and isinstance(bwd_out, Symbol))
        if merge_outputs:
            # O(1) graph nodes: reverse the backward stream on the time axis
            # and join the feature axes
            bwd_rev = symbol.reverse(_to_merged(bwd_out, length, layout),
                                     axis=t_ax)
            outputs = symbol.Concat(_to_merged(fwd_out, length, layout),
                                    bwd_rev, dim=2,
                                    name=f"{self._output_prefix}out")
        else:
            outputs = [
                symbol.Concat(f, b, dim=1, name=f"{self._output_prefix}t{t}")
                for t, (f, b) in enumerate(
                    zip(_to_steps(fwd_out, length, layout),
                        reversed(_to_steps(bwd_out, length, layout))))]
        return outputs, fwd_states + bwd_states


def LSTMBiasInit(forget_bias):
    """Initializer spec for the stacked LSTM i2h bias (forget gate filled
    with ``forget_bias``); resolved lazily to avoid an import cycle."""
    from ..initializer import LSTMBias
    return LSTMBias(forget_bias=forget_bias)
