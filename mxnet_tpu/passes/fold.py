"""Constant folding + dead-branch elimination.

Subgraphs reachable only from creation ops (``zeros``/``full``/``arange``/
…) with static attrs are evaluated ONCE at pass time and replaced by a
single ``_graph_const`` node, and a ``where`` whose condition folds to a
uniform boolean drops the dead branch entirely — the *rewrite* form of what
mxlint's MXL-G106 only detects.  Serialized-graph dead-node removal rides
the same pass through ``tools/mxopt.py`` (a ``load_json``→``tojson`` round
trip keeps only head-reachable nodes; the CLI reports the count).

Folding is size-capped: a materialized constant above
``MAX_CONST_ELEMENTS`` stays a creator op (baking a megabyte tuple into
node attrs would bloat the jit cache key and the JSON), and random/host
ops never fold.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..ops.registry import get_op, register as _register_op
from ..symbol.symbol import Symbol, _Node
from .manager import Pass, PassContext, Namer, is_barrier, register_pass

__all__ = ["ConstantFoldPass", "MAX_CONST_ELEMENTS"]

#: largest folded constant materialized into a ``_graph_const`` node
MAX_CONST_ELEMENTS = 4096
#: largest intermediate value the folder will evaluate at all
_MAX_EVAL_ELEMENTS = 1 << 16


@_register_op("_graph_const", differentiable=False)
def _graph_const(value=(), shape=(), dtype="float32"):
    """A pass-materialized constant; ``value`` is the flat element tuple."""
    import jax.numpy as jnp
    return jnp.asarray(np.array(value, dtype=np.dtype(str(dtype)))
                       .reshape(tuple(shape)))


#: zero-input creation ops (static attrs fully determine the value)
CREATORS = frozenset({
    "_zeros", "zeros", "_ones", "ones", "_full", "full", "_arange",
    "arange", "_linspace", "linspace", "_eye", "eye", "_graph_const",
})

#: pure ops the folder evaluates when every input is constant
FOLDABLE = frozenset({
    "transpose", "Reshape", "reshape", "Flatten", "flatten", "expand_dims",
    "squeeze", "Cast", "cast", "negative", "abs", "exp", "log", "sqrt",
    "square", "clip", "_plus_scalar", "_minus_scalar", "_rminus_scalar",
    "_mul_scalar", "_div_scalar", "_rdiv_scalar", "_power_scalar",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "zeros_like", "ones_like", "Concat", "concat", "where",
})


def _static_attrs(attrs) -> bool:
    def ok(v):
        if isinstance(v, (int, float, bool, str, bytes, type(None))):
            return True
        if isinstance(v, (tuple, list)):
            return all(ok(x) for x in v)
        return False
    return all(ok(v) for v in (attrs or {}).values())


def _uniform_bool(arr: np.ndarray):
    flat = np.asarray(arr).ravel()
    if flat.size == 0:
        return None
    if np.all(flat):
        return True
    if not np.any(flat):
        return False
    return None


@register_pass
class ConstantFoldPass(Pass):
    name = "fold"

    def apply(self, sym: Symbol, ctx: PassContext):
        nodes = sym.topo_nodes()
        if not any(n.op in CREATORS for n in nodes if not n.is_var):
            return sym, 0

        # ---- evaluate the constant frontier
        const: Dict[Tuple[int, int], np.ndarray] = {}
        creator = set()
        for node in nodes:
            if node.is_var or is_barrier(node):
                continue
            if node.op not in CREATORS and node.op not in FOLDABLE:
                continue
            try:
                opdef = get_op(node.op)
            except Exception:
                continue
            if opdef.needs_rng or opdef.host or node.num_outputs != 1:
                continue
            if not _static_attrs(node.attrs):
                continue
            ins = []
            all_const = True
            for (src, idx) in node.inputs:
                v = const.get((id(src), idx))
                if v is None:
                    all_const = False
                    break
                ins.append(v)
            if not all_const:
                continue
            try:
                out = opdef.fn(*ins, **dict(node.attrs))
                out = np.asarray(out)
            except Exception:
                continue
            if out.size > _MAX_EVAL_ELEMENTS:
                continue
            try:
                # the value must survive a tolist()/np.dtype(str) round
                # trip into _graph_const attrs (bf16 & friends need not)
                np.dtype(str(out.dtype))
            except TypeError:
                continue
            const[(id(node), 0)] = out
            if node.op in CREATORS:
                creator.add(id(node))

        if not const:
            return sym, 0

        namer = Namer(sym)
        remap: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        const_nodes: Dict[Tuple[int, int], _Node] = {}
        count = 0
        # dead-branch elimination needs output avals (where() broadcasts:
        # passing a branch through is only sound when its shape already IS
        # the result shape) — annotate lazily, only if a candidate exists
        avals = None
        if any(n.op == "where" and len(n.inputs) == 3
               and (id(n.inputs[0][0]), n.inputs[0][1]) in const
               for n in nodes if not n.is_var):
            avals = ctx.annotate(sym)

        def const_entry(entry):
            nonlocal count
            k = (id(entry[0]), entry[1])
            if k not in const_nodes:
                v = const[k]
                node = _Node("_graph_const",
                             namer.fresh(entry[0].name + "_folded"),
                             {"value": tuple(v.ravel().tolist()),
                              "shape": tuple(int(d) for d in v.shape),
                              "dtype": str(v.dtype)}, [])
                const_nodes[k] = node
                count += 1
            return (const_nodes[k], 0)

        def map_entry(entry):
            src, idx = entry
            if src.is_var:
                return (src, idx)
            k = (id(src), idx)
            # fold a COMPUTED constant into a _graph_const; plain creators
            # stay as they are (replacing zeros() with a zeros tuple is
            # pure churn), oversized values stay live ops
            if k in const and id(src) not in creator \
                    and src.op != "_graph_const" \
                    and const[k].size <= MAX_CONST_ELEMENTS:
                return const_entry(entry)
            return remap[k]

        for node in nodes:
            if node.is_var:
                continue
            # dead-branch elimination: a where() whose condition folded to
            # a uniform boolean passes one branch straight through
            if node.op == "where" and len(node.inputs) == 3 \
                    and not is_barrier(node):
                cv = const.get((id(node.inputs[0][0]), node.inputs[0][1]))
                u = _uniform_bool(cv) if cv is not None else None
                if u is not None and avals is not None:
                    live = node.inputs[1] if u else node.inputs[2]
                    out_av = avals.get((id(node), 0))
                    live_av = avals.get((id(live[0]), live[1]))
                    if out_av is not None and live_av is not None \
                            and tuple(out_av.shape) == tuple(live_av.shape) \
                            and out_av.dtype == live_av.dtype:
                        remap[(id(node), 0)] = map_entry(live)
                        count += 1
                        continue
            ins = [map_entry(e) for e in node.inputs]
            if all(a is b[0] and i == b[1]
                   for (a, i), b in zip(node.inputs, ins)):
                nn = node
            else:
                nn = _Node(node.op, node.name, dict(node.attrs), ins)
                nn._attr_dict = dict(node._attr_dict)
            for i in range(node.num_outputs):
                remap.setdefault((id(node), i), (nn, i))

        if count == 0:
            return sym, 0
        new_heads = []
        for e in sym._outputs:
            new_heads.append(map_entry(e) if not e[0].is_var else e)
        return Symbol(new_heads), count
