"""Graph-pass manager — optimizing rewrites over symbol graphs.

``analysis/`` is the read-only half of the compiler-pass framework
(mxlint); this package is the write half: Relay/TVM-style rewrite passes
that turn the measured perf levers (NHWC layout, space-to-depth stem,
constant folding, fusion-friendly reordering) into automatic defaults every
captured graph inherits.  ``Module`` and
:class:`~mxnet_tpu.parallel.DataParallelTrainer` run the default pipeline
unless constructed with ``passes=False``; ``MXNET_PASSES`` tunes it;
``tools/mxopt.py`` is the CLI.  Catalog: docs/passes.md.

    from mxnet_tpu import passes
    res = passes.PassManager().run(sym, shapes={"data": (8, 3, 224, 224)})
    res.symbol          # the rewritten graph
    res.counts          # per-pass rewrite counts
    res.var_transforms  # value transforms for re-homed parameters
"""
from .manager import (Pass, PassContext, PassManager, PassResult,
                      DEFAULT_PIPELINE, PASS_REGISTRY, register_pass,
                      default_names, resolve, annotate_graph, apply_spec,
                      spec_shape, provenance,
                      s2d_weight_forward, s2d_weight_inverse)
# importing the pass modules populates PASS_REGISTRY
from .fold import ConstantFoldPass
from .layout import LayoutPass
from .s2d import SpaceToDepthPass
from .fusion import FusionReorderPass
# the quantization passes register too (names: quantize/requantize/
# dequantize) but stay OPT-IN — quantization changes numerics, so they are
# never part of DEFAULT_PIPELINE.  Imported as a module (not names) so the
# quant→passes→quant import cycle resolves in either entry order;
# mxnet_tpu.quant is the driving surface for these passes.
from ..quant import qpass as _quant_qpass  # noqa: F401

__all__ = ["Pass", "PassContext", "PassManager", "PassResult",
           "DEFAULT_PIPELINE", "PASS_REGISTRY", "register_pass",
           "default_names", "resolve", "annotate_graph", "apply_spec",
           "spec_shape", "provenance",
           "s2d_weight_forward", "s2d_weight_inverse",
           "ConstantFoldPass", "LayoutPass", "SpaceToDepthPass",
           "FusionReorderPass"]
