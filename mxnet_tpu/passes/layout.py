"""Automatic NCHW→NHWC layout propagation (the measured r4 perf win).

TPU convs want channel-last: the MXU contracts over the minor dimension,
and an NCHW conv pays per-step relayouts the hand-flagged ``layout="NHWC"``
nets avoid.  This pass makes that a *graph rewrite*: every NCHW 2-D
Convolution/Pooling (and the BatchNorms riding on them) is converted to its
NHWC twin, the layout is pushed through elementwise ops so interior
transposes cancel structurally, and — where the caller allows re-homing —
conv weight variables become OHWI and rank-4 input variables become
channel-last, leaving ZERO residual transposes.  With re-homing the
rewritten ResNet graph is node-for-node the one ``layout="NHWC"`` would
have built by hand (the bitwise HLO acceptance test in
tests/test_passes.py).

Layout decisions are dataflow: an entry is *NHWC-homed* when its producer
emits channel-last; elementwise consumers follow suit when every operand
is homed / rank-0 / transposable rank-4; everything else consumes the
original layout through a lazily-materialized back-transpose.  Global-pool
outputs are marked spatially degenerate so Flatten/FullyConnected consume
them channel-last directly ((B,1,1,C) and (B,C,1,1) flatten identically).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..symbol.symbol import Symbol, _Node
from .manager import (Pass, PassContext, Namer, is_barrier, register_pass,
                      _NCHW_SPELLINGS)

__all__ = ["LayoutPass", "is_nchw_conv"]

TO_NHWC = (0, 2, 3, 1)     # NCHW data -> NHWC; OIHW weight -> OHWI
TO_NCHW = (0, 3, 1, 2)

#: shape-preserving single-array-input ops the layout propagates through
#: bitwise (note: Dropout is deliberately absent — its mask draw depends on
#: the operand shape ORDER, so a permuted trace is only statistically
#: equivalent and would break the bitwise/tolerance equivalence contract)
UNARY_ELEMWISE = frozenset({
    "Activation", "relu", "sigmoid", "tanh", "softsign", "exp", "log",
    "sqrt", "square", "abs", "negative", "clip", "Cast", "cast",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_maximum_scalar",
    "_minimum_scalar",
})

#: broadcasting/elementwise multi-input ops: safe when every operand is
#: homed, rank-0, or a transposable rank-4 (a 0<rank<4 operand would
#: broadcast against DIFFERENT axes after the permutation — bail)
MULTI_ELEMWISE = frozenset({
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
})

FLATTEN_OPS = frozenset({"Flatten", "flatten"})


def _conv_eligible(node) -> bool:
    if node.op != "Convolution":
        return False
    attrs = node.attrs or {}
    if attrs.get("layout") not in _NCHW_SPELLINGS:
        return False
    return len(tuple(attrs.get("kernel") or ())) == 2


#: the ONE "NCHW 2-D conv the layout pass would convert" predicate —
#: shared by mxlint MXL-G107 and the trainer's capture-time counting so
#: the lint rule can never drift from what the pass actually rewrites
is_nchw_conv = _conv_eligible


def _pool_eligible(node, rank: Optional[int] = None) -> bool:
    """2-D pooling only.  A len-2 kernel implies rank-4 data by op
    semantics; a global pool declares no meaningful kernel, so it needs
    the annotated rank (``rank=None`` = unknown => not eligible) — an NCW/
    NCDHW global pool must never receive rank-4 transposes."""
    if node.op != "Pooling":
        return False
    attrs = node.attrs or {}
    if attrs.get("layout") not in _NCHW_SPELLINGS:
        return False
    kernel = tuple(attrs.get("kernel") or ())
    if len(kernel) == 2 and not attrs.get("global_pool"):
        return True
    return bool(attrs.get("global_pool")) and rank == 4


def _bn_eligible(node, rank: Optional[int]) -> bool:
    if node.op != "BatchNorm":
        return False
    try:
        axis = int((node.attrs or {}).get("axis", 1))
    except (TypeError, ValueError):
        return False
    return axis == 1 and rank == 4


def _truthy(v) -> bool:
    return str(v).lower() in ("1", "true", "yes", "on")


@register_pass
class LayoutPass(Pass):
    name = "layout"

    def apply(self, sym: Symbol, ctx: PassContext):
        nodes = sym.topo_nodes()
        has_conv_pool = any(_conv_eligible(n) or _pool_eligible(n)
                            for n in nodes if not n.is_var and
                            not is_barrier(n))
        if not has_conv_pool:
            return sym, 0
        avals = ctx.annotate(sym)

        def rank_of(entry) -> Optional[int]:
            av = avals.get((id(entry[0]), entry[1]))
            return len(av.shape) if av is not None else None

        namer = Namer(sym)
        orig_map: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        nhwc_map: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        degen = set()          # old entries with 1x1 spatial extent
        used_orig_vars = set()  # ids of vars consumed in original layout
        var_ph: Dict[int, Dict] = {}   # var id -> placeholder info
        rehomed_inputs: Dict[int, _Node] = {}   # var id -> NHWC var clone
        count = 0

        # rank-4 input variables under the NHWC feed contract are
        # re-declared channel-last up front: the caller COMMITS to feeding
        # NHWC, so even partially-converted graphs stay consistent
        for n in nodes:
            if n.is_var and ctx.can_rehome_input(n.name):
                shp = ctx.shapes.get(n.name)
                if shp is not None and len(shp) == 4:
                    clone = _Node(None, n.name, {}, [])
                    clone._attr_dict = dict(n._attr_dict)
                    if "__shape__" in clone._attr_dict:
                        clone._attr_dict["__shape__"] = str(
                            tuple(shp[i] for i in TO_NHWC))
                    rehomed_inputs[id(n)] = clone
                    ctx.input_layouts[n.name] = "NHWC"

        def get_orig(entry):
            src, idx = entry
            if src.is_var:
                if id(src) in rehomed_inputs:
                    # NHWC-declared input: original layout via back-transpose
                    k = (id(src), idx)
                    if k not in orig_map:
                        t = _Node("transpose",
                                  namer.fresh(src.name + "_nchw"),
                                  {"axes": TO_NCHW},
                                  [(rehomed_inputs[id(src)], 0)])
                        orig_map[k] = (t, 0)
                    return orig_map[k]
                used_orig_vars.add(id(src))
                return (src, idx)
            k = (id(src), idx)
            if k in orig_map:
                return orig_map[k]
            nh = nhwc_map[k]
            t = _Node("transpose", namer.fresh(src.name + "_nchw"),
                      {"axes": TO_NCHW}, [nh])
            orig_map[k] = (t, 0)
            return (t, 0)

        def nhwc_available(entry) -> bool:
            src, idx = entry
            if src.is_var:
                return id(src) in rehomed_inputs
            return (id(src), idx) in nhwc_map

        def get_nhwc(entry, perm=TO_NHWC):
            src, idx = entry
            if src.is_var:
                if id(src) in rehomed_inputs:
                    return (rehomed_inputs[id(src)], 0)
                ph = var_ph.get(id(src))
                if ph is not None:
                    if ph["perm"] == perm:
                        return (ph["node"], 0)
                    # conflicting perms on one var: plain transpose
                    t = _Node("transpose", namer.fresh(src.name + "_nhwc"),
                              {"axes": perm}, [(src, 0)])
                    used_orig_vars.add(id(src))
                    return (t, 0)
                node = _Node("transpose", namer.fresh(src.name + "_nhwc"),
                             {"axes": perm}, [(src, 0)])
                var_ph[id(src)] = {"node": node, "perm": perm, "var": src}
                return (node, 0)
            k = (id(src), idx)
            if k in nhwc_map:
                return nhwc_map[k]
            o = orig_map[k]
            t = _Node("transpose", namer.fresh(src.name + "_nhwc"),
                      {"axes": TO_NHWC}, [o])
            nhwc_map[k] = (t, 0)
            return (t, 0)

        def emit(node, new_inputs, attrs=None):
            """Clone ``node`` with mapped inputs; reuse the original object
            when nothing changed (keeps untouched subtrees shared)."""
            if attrs is None and \
                    all(a is b[0] and i == b[1]
                        for (a, i), b in zip(node.inputs, new_inputs)) \
                    and len(new_inputs) == len(node.inputs):
                return node
            nn = _Node(node.op, node.name,
                       dict(node.attrs) if attrs is None else attrs,
                       list(new_inputs))
            nn._attr_dict = dict(node._attr_dict)
            return nn

        def register(node, nn, target_map):
            for i in range(node.num_outputs):
                target_map[(id(node), i)] = (nn, i)

        for node in nodes:
            if node.is_var:
                continue
            if is_barrier(node):
                nn = emit(node, [get_orig(e) for e in node.inputs])
                register(node, nn, orig_map)
                continue

            if _conv_eligible(node):
                attrs = dict(node.attrs)
                attrs["layout"] = "NHWC"
                ins = [get_nhwc(node.inputs[0]),
                       get_nhwc(node.inputs[1], perm=TO_NHWC)]
                ins += [get_orig(e) for e in node.inputs[2:]]
                nn = emit(node, ins, attrs)
                register(node, nn, nhwc_map)
                count += 1
                continue

            if _pool_eligible(node, rank_of(node.inputs[0])):
                attrs = dict(node.attrs)
                attrs["layout"] = "NHWC"
                nn = emit(node, [get_nhwc(node.inputs[0])], attrs)
                register(node, nn, nhwc_map)
                if _truthy(attrs.get("global_pool")):
                    degen.add((id(node), 0))
                elif (id(node.inputs[0][0]), node.inputs[0][1]) in degen:
                    degen.add((id(node), 0))
                count += 1
                continue

            if _bn_eligible(node, rank_of(node.inputs[0])) \
                    and nhwc_available(node.inputs[0]):
                attrs = dict(node.attrs)
                attrs["axis"] = -1
                ins = [get_nhwc(node.inputs[0])]
                ins += [get_orig(e) for e in node.inputs[1:]]
                nn = emit(node, ins, attrs)
                # out0 is channel-last; the mean/var outputs are rank-1 and
                # layout-free (registered identically in both views)
                nhwc_map[(id(node), 0)] = (nn, 0)
                for i in range(1, node.num_outputs):
                    nhwc_map[(id(node), i)] = (nn, i)
                    orig_map[(id(node), i)] = (nn, i)
                if (id(node.inputs[0][0]), node.inputs[0][1]) in degen:
                    degen.add((id(node), 0))
                count += 1
                continue

            if node.op in UNARY_ELEMWISE and len(node.inputs) == 1 \
                    and nhwc_available(node.inputs[0]):
                nn = emit(node, [get_nhwc(node.inputs[0])])
                register(node, nn, nhwc_map)
                if (id(node.inputs[0][0]), node.inputs[0][1]) in degen:
                    degen.add((id(node), 0))
                continue

            if node.op in MULTI_ELEMWISE and node.inputs:
                homed = [nhwc_available(e) for e in node.inputs]
                ranks = [rank_of(e) for e in node.inputs]
                convertible = any(homed) and all(
                    h or r == 0 or r == 4
                    for h, r in zip(homed, ranks))
                if convertible:
                    ins = [get_orig(e) if (not h and r == 0)
                           else get_nhwc(e)
                           for e, h, r in zip(node.inputs, homed, ranks)]
                    nn = emit(node, ins)
                    register(node, nn, nhwc_map)
                    if all((id(e[0]), e[1]) in degen or r == 0
                           for e, r in zip(node.inputs, ranks)):
                        degen.add((id(node), 0))
                    continue

            if node.op in FLATTEN_OPS and len(node.inputs) == 1:
                e = node.inputs[0]
                if nhwc_available(e) and (id(e[0]), e[1]) in degen:
                    # (B,1,1,C) flattens to the same (B,C) as (B,C,1,1):
                    # consume channel-last directly, no transpose
                    nn = emit(node, [get_nhwc(e)])
                    register(node, nn, orig_map)
                    continue

            if node.op == "FullyConnected" and node.inputs:
                e = node.inputs[0]
                if nhwc_available(e) and (id(e[0]), e[1]) in degen \
                        and (node.attrs or {}).get("flatten", True) \
                        is not False:
                    ins = [get_nhwc(e)] + [get_orig(x)
                                           for x in node.inputs[1:]]
                    nn = emit(node, ins)
                    register(node, nn, orig_map)
                    continue

            # default: consume and produce the original layout
            nn = emit(node, [get_orig(e) for e in node.inputs])
            register(node, nn, orig_map)

        if count == 0:
            return sym, 0

        # resolve variable placeholders: a var consumed ONLY channel-last
        # (and re-homable by policy) mutates its placeholder into a fresh
        # NHWC-declared variable, recording the value transform; otherwise
        # the placeholder stays a real transpose
        for vid, ph in var_ph.items():
            var = ph["var"]
            if vid in used_orig_vars or not ctx.can_rehome_param(var.name):
                continue
            node = ph["node"]
            node.op = None
            node.name = var.name
            node.attrs = {}
            node.inputs = []
            node.num_outputs = 1
            node._attr_dict = dict(var._attr_dict)
            if "__shape__" in node._attr_dict:
                from ..analysis.graph_lint import _parse_shape_attr
                shp = _parse_shape_attr(node._attr_dict["__shape__"])
                if shp is not None and len(shp) == len(ph["perm"]):
                    node._attr_dict["__shape__"] = str(
                        tuple(shp[i] for i in ph["perm"]))
            ctx.add_var_transform(var.name, ("transpose", ph["perm"]))

        new_heads = [get_orig(e) for e in sym._outputs]
        return Symbol(new_heads), count
