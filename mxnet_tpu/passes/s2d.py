"""Space-to-depth stem rewrite for stride-2 input convs (MLPerf ResNet).

A 7x7/s2 conv over 3 input channels wastes the MXU's 128-deep contraction
lanes; the block-2 space-to-depth reparameterization (pad → reshape →
transpose → reshape → 4x4/s1 conv over 12 channels) computes EXACTLY the
same linear map with 4x the arithmetic intensity
(``tests/test_s2d_stem.py`` pins the algebra; the model zoo's
``SpaceToDepthStem`` is the hand-built form).  This pass applies it as a
graph rewrite to any eligible NHWC conv — stride (2,2), dilation 1, no
groups, few input channels (a stem signature), even padded spatial extent —
so ``stem_s2d=True`` stops being a flag every workload must rediscover.

The conv weight re-homes from (O,kh,kw,C) to (O,⌈kh/2⌉,⌈kw/2⌉,4C) with the
value transform recorded in the :class:`~.manager.PassResult` (capture
applies it to the parameter, ``sync_to_net`` inverts it).  When the weight
variable cannot be re-homed (shared, or re-homing disabled) the same
rearrangement is emitted as in-graph ops on the weight — XLA folds it once
per step.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..symbol.symbol import Symbol, _Node
from .manager import Pass, PassContext, Namer, is_barrier, register_pass

__all__ = ["SpaceToDepthPass"]

#: a conv is stem-shaped when depth-to-space quadrupling keeps it tiny on
#: the contraction axis (3 -> 12 channels; anything past this already
#: feeds the MXU adequately and the rewrite only adds reshapes)
MAX_IN_CHANNELS = 4


def _pair(v) -> Tuple[int, ...]:
    t = tuple(v) if isinstance(v, (tuple, list)) else (v, v)
    return tuple(int(x) for x in t)


@register_pass
class SpaceToDepthPass(Pass):
    name = "s2d"

    def _eligible(self, node, avals) -> Optional[Dict]:
        if node.op != "Convolution" or is_barrier(node):
            return None
        attrs = node.attrs or {}
        if str(attrs.get("layout")) != "NHWC":
            return None
        kernel = tuple(attrs.get("kernel") or ())
        if len(kernel) != 2 or max(kernel) < 2:
            return None
        if _pair(attrs.get("stride") or (1, 1)) != (2, 2):
            return None
        dil = tuple(attrs.get("dilate") or ())
        if dil and _pair(dil) != (1, 1):
            return None
        if int(attrs.get("num_group", 1) or 1) != 1:
            return None
        av = avals.get((id(node.inputs[0][0]), node.inputs[0][1]))
        if av is None or len(av.shape) != 4:
            return None
        B, H, W, C = av.shape
        if C > MAX_IN_CHANNELS:
            return None
        pad = _pair(attrs.get("pad") or (0, 0))
        if (H + 2 * pad[0]) % 2 or (W + 2 * pad[1]) % 2:
            return None
        kh, kw = int(kernel[0]), int(kernel[1])
        return {"kh": kh, "kw": kw, "pad": pad, "C": int(C),
                "O": int(attrs.get("num_filter", 0) or 0)}

    def apply(self, sym: Symbol, ctx: PassContext):
        nodes = sym.topo_nodes()
        if not any(n.op == "Convolution" for n in nodes if not n.is_var):
            return sym, 0
        avals = ctx.annotate(sym)
        plans = {id(n): p for n in nodes if not n.is_var
                 for p in (self._eligible(n, avals),) if p is not None}
        if not plans:
            return sym, 0

        # weight vars re-home only when this conv is their sole consumer
        consumers: Dict[int, int] = {}
        for n in nodes:
            for (src, _) in n.inputs:
                consumers[id(src)] = consumers.get(id(src), 0) + 1
        for (hn, _) in sym._outputs:
            consumers[id(hn)] = consumers.get(id(hn), 0) + 1

        namer = Namer(sym)
        remap: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        var_sub: Dict[int, _Node] = {}
        count = 0

        def map_entry(entry):
            src, idx = entry
            if src.is_var:
                return (var_sub.get(id(src), src), idx)
            return remap[(id(src), idx)]

        def clone_default(node):
            ins = [map_entry(e) for e in node.inputs]
            if all(a is b[0] and i == b[1]
                   for (a, i), b in zip(node.inputs, ins)):
                return node
            nn = _Node(node.op, node.name, dict(node.attrs), ins)
            nn._attr_dict = dict(node._attr_dict)
            return nn

        for node in nodes:
            if node.is_var:
                continue
            plan = plans.get(id(node))
            if plan is None:
                nn = clone_default(node)
                for i in range(node.num_outputs):
                    remap[(id(node), i)] = (nn, i)
                continue

            kh, kw, (ph, pw), C = (plan["kh"], plan["kw"], plan["pad"],
                                   plan["C"])
            kh2, kw2 = (kh + 1) // 2, (kw + 1) // 2
            O = plan["O"]
            base = node.name

            # ---- data side: pad -> s2d (reshape/transpose/reshape), the
            # exact node sequence SpaceToDepthStem's forward traces
            cur = map_entry(node.inputs[0])
            if ph or pw:
                cur = (_Node("pad", namer.fresh(base + "_s2d_pad"),
                             {"mode": "constant",
                              "pad_width": (0, 0, ph, ph, pw, pw, 0, 0)},
                             [cur]), 0)
            cur = (_Node("reshape", namer.fresh(base + "_s2d_split"),
                         {"shape": (0, -4, -1, 2, -4, -1, 2, 0)}, [cur]), 0)
            cur = (_Node("transpose", namer.fresh(base + "_s2d_perm"),
                         {"axes": (0, 1, 3, 2, 4, 5)}, [cur]), 0)
            cur = (_Node("reshape", namer.fresh(base + "_s2d_merge"),
                         {"shape": (0, 0, 0, -1)}, [cur]), 0)

            # ---- weight side: re-home the variable when possible, else
            # emit the same block rearrangement as in-graph ops
            wsrc, widx = node.inputs[1]
            if wsrc.is_var and consumers.get(id(wsrc), 0) == 1 \
                    and ctx.can_rehome_param(wsrc.name):
                wclone = var_sub.get(id(wsrc))
                if wclone is None:
                    wclone = _Node(None, wsrc.name, {}, [])
                    wclone._attr_dict = dict(wsrc._attr_dict)
                    if "__shape__" in wclone._attr_dict:
                        wclone._attr_dict["__shape__"] = str(
                            (O, kh2, kw2, 4 * C)) if O else \
                            wclone._attr_dict["__shape__"]
                    var_sub[id(wsrc)] = wclone
                ctx.add_var_transform(wsrc.name, ("s2d_weight", kh, kw))
                w_entry = (wclone, 0)
            else:
                w_entry = map_entry(node.inputs[1])
                if O:
                    if 2 * kh2 - kh or 2 * kw2 - kw:
                        w_entry = (_Node(
                            "pad", namer.fresh(base + "_s2dw_pad"),
                            {"mode": "constant",
                             "pad_width": (0, 0, 0, 2 * kh2 - kh,
                                           0, 2 * kw2 - kw, 0, 0)},
                            [w_entry]), 0)
                    w_entry = (_Node(
                        "reshape", namer.fresh(base + "_s2dw_split"),
                        {"shape": (O, kh2, 2, kw2, 2, C)}, [w_entry]), 0)
                    w_entry = (_Node(
                        "transpose", namer.fresh(base + "_s2dw_perm"),
                        {"axes": (0, 1, 3, 2, 4, 5)}, [w_entry]), 0)
                    w_entry = (_Node(
                        "reshape", namer.fresh(base + "_s2dw_merge"),
                        {"shape": (O, kh2, kw2, 4 * C)}, [w_entry]), 0)
                else:   # num_filter unknown: cannot rearrange — skip conv
                    nn = clone_default(node)
                    for i in range(node.num_outputs):
                        remap[(id(node), i)] = (nn, i)
                    continue

            attrs = dict(node.attrs)
            attrs.update(kernel=(kh2, kw2), stride=(1, 1), pad=(0, 0))
            ins = [cur, w_entry] + [map_entry(e) for e in node.inputs[2:]]
            nn = _Node(node.op, node.name, attrs, ins)
            nn._attr_dict = dict(node._attr_dict)
            for i in range(node.num_outputs):
                remap[(id(node), i)] = (nn, i)
            count += 1

        if count == 0:
            return sym, 0
        new_heads = [map_entry(e) for e in sym._outputs]
        return Symbol(new_heads), count
