"""Fusion-friendly reordering: hoist casts/transposes so XLA fuses across.

Three structural rewrites, iterated to a fixpoint:

* **compose/cancel** — ``transpose(transpose(x, q), p)`` becomes one
  transpose with the composed permutation, or disappears entirely when the
  composition is the identity (the pair the layout pass's boundaries can
  leave behind, and the classic user-graph wart);
* **sink through unary** — ``relu(transpose(x))`` → ``transpose(relu(x))``
  (casts included: a ``Cast`` stranded under a transpose blocks XLA from
  fusing the convert into the producer's HBM pass).  Sinking moves
  transposes toward consumers where the compose rule can cancel them;
* **sink through binary** — ``add(transpose(x), transpose(y))`` with equal
  permutations → ``transpose(add(x, y))``.

All three are bitwise-exact (pure data-movement reordering around
elementwise math), so they're validated by bitwise equivalence tests.
Rewrites only fire when the transposed intermediate has a single consumer —
duplicating a transpose to sink it would pessimize.
"""
from __future__ import annotations

from typing import Dict, Tuple

from ..symbol.symbol import Symbol, _Node
from .manager import Pass, PassContext, Namer, is_barrier, register_pass
from .layout import UNARY_ELEMWISE, MULTI_ELEMWISE

__all__ = ["FusionReorderPass"]

_MAX_ROUNDS = 8


def _axes_of(node) -> Tuple[int, ...]:
    axes = (node.attrs or {}).get("axes")
    if isinstance(axes, (tuple, list)) and axes:
        return tuple(int(a) for a in axes)
    return ()


def _is_transpose(node) -> bool:
    return node is not None and node.op == "transpose" and bool(_axes_of(node))


@register_pass
class FusionReorderPass(Pass):
    name = "fusion"

    def apply(self, sym: Symbol, ctx: PassContext):
        total = 0
        for _ in range(_MAX_ROUNDS):
            sym, n = self._round(sym)
            total += n
            if n == 0:
                break
        return sym, total

    def _round(self, sym: Symbol):
        nodes = sym.topo_nodes()
        if not any(_is_transpose(n) for n in nodes if not n.is_var):
            return sym, 0
        consumers: Dict[int, int] = {}
        for n in nodes:
            for (src, _) in n.inputs:
                consumers[id(src)] = consumers.get(id(src), 0) + 1
        for (hn, _) in sym._outputs:
            consumers[id(hn)] = consumers.get(id(hn), 0) + 1

        namer = Namer(sym)
        remap: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        count = 0

        def map_entry(entry):
            src, idx = entry
            if src.is_var:
                return (src, idx)
            return remap[(id(src), idx)]

        def register(node, entry_or_node):
            if isinstance(entry_or_node, tuple):
                remap[(id(node), 0)] = entry_or_node
            else:
                for i in range(node.num_outputs):
                    remap[(id(node), i)] = (entry_or_node, i)

        def clone(node, ins, attrs=None):
            if attrs is None and all(
                    a is b[0] and i == b[1]
                    for (a, i), b in zip(node.inputs, ins)):
                return node
            nn = _Node(node.op, node.name,
                       dict(node.attrs) if attrs is None else attrs, ins)
            nn._attr_dict = dict(node._attr_dict)
            return nn

        for node in nodes:
            if node.is_var:
                continue
            if is_barrier(node):
                register(node, clone(node, [map_entry(e)
                                            for e in node.inputs]))
                continue

            ins = [map_entry(e) for e in node.inputs]

            # ---- compose / cancel consecutive transposes
            if _is_transpose(node) and len(ins) == 1 \
                    and _is_transpose(ins[0][0]) and ins[0][1] == 0:
                inner = ins[0][0]
                p, q = _axes_of(node), _axes_of(inner)
                if len(p) == len(q):
                    composed = tuple(q[a] for a in p)
                    count += 1
                    if composed == tuple(range(len(composed))):
                        register(node, inner.inputs[0])
                    else:
                        register(node, clone(
                            node, [inner.inputs[0]],
                            dict(node.attrs, axes=composed)))
                    continue

            # ---- sink a single-consumer transpose through unary elemwise
            if node.op in UNARY_ELEMWISE and len(node.inputs) == 1 \
                    and _is_transpose(ins[0][0]) and ins[0][1] == 0 \
                    and consumers.get(id(node.inputs[0][0]), 0) == 1:
                t = ins[0][0]
                inner_op = _Node(node.op, node.name, dict(node.attrs),
                                 [t.inputs[0]])
                inner_op._attr_dict = dict(node._attr_dict)
                out_t = _Node("transpose", namer.fresh(node.name + "_sunk"),
                              {"axes": _axes_of(t)}, [(inner_op, 0)])
                register(node, out_t)
                count += 1
                continue

            # ---- sink matching transposes through binary elemwise
            if node.op in MULTI_ELEMWISE and len(node.inputs) == 2 \
                    and all(_is_transpose(i[0]) and i[1] == 0 for i in ins) \
                    and _axes_of(ins[0][0]) == _axes_of(ins[1][0]) \
                    and all(consumers.get(id(e[0]), 0) == 1
                            for e in node.inputs):
                ta, tb = ins[0][0], ins[1][0]
                inner_op = _Node(node.op, node.name, dict(node.attrs),
                                 [ta.inputs[0], tb.inputs[0]])
                inner_op._attr_dict = dict(node._attr_dict)
                out_t = _Node("transpose", namer.fresh(node.name + "_sunk"),
                              {"axes": _axes_of(ta)}, [(inner_op, 0)])
                register(node, out_t)
                count += 1
                continue

            register(node, clone(node, ins))

        if count == 0:
            return sym, 0
        new_heads = [map_entry(e) for e in sym._outputs]
        return Symbol(new_heads), count
