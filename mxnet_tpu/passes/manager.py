"""Graph-pass manager — the write half of the compiler-pass framework.

``analysis/`` walks symbol graphs read-only (mxlint); this package REWRITES
them, Relay/TVM-style (PAPERS.md): each measured perf lever becomes a
rewrite pass over the symbol IR, so every net inherits it by construction
instead of by tuning run.  A :class:`PassManager` is an ordered pipeline of
:class:`Pass` instances; ``Module``/``DataParallelTrainer`` run the default
pipeline on every captured graph unless constructed with ``passes=False``.

Pipeline semantics:

* Passes run in declared order over a **functional rebuild** of the node
  DAG — the input :class:`~mxnet_tpu.symbol.Symbol` is never mutated, and a
  pass that rewrites nothing returns the input symbol object unchanged (so
  a no-op pipeline is bitwise-invisible to the jit cache).
* A pass may **re-home a variable** (change its declared layout/shape —
  e.g. an OIHW conv weight becoming OHWI) instead of inserting in-graph
  transposes.  Every re-homing is recorded in the
  :class:`PassResult` as a value transform, and the capture path applies
  it to the parameter values (and its inverse on ``sync_to_net``), so the
  user-visible net keeps its original layout.
* ``MXNET_PASSES`` selects the default pipeline: ``"0"``/``"off"`` disables
  it, ``"layout,fusion"`` runs exactly those passes, ``"-s2d"`` runs the
  default set minus a pass.

Pass catalog (docs/passes.md): ``fold`` (constant folding + dead-branch
elimination), ``layout`` (automatic NCHW→NHWC propagation), ``s2d``
(space-to-depth stem rewrite for stride-2 input convs), ``fusion``
(transpose/cast reordering so XLA fuses across layout boundaries).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, get_env, logger, register_config

__all__ = ["Pass", "PassContext", "PassResult", "PassManager",
           "DEFAULT_PIPELINE", "PASS_REGISTRY", "register_pass",
           "default_names", "resolve", "annotate_graph", "apply_spec",
           "spec_shape", "provenance"]

register_config(
    "MXNET_PASSES", "", str,
    "Default graph-pass pipeline for Module/DataParallelTrainer capture. "
    "Empty = the built-in default (fold,layout,s2d,fusion); '0'/'off' "
    "disables it; 'layout,fusion' runs exactly those; '-s2d' runs the "
    "default minus a pass.")

#: canonical order; also the default pipeline contents
DEFAULT_PIPELINE = ("fold", "layout", "s2d", "fusion")

#: name -> Pass subclass (populated by the pass modules at import)
PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls) -> type:
    PASS_REGISTRY[cls.name] = cls
    return cls


class Pass:
    """One rewrite pass over a Symbol graph.

    Subclasses set ``name`` and implement ``apply(sym, ctx) ->
    (new_sym, rewrite_count)``.  ``apply`` MUST be functional: return the
    input symbol unchanged when nothing rewrites, never mutate existing
    nodes (re-homed variables are fresh clones)."""

    name = "pass"

    def apply(self, sym, ctx: "PassContext"):
        raise NotImplementedError

    def __repr__(self):
        return f"<Pass {self.name}>"


# --------------------------------------------------------------------------
# shared graph utilities
# --------------------------------------------------------------------------

_NCHW_SPELLINGS = (None, "None", "", "NCHW")


def node_names(sym) -> set:
    return {n.name for n in sym.topo_nodes()}


class Namer:
    """Unique-name generator for pass-inserted nodes.  Seeded with every
    existing node name (and, for partitioned graphs, the inner subgraph
    names) so a rewrite can never collide with a partition boundary — the
    subgraph re-anchoring contract tests/test_passes.py pins."""

    def __init__(self, sym):
        self._taken = set()
        for n in sym.topo_nodes():
            self._taken.add(n.name)
            for key in ("subgraph_id", "then_id", "else_id", "cond_id",
                        "body_id"):
                if n.op is not None and key in (n.attrs or {}):
                    try:
                        from ..subgraph import get_stored_subgraph
                        inner = get_stored_subgraph(int(n.attrs[key]))
                        self._taken |= {m.name for m in inner.topo_nodes()}
                    except Exception:
                        pass

    def fresh(self, base: str) -> str:
        name = base
        i = 0
        while name in self._taken:
            i += 1
            name = f"{base}{i}"
        self._taken.add(name)
        return name


#: ops that own nested subgraphs — passes treat them as opaque barriers
#: (rewriting across a partition/control-flow boundary would desync the
#: stored inner symbol from the outer wiring)
def is_barrier(node) -> bool:
    if node.op is None:
        return False
    if node.op == "_subgraph":
        return True
    attrs = node.attrs or {}
    return any(k in attrs for k in ("subgraph_id", "then_id", "else_id",
                                    "cond_id", "body_id"))


def annotate_graph(sym, shapes: Optional[Dict[str, Sequence[int]]] = None,
                   dtypes: Optional[Dict[str, Any]] = None
                   ) -> Dict[Tuple[int, int], Any]:
    """Tolerant abstract evaluation: map every graph entry ``(id(node),
    out_idx)`` to a ``jax.ShapeDtypeStruct`` (or ``None`` where inference
    fails — passes skip nodes with unknown inputs instead of raising).
    Variables are keyed ``(id(var), 0)``.  The same parameter-shape
    backfill rules the executor uses resolve weight shapes from data
    shapes, so providing the input-batch shapes is usually enough."""
    import jax
    import jax.numpy as jnp
    from ..ops.registry import get_op
    from ..executor import _PARAM_SHAPE_RULES
    from .._imperative import _op_signature_flags
    from ..analysis.graph_lint import _parse_shape_attr, _parse_dtype_attr

    shapes = {k: tuple(v) for k, v in (shapes or {}).items()}
    dtypes = dict(dtypes or {})
    var_shape: Dict[str, Tuple[int, ...]] = {}
    var_dtype: Dict[str, Any] = {}
    nodes = sym.topo_nodes()
    for n in nodes:
        if not n.is_var:
            continue
        s = shapes.get(n.name)
        if s is None and "__shape__" in n._attr_dict:
            s = _parse_shape_attr(n._attr_dict["__shape__"])
        if s is not None:
            var_shape[n.name] = tuple(s)
        dt = dtypes.get(n.name)
        if dt is None and "__dtype__" in n._attr_dict:
            dt = _parse_dtype_attr(n._attr_dict["__dtype__"])
        if dt is not None:
            var_dtype[n.name] = dt

    avals: Dict[Tuple[int, int], Any] = {}
    for node in nodes:
        if node.is_var:
            if node.name in var_shape:
                avals[(id(node), 0)] = jax.ShapeDtypeStruct(
                    var_shape[node.name],
                    np.dtype(var_dtype.get(node.name, np.float32)))
            else:
                avals[(id(node), 0)] = None
            continue
        try:
            opdef = get_op(node.op)
        except MXNetError:
            continue
        if opdef.host:
            continue
        arg_names = opdef.arg_names() or []
        rule = _PARAM_SHAPE_RULES.get(node.op)
        if rule is not None and node.inputs:
            src0, idx0 = node.inputs[0]
            ds = (var_shape.get(src0.name) if src0.is_var
                  else (tuple(avals[(id(src0), idx0)].shape)
                        if avals.get((id(src0), idx0)) is not None else None))
            if ds is not None:
                try:
                    param_shapes = rule(dict(node.attrs), tuple(ds))
                except Exception:
                    param_shapes = {}
                for i, (src, _) in enumerate(node.inputs):
                    if src.is_var and src.name not in var_shape \
                            and i < len(arg_names) \
                            and arg_names[i] in param_shapes:
                        var_shape[src.name] = param_shapes[arg_names[i]]
                        avals[(id(src), 0)] = jax.ShapeDtypeStruct(
                            var_shape[src.name],
                            np.dtype(var_dtype.get(src.name, np.float32)))
        in_avals = []
        ok = True
        for (src, idx) in node.inputs:
            av = avals.get((id(src), idx))
            if av is None:
                ok = False
                break
            in_avals.append(av)
        if not ok:
            continue
        attrs = dict(node.attrs)
        accepts_train, accepts_rng = _op_signature_flags(opdef)
        if accepts_train and "is_train" not in attrs:
            attrs["is_train"] = True

        def run(*arrs):
            kw = dict(attrs)
            if accepts_rng:
                kw["rng"] = jax.random.PRNGKey(0)
            return opdef.fn(*arrs, **kw)

        try:
            out_avals = jax.eval_shape(run, *in_avals)
        except Exception:
            continue
        if not isinstance(out_avals, tuple):
            out_avals = (out_avals,)
        for i, av in enumerate(out_avals):
            avals[(id(node), i)] = av
    return avals


# --------------------------------------------------------------------------
# value transforms (re-homed variables)
# --------------------------------------------------------------------------

def _inv_perm(perm):
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return tuple(inv)


def s2d_weight_forward(w: np.ndarray) -> np.ndarray:
    """(O,kh,kw,C) OHWI conv weight -> its block-2 space-to-depth twin
    (O,ceil(kh/2),ceil(kw/2),4C): W'[o,du,dv,(2r+s)C+c] = W[o,2du+r,2dv+s,c],
    zero where the source index falls past the kernel (the exact
    reparameterization tests/test_s2d_stem.py pins)."""
    O, kh, kw, C = w.shape
    kh2, kw2 = (kh + 1) // 2, (kw + 1) // 2
    padded = np.zeros((O, 2 * kh2, 2 * kw2, C), w.dtype)
    padded[:, :kh, :kw, :] = w
    return padded.reshape(O, kh2, 2, kw2, 2, C) \
                 .transpose(0, 1, 3, 2, 4, 5) \
                 .reshape(O, kh2, kw2, 4 * C)


def s2d_weight_inverse(w2: np.ndarray, kh: int, kw: int) -> np.ndarray:
    O, kh2, kw2, c4 = w2.shape
    C = c4 // 4
    padded = w2.reshape(O, kh2, kw2, 2, 2, C) \
               .transpose(0, 1, 3, 2, 4, 5) \
               .reshape(O, 2 * kh2, 2 * kw2, C)
    return np.ascontiguousarray(padded[:, :kh, :kw, :])


def apply_spec(spec, value: np.ndarray, inverse: bool = False) -> np.ndarray:
    kind = spec[0]
    if kind == "transpose":
        perm = spec[1]
        return np.transpose(value, _inv_perm(perm) if inverse else perm)
    if kind == "s2d_weight":
        kh, kw = spec[1], spec[2]
        return s2d_weight_inverse(value, kh, kw) if inverse \
            else s2d_weight_forward(value)
    raise MXNetError(f"unknown variable-transform spec {spec!r}")


def spec_shape(spec, shape: Sequence[int]) -> Tuple[int, ...]:
    """The shape ``apply_spec(spec, ·)`` produces, without materializing a
    value — every transform kind added to ``apply_spec`` adds its shape
    effect HERE (annotate + PassResult.transformed_shape + mxopt all read
    this one function)."""
    shape = tuple(int(d) for d in shape)
    kind = spec[0]
    if kind == "transpose":
        return tuple(shape[i] for i in spec[1])
    if kind == "s2d_weight":
        kh, kw = spec[1], spec[2]
        O, _, _, C = shape
        return (O, (kh + 1) // 2, (kw + 1) // 2, 4 * C)
    raise MXNetError(f"unknown variable-transform spec {spec!r}")


def rehomed_shapes(shapes: Dict[str, Sequence[int]],
                   var_transforms: Dict[str, List[tuple]],
                   input_layouts: Dict[str, str]) -> Dict[str, Tuple]:
    """Original variable shapes -> the shapes the REWRITTEN graph
    declares: value transforms folded through :func:`spec_shape`, NHWC
    re-homed rank-4 inputs permuted.  Shared by ``PassContext.annotate``
    and ``PassResult.transformed_shapes`` (mxopt's after-lint)."""
    out = {k: tuple(int(d) for d in v) for k, v in shapes.items()}
    for name, specs in var_transforms.items():
        if name in out:
            s = out[name]
            for spec in specs:
                s = spec_shape(spec, s)
            out[name] = s
    for name, lay in input_layouts.items():
        s = out.get(name)
        if lay == "NHWC" and s is not None and len(s) == 4:
            out[name] = (s[0], s[2], s[3], s[1])
    return out


def provenance(manager: Optional["PassManager"],
               result: Optional["PassResult"],
               fallback_rewrites: Optional[Dict[str, int]] = None
               ) -> Dict[str, Any]:
    """The ``passes=`` provenance dict stamped into bench/ladder rows —
    ONE schema shared by DataParallelTrainer and Module."""
    if manager is None:
        return {"enabled": False, "pipeline": [], "applied": []}
    prov: Dict[str, Any] = {"enabled": True,
                            "pipeline": list(manager.names)}
    if manager.input_layout:
        prov["input_layout"] = manager.input_layout
    if result is not None:
        prov["applied"] = result.applied
        prov["rewrites"] = {k: v for k, v in result.counts.items() if v}
    else:
        prov["applied"] = []
        if fallback_rewrites:
            prov["rewrites"] = {k: v for k, v in fallback_rewrites.items()
                                if v}
    return prov


# --------------------------------------------------------------------------
# context / result / manager
# --------------------------------------------------------------------------

class PassContext:
    """Per-pipeline-run state shared by the passes: known shapes, which
    variables are inputs vs parameters, re-homing policy, and the
    accumulated variable transforms."""

    def __init__(self, shapes=None, dtypes=None, input_vars: Sequence[str] = (),
                 param_names: Optional[Sequence[str]] = None,
                 rehome_params: bool = False,
                 input_layout: Optional[str] = None):
        self.shapes = dict(shapes or {})
        self.dtypes = dict(dtypes or {})
        self.input_vars = set(input_vars or ())
        self.param_names = set(param_names) if param_names is not None \
            else None
        self.rehome_params = bool(rehome_params)
        # "NHWC" = the caller commits to feeding channel-last batches, so
        # the layout pass may re-home rank-4 input variables instead of
        # inserting a leading transpose (the tuner's flag-vs-pass route)
        self.input_layout = input_layout
        #: var name -> ordered transform specs (applied left to right to
        #: the ORIGINAL value to obtain the rewritten graph's value)
        self.var_transforms: Dict[str, List[tuple]] = {}
        #: NEW variables a pass introduced, with a spec describing how to
        #: derive each value from the original parameter dict (the
        #: quantize pass mints int8 weights + range scalars this way);
        #: materialized by :meth:`PassResult.materialize_params`
        self.synth_params: Dict[str, tuple] = {}
        #: internal source values synthesized specs may reference (e.g. a
        #: zero bias) — never returned to the caller themselves
        self.synth_sources: Dict[str, tuple] = {}
        #: var name -> declared layout after re-homing (inputs only)
        self.input_layouts: Dict[str, str] = {}
        self.counts: Dict[str, int] = {}
        self._aval_cache: Dict[int, Dict] = {}
        self._aval_keep: List[Any] = []   # pin cached symbols (id reuse)

    def can_rehome_param(self, name: str) -> bool:
        if not self.rehome_params:
            return False
        if name in self.input_vars:
            return False
        if self.param_names is not None:
            return name in self.param_names
        return False

    def can_rehome_input(self, name: str) -> bool:
        return self.input_layout == "NHWC" and name in self.input_vars

    def add_var_transform(self, name: str, spec: tuple) -> None:
        self.var_transforms.setdefault(name, []).append(spec)

    def add_synth_param(self, name: str, spec: tuple) -> None:
        """Declare a NEW variable the rewritten graph consumes, derived
        from the original params per ``spec``: ``("const", value)`` a
        literal scalar, ``("quant_of", src, part)`` one leg of the int8
        (quantized/min/max) triple of parameter ``src``."""
        self.synth_params[name] = tuple(spec)

    def add_synth_source(self, name: str, spec: tuple) -> None:
        """Declare an internal source value (``("zeros", shape)``) that
        ``quant_of`` specs may reference but which is not itself a graph
        variable."""
        self.synth_sources[name] = tuple(spec)

    def annotate(self, sym) -> Dict[Tuple[int, int], Any]:
        key = id(sym)
        if key not in self._aval_cache:
            # re-homed vars already carry transforms: their live shapes in
            # THIS graph are the transformed ones
            shapes = rehomed_shapes(self.shapes, self.var_transforms,
                                    self.input_layouts)
            self._aval_cache[key] = annotate_graph(sym, shapes, self.dtypes)
            self._aval_keep.append(sym)
        return self._aval_cache[key]


class PassResult:
    """What a pipeline run produced: the rewritten symbol, per-pass rewrite
    counts, and the variable value transforms the caller must apply."""

    def __init__(self, symbol, ctx: PassContext, names: Sequence[str]):
        self.symbol = symbol
        self.counts = dict(ctx.counts)
        self.var_transforms = {k: list(v)
                               for k, v in ctx.var_transforms.items()}
        self.input_layouts = dict(ctx.input_layouts)
        self.synth_params = dict(ctx.synth_params)
        self.synth_sources = dict(ctx.synth_sources)
        self.names = tuple(names)

    @property
    def total_rewrites(self) -> int:
        return sum(self.counts.values())

    @property
    def applied(self) -> List[str]:
        """Pass names that actually rewrote something."""
        return [n for n in self.names if self.counts.get(n)]

    def transform_var(self, name: str, value):
        v = np.asarray(value)
        for spec in self.var_transforms.get(name, ()):
            v = apply_spec(spec, v)
        return v

    def transformed_shape(self, name: str, shape) -> Tuple[int, ...]:
        """The re-homed shape of variable ``name`` given its original
        ``shape`` (identity when un-transformed) — shape math only."""
        s = tuple(int(d) for d in shape)
        for spec in self.var_transforms.get(name, ()):
            s = spec_shape(spec, s)
        return s

    def transformed_shapes(self, shapes: Dict) -> Dict:
        """Map a whole original-shape dict into the rewritten graph's
        shapes (value transforms + NHWC input re-homing) — what the
        rewritten symbol binds/lints with."""
        return rehomed_shapes(shapes, self.var_transforms,
                              self.input_layouts)

    def inverse_var(self, name: str, value):
        v = np.asarray(value)
        for spec in reversed(self.var_transforms.get(name, ())):
            v = apply_spec(spec, v, inverse=True)
        return v

    def materialize_params(self, arg_params: Dict) -> Dict:
        """Compute the values of every pass-synthesized variable
        (``ctx.add_synth_param``) from the ORIGINAL parameter dict — the
        extra params the caller merges into its bind dict. One source of
        truth for the int8 math: ``contrib.quantization.quantize_params``."""
        if not self.synth_params:
            return {}
        from .. import ndarray as nd_mod
        src = dict(arg_params)
        for name, spec in self.synth_sources.items():
            if spec[0] == "zeros":
                src[name] = nd_mod.zeros(tuple(int(d) for d in spec[1]))
            else:
                raise MXNetError(f"unknown synth-source spec {spec!r}")
        out: Dict[str, Any] = {}
        quant_cache: Dict[str, Dict] = {}
        for name, spec in self.synth_params.items():
            kind = spec[0]
            if kind == "const":
                out[name] = nd_mod.array(np.float32(spec[1]))
            elif kind == "quant_of":
                pname, part = spec[1], spec[2]
                if pname not in quant_cache:
                    from ..contrib.quantization import quantize_params
                    if pname not in src:
                        raise MXNetError(
                            f"synthesized param {name!r} derives from "
                            f"{pname!r}, which is not in arg_params")
                    quant_cache[pname] = quantize_params({pname: src[pname]})
                out[name] = quant_cache[pname][f"{pname}_{part}"]
            else:
                raise MXNetError(f"unknown synth-param spec {spec!r}")
        return out



def default_names(spec: Optional[str] = None) -> Tuple[str, ...]:
    """Resolve a pipeline spelling (the ``MXNET_PASSES`` grammar) to an
    ordered tuple of pass names.  ``None`` reads the env knob."""
    if spec is None:
        spec = str(get_env("MXNET_PASSES", "") or "")
    spec = spec.strip()
    if spec.lower() in ("0", "off", "none", "false"):
        return ()
    if not spec:
        return DEFAULT_PIPELINE
    tokens = [t.strip() for t in spec.split(",") if t.strip()]
    removed = {t[1:].strip() for t in tokens if t.startswith("-")}
    listed = [t for t in tokens if not t.startswith("-")]
    base = list(listed) if listed else list(DEFAULT_PIPELINE)
    for name in set(base) | removed:
        if name not in PASS_REGISTRY:
            raise MXNetError(
                f"unknown graph pass {name!r} "
                f"(registered: {', '.join(sorted(PASS_REGISTRY))})")
    return tuple(n for n in base if n not in removed)


class PassManager:
    """Ordered, configurable pipeline of graph passes.

    ``passes`` may be pass names, :class:`Pass` instances, or a spec string
    in the ``MXNET_PASSES`` grammar; ``None`` takes the env-configured
    default.  ``input_layout="NHWC"`` declares that the caller feeds
    channel-last batches, letting the layout pass re-home rank-4 input
    variables (zero residual transposes — the hand-flag-identical route)."""

    def __init__(self, passes=None, input_layout: Optional[str] = None,
                 rehome_params: bool = True):
        if passes is None or isinstance(passes, str):
            names = default_names(passes)
            self.passes: List[Pass] = [PASS_REGISTRY[n]() for n in names]
        else:
            self.passes = []
            for p in passes:
                if isinstance(p, Pass):
                    self.passes.append(p)
                elif isinstance(p, str):
                    if p not in PASS_REGISTRY:
                        raise MXNetError(f"unknown graph pass {p!r}")
                    self.passes.append(PASS_REGISTRY[p]())
                elif isinstance(p, type) and issubclass(p, Pass):
                    self.passes.append(p())
                else:
                    raise MXNetError(f"not a pass: {p!r}")
        if input_layout not in (None, "NHWC"):
            raise MXNetError("input_layout must be None or 'NHWC', got %r"
                             % (input_layout,))
        self.input_layout = input_layout
        self.rehome_params = bool(rehome_params)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(p.name for p in self.passes)

    def __len__(self):
        return len(self.passes)

    def __repr__(self):
        return f"<PassManager {','.join(self.names) or '(empty)'}>"

    def init_view(self, arrays):
        """The sample batch as the NET expects it for the deferred-init
        host forward: under ``input_layout='NHWC'`` the caller feeds
        channel-last batches to an NCHW-built net, so rank-4 arrays are
        permuted back to NCHW for initialization only."""
        if self.input_layout != "NHWC":
            return list(arrays)
        import jax
        out = []
        for a in arrays:
            if getattr(a, "ndim", 0) == 4:
                out.append(np.transpose(np.asarray(jax.device_get(a)),
                                        (0, 3, 1, 2)))
            else:
                out.append(a)
        return out

    def run(self, sym, shapes=None, dtypes=None, input_vars: Sequence[str] = (),
            param_names: Optional[Sequence[str]] = None,
            rehome_params: Optional[bool] = None) -> PassResult:
        """Run the pipeline over ``sym``; returns a :class:`PassResult`.
        ``shapes`` plays the ``simple_bind`` kwargs role (data shapes;
        parameter shapes backfill from the executor's rules).  The input
        symbol is never mutated; with zero rewrites ``result.symbol is
        sym``."""
        ctx = PassContext(
            shapes=shapes, dtypes=dtypes, input_vars=input_vars,
            param_names=param_names,
            rehome_params=self.rehome_params if rehome_params is None
            else bool(rehome_params),
            input_layout=self.input_layout)
        cur = sym
        for p in self.passes:
            try:
                cur, n = p.apply(cur, ctx)
            except MXNetError:
                raise
            except Exception as e:
                # a pass must never take down a capture: log and continue
                # with the last good graph (equivalence holds trivially)
                logger.warning("graph pass %r failed, skipped: %r",
                               p.name, e)
                n = 0
            ctx.counts[p.name] = ctx.counts.get(p.name, 0) + int(n)
        return PassResult(cur, ctx, self.names)


def resolve(passes) -> Optional[PassManager]:
    """Normalize the ``passes=`` ctor argument shared by Module and
    DataParallelTrainer: ``None`` = env-default pipeline (may be empty =>
    None), any explicit falsy spelling (``False``/``0``/``""``/``()``) =
    off — only the unset default silently enables (the falsy-spelling
    contract PR-5/PR-7 established for recovery/scaler configs) — a
    :class:`PassManager` = itself, a spec string / sequence = custom."""
    if passes is None:
        mgr = PassManager()
        return mgr if len(mgr) else None
    if passes is True:
        # an EXPLICIT opt-in beats the ambient env knob: MXNET_PASSES=off
        # must not silently disable a trainer that asked for the pipeline
        return PassManager(DEFAULT_PIPELINE)
    if isinstance(passes, PassManager):
        return passes if len(passes) else None
    if not passes or (isinstance(passes, str) and not passes.strip()):
        return None
    mgr = PassManager(passes)
    return mgr if len(mgr) else None
