"""Network visualization (``mx.viz``).

Reference parity: ``python/mxnet/visualization.py`` — ``print_summary``
renders a layer table with parameter counts; ``plot_network`` renders the
Symbol DAG as a graphviz digraph.
"""
from __future__ import annotations

from .symbol import Symbol
from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol: Symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a table of layers, output shapes, param counts and connections."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))

    positions = [int(line_length * p) for p in positions]
    to_display = ['Layer (type)', 'Output Shape', 'Param #', 'Previous Layer']

    def print_row(fields, posns):
        line = ''
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:posns[i]]
            line += ' ' * (posns[i] - len(line))
        print(line)

    print('_' * line_length)
    print_row(to_display, positions)
    print('=' * line_length)

    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node.op
        pre_nodes = [src.name for (src, _) in node.inputs
                     if src.op is not None or src.name.endswith('data')
                     or not _is_param(src.name)]
        cur_param = 0
        attrs = node.attrs
        if op == 'Convolution':
            num_group = int(attrs.get('num_group', '1'))
            cur_param = _prod(_parse_tuple(attrs['kernel'])) // num_group
            chan = _input_channel(node, shape_dict)
            if chan:
                cur_param *= chan
            cur_param *= int(attrs['num_filter'])
            if attrs.get('no_bias') not in ('True', 'true', True):
                cur_param += int(attrs['num_filter'])
        elif op == 'FullyConnected':
            num_hidden = int(attrs['num_hidden'])
            chan = _input_channel(node, shape_dict, flatten=True)
            cur_param = num_hidden * (chan or 0)
            if attrs.get('no_bias') not in ('True', 'true', True):
                cur_param += num_hidden
        elif op == 'BatchNorm':
            key = node.name + '_output'
            if show_shape and key in shape_dict:
                num_filter = shape_dict[key][1]
                cur_param = int(num_filter) * 2
        elif op == 'Embedding':
            cur_param = int(attrs['input_dim']) * int(attrs['output_dim'])
        first_connection = pre_nodes[0] if pre_nodes else ''
        fields = ['%s(%s)' % (node.name, op), str(out_shape), cur_param,
                  first_connection]
        print_row(fields, positions)
        for conn in pre_nodes[1:]:
            print_row(['', '', '', conn], positions)
        total_params[0] += cur_param

    nodes = symbol.topo_nodes()
    for i, node in enumerate(nodes):
        if node.is_var:
            continue
        out_shape = None
        if show_shape:
            key = node.name + '_output'
            if key in shape_dict:
                out_shape = shape_dict[key]
        print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print('=' * line_length)
        else:
            print('_' * line_length)
    print('Total params: %s' % total_params[0])
    print('_' * line_length)
    return total_params[0]


def _prod(t):
    r = 1
    for x in t:
        r *= x
    return r


def _parse_tuple(s):
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    return tuple(int(x) for x in str(s).strip('()[] ').split(',') if x.strip())


def _is_param(name):
    return name.endswith(('_weight', '_bias', '_gamma', '_beta',
                          '_moving_mean', '_moving_var'))


def _input_channel(node, shape_dict, flatten=False):
    for (src, idx) in node.inputs:
        nm = src.name
        if _is_param(nm):
            continue
        for key in (nm + '_output', nm):
            if key in shape_dict:
                s = shape_dict[key]
                if len(s) > 1:
                    if not flatten:
                        return s[1]
                    # FC consumes the flattened trailing dims
                    c = 1
                    for d in s[1:]:
                        c *= d
                    return c
    return None


def plot_network(symbol, title="plot", save_format='pdf', shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the Symbol DAG (requires graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")

    shape_dict = {}
    draw_shape = False
    if shape is not None:
        draw_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))

    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs or {})
    dot = Digraph(name=title, format=save_format)

    # color palette per op family (reference visualization.py scheme)
    fill_colors = ["#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3",
                   "#fdb462", "#b3de69", "#fccde5"]

    nodes = symbol.topo_nodes()
    hidden = set()
    for node in nodes:
        name = node.name
        attr = dict(node_attr)
        if node.is_var:
            if hide_weights and _is_param(name):
                hidden.add(id(node))
                continue
            attr["fillcolor"] = fill_colors[0]
            label = name
        else:
            op = node.op
            if op == 'Convolution':
                label = "Convolution\n%s/%s, %s" % (
                    node.attrs.get('kernel'), node.attrs.get('stride', '1'),
                    node.attrs.get('num_filter'))
                attr["fillcolor"] = fill_colors[1]
            elif op == 'FullyConnected':
                label = "FullyConnected\n%s" % node.attrs.get('num_hidden')
                attr["fillcolor"] = fill_colors[1]
            elif op == 'BatchNorm':
                label = "BatchNorm"
                attr["fillcolor"] = fill_colors[3]
            elif op == 'Activation' or op == 'LeakyReLU':
                label = "%s\n%s" % (op, node.attrs.get('act_type', ''))
                attr["fillcolor"] = fill_colors[2]
            elif op == 'Pooling':
                label = "Pooling\n%s, %s/%s" % (
                    node.attrs.get('pool_type'), node.attrs.get('kernel'),
                    node.attrs.get('stride', '1'))
                attr["fillcolor"] = fill_colors[4]
            elif op in ('Concat', 'Flatten', 'Reshape'):
                label = op
                attr["fillcolor"] = fill_colors[5]
            elif op == 'Softmax' or op == 'SoftmaxOutput':
                label = op
                attr["fillcolor"] = fill_colors[6]
            else:
                label = op
                attr["fillcolor"] = fill_colors[7]
        dot.node(name=name, label=label, **attr)

    for node in nodes:
        if node.is_var or id(node) in hidden:
            continue
        for (src, idx) in node.inputs:
            if id(src) in hidden:
                continue
            label = ""
            if draw_shape:
                for key in (src.name + '_output', src.name):
                    if key in shape_dict:
                        label = "x".join([str(x) for x in shape_dict[key][1:]])
                        break
            dot.edge(tail_name=src.name, head_name=node.name, label=label,
                     arrowtail="open", dir="back")
    return dot
