"""Detection augmenters (reference ``python/mxnet/image/detection.py`` +
``src/io/image_aug_default.cc`` det variants).

Augmenters transform ``(image HWC NDArray, label (N, 5) numpy [cls, x1, y1,
x2, y2] normalized)`` pairs, keeping boxes consistent with the pixels:
flips mirror coordinates, IOU-constrained random crops drop/clip boxes,
random expansion pads and rescales them."""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

import numpy as np

from . import image as img_mod
from . import ndarray as nd

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "CreateDetAugmenter"]


class DetAugmenter:
    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image augmenter that doesn't move pixels' positions
    (color jitter, cast, normalize — reference DetBorrowAug)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = nd.flip(src, axis=1)
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[:, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1[valid]
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IOU-constrained random crop (reference DetRandomCropAug / SSD data
    augmentation): sample crops until one overlaps some box with IOU >=
    min_object_covered; clip boxes to the crop, drop those whose center
    falls outside."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=25):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _crop_iou(self, crop, boxes):
        cx1, cy1, cx2, cy2 = crop
        ix1 = np.maximum(boxes[:, 0], cx1)
        iy1 = np.maximum(boxes[:, 1], cy1)
        ix2 = np.minimum(boxes[:, 2], cx2)
        iy2 = np.minimum(boxes[:, 3], cy2)
        inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
        area = np.maximum((boxes[:, 2] - boxes[:, 0])
                          * (boxes[:, 3] - boxes[:, 1]), 1e-12)
        return inter / area

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            area = random.uniform(*self.area_range)
            ar = random.uniform(*self.aspect_ratio_range)
            cw = min(np.sqrt(area * ar), 1.0)
            ch = min(np.sqrt(area / ar), 1.0)
            cx = random.uniform(0, 1.0 - cw)
            cy = random.uniform(0, 1.0 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if len(boxes) and self._crop_iou(crop, boxes).max() \
                    < self.min_object_covered:
                continue
            # pixel crop
            x0, y0 = int(cx * w), int(cy * h)
            x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
            out = src[y0:y1, x0:x1]
            new_label = np.full_like(label, -1.0)
            j = 0
            for row in label[valid]:
                bx1, by1, bx2, by2 = row[1:5]
                ctr_x, ctr_y = (bx1 + bx2) / 2, (by1 + by2) / 2
                if not (crop[0] <= ctr_x <= crop[2]
                        and crop[1] <= ctr_y <= crop[3]):
                    continue
                nx1 = (max(bx1, crop[0]) - crop[0]) / cw
                ny1 = (max(by1, crop[1]) - crop[1]) / ch
                nx2 = (min(bx2, crop[2]) - crop[0]) / cw
                ny2 = (min(by2, crop[3]) - crop[1]) / ch
                new_label[j] = (row[0], nx1, ny1, nx2, ny2)
                j += 1
            if j == 0:
                continue
            return out, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expansion: place the image inside a larger mean-filled canvas
    and rescale boxes (reference DetRandomPadAug / SSD zoom-out)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), pad_val=(127, 127, 127), p=0.5):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.pad_val = np.asarray(pad_val, "float32")
        self.p = p

    def __call__(self, src, label):
        if random.random() > self.p:
            return src, label
        h, w = src.shape[0], src.shape[1]
        expand = random.uniform(*self.area_range)
        if expand <= 1.0:
            return src, label
        nh, nw = int(h * np.sqrt(expand)), int(w * np.sqrt(expand))
        y0 = random.randint(0, nh - h)
        x0 = random.randint(0, nw - w)
        canvas = np.tile(self.pad_val.reshape(1, 1, 3), (nh, nw, 1))
        canvas[y0:y0 + h, x0:x0 + w] = src.asnumpy()
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] * w + x0) / nw
        label[valid, 3] = (label[valid, 3] * w + x0) / nw
        label[valid, 2] = (label[valid, 2] * h + y0) / nh
        label[valid, 4] = (label[valid, 4] * h + y0) / nh
        return nd.array(canvas), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, hue=0, pca_noise=0,
                       min_object_covered=0.3, area_range=(0.3, 3.0),
                       **kwargs) -> List[DetAugmenter]:
    """Detection augmentation list builder (reference
    image/detection.py:CreateDetAugmenter)."""
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(img_mod.ResizeAug(resize)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(
            min_object_covered=min_object_covered,
            area_range=(area_range[0], min(area_range[1], 1.0))))
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(
            area_range=(1.0, max(area_range[1], 1.0)), p=rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(img_mod.ForceResizeAug(
        (data_shape[2], data_shape[1]))))
    auglist.append(DetBorrowAug(img_mod.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            img_mod.ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(img_mod.HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(DetBorrowAug(
            img_mod.LightingAug(pca_noise, eigval, eigvec)))
    norm = img_mod.make_norm_aug(mean, std)
    if norm is not None:
        auglist.append(DetBorrowAug(norm))
    return auglist
