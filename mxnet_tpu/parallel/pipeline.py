"""Pipeline parallelism over the 'pp' mesh axis.

Absent from the reference (SURVEY.md §2.3: "nearest: DAG-level
auto-parallelism"); built first-class here. GPipe-style schedule expressed
the SPMD way: every device holds ONE stage's parameters (stacked arrays
sharded on their leading 'stage' dim); a ``lax.fori_loop`` runs
n_micro + n_stages - 1 ticks in which each device applies its stage to the
activation it holds and ``ppermute``s the result to the next device.
Bubble fraction = (n-1)/(m+n-1), as usual — choose n_micro accordingly.

Constraint (same as scan-based pipelining generally): all stages share one
activation shape, e.g. a stack of identical transformer/MLP blocks.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis: str = "pp"):
    """Run ``stage_fn(params_i, x) -> x`` over n_stages = mesh[axis] stages.

    stacked_params: pytree whose leaves have leading dim n_stages (sharded on
    ``axis``). x_microbatches: (n_micro, *batch_shape) replicated input; the
    return is (n_micro, *batch_shape) of the final stage's outputs.
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total = n_micro + n_stages - 1

    def local(params_stacked, xs):
        # params_stacked leaves: (1, ...) local slice -> squeeze stage dim
        params = jax.tree_util.tree_map(lambda a: a[0], params_stacked)
        rank = lax.axis_index(axis)
        from .ring_attention import _pvary
        state = _pvary(jnp.zeros_like(xs[0]), axis)  # activation currently held
        outs = _pvary(jnp.zeros_like(xs), axis)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outs = carry
            # stage 0 ingests microbatch t (if any remain)
            feed = xs[jnp.minimum(t, n_micro - 1)]
            state = jnp.where(rank == 0, feed, state)
            new_state = stage_fn(params, state)
            # last stage emits result of microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(rank == n_stages - 1, out_idx >= 0)
            slot = jnp.maximum(out_idx, 0)
            outs = outs.at[slot].set(jnp.where(emit, new_state, outs[slot]))
            state = lax.ppermute(new_state, axis, fwd_perm)
            return state, outs

        state, outs = lax.fori_loop(0, total, tick, (state, outs))
        # only the last rank's outs are real; broadcast them
        outs = lax.psum(jnp.where(rank == n_stages - 1, outs, 0.0), axis)
        return outs

    fn = shard_map(local, mesh=mesh,
                   in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params,
                                                    is_leaf=lambda l: hasattr(l, "shape")),
                             P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)
