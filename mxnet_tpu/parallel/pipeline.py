"""Pipeline parallelism over the 'pp' mesh axis.

Absent from the reference (SURVEY.md §2.3: "nearest: DAG-level
auto-parallelism"); built first-class here. GPipe-style schedule expressed
the SPMD way: every device holds ONE stage's parameters (stacked arrays
sharded on their leading 'stage' dim); a ``lax.fori_loop`` runs
n_micro + n_stages - 1 ticks in which each device applies its stage to the
activation it holds and ``ppermute``s the result to the next device.
Bubble fraction = (n-1)/(m+n-1), as usual — choose n_micro accordingly.

Constraint (same as scan-based pipelining generally): all stages share one
activation shape, e.g. a stack of identical transformer/MLP blocks.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "GluonPipelineStack", "HeterogeneousPipeline"]


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis: str = "pp"):
    """Run ``stage_fn(params_i, x) -> x`` over n_stages = mesh[axis] stages.

    stacked_params: pytree whose leaves have leading dim n_stages (sharded on
    ``axis``). x_microbatches: (n_micro, *batch_shape) replicated input; the
    return is (n_micro, *batch_shape) of the final stage's outputs.
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total = n_micro + n_stages - 1

    def local(params_stacked, xs):
        # params_stacked leaves: (1, ...) local slice -> squeeze stage dim
        params = jax.tree_util.tree_map(lambda a: a[0], params_stacked)
        rank = lax.axis_index(axis)
        from .ring_attention import _pvary
        state = _pvary(jnp.zeros_like(xs[0]), axis)  # activation currently held
        outs = _pvary(jnp.zeros_like(xs), axis)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outs = carry
            # stage 0 ingests microbatch t (if any remain)
            feed = xs[jnp.minimum(t, n_micro - 1)]
            state = jnp.where(rank == 0, feed, state)
            new_state = stage_fn(params, state)
            # last stage emits result of microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(rank == n_stages - 1, out_idx >= 0)
            slot = jnp.maximum(out_idx, 0)
            outs = outs.at[slot].set(jnp.where(emit, new_state, outs[slot]))
            state = lax.ppermute(new_state, axis, fwd_perm)
            return state, outs

        state, outs = lax.fori_loop(0, total, tick, (state, outs))
        # only the last rank's outs are real; broadcast them
        outs = lax.psum(jnp.where(rank == n_stages - 1, outs, 0.0), axis)
        return outs

    fn = shard_map(local, mesh=mesh,
                   in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params,
                                                    is_leaf=lambda l: hasattr(l, "shape")),
                             P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)


class GluonPipelineStack:
    """Bridge structurally-identical gluon Blocks onto ``pipeline_apply``.

    This is the TPU-native expression of the reference's model-parallel
    LSTM doc case (``docs/faq/model_parallel_lstm.md`` /
    ``group2ctx``-based layer placement): the homogeneous middle of a
    model — e.g. a stack of LSTM layers, each ``(B, T, H) -> (B, T, H)``
    — runs one-stage-per-device over the ``pp`` mesh axis, while the
    heterogeneous ends (embedding, decoder) stay replicated outside.

    Usage::

        stack = GluonPipelineStack(layer_blocks, sample, mesh, axis='pp')
        y_mb = stack.apply(stack.stacked_params, x_microbatches)
        # ... train on a params pytree via jax.grad, then:
        stack.write_back(trained_params)

    The blocks must already be initialized and share parameter structure
    (same shapes in the same topological order); an input microbatch shape
    equals the inter-stage activation shape.
    """

    def __init__(self, blocks, sample, mesh: Mesh, axis: str = "pp"):
        from ..base import MXNetError
        from .. import symbol as sym_mod
        from .. import autograd
        from ..executor import _GraphLowering
        from ..ndarray.ndarray import _unwrap, _wrap

        if mesh.shape[axis] != len(blocks):
            raise MXNetError(
                f"GluonPipelineStack needs one block per '{axis}' device: "
                f"{len(blocks)} blocks vs mesh[{axis!r}]={mesh.shape[axis]}")
        self._blocks = list(blocks)
        self._mesh = mesh
        self._axis = axis

        sample = jnp.asarray(sample)
        with autograd.pause():                 # materialize deferred params
            for b in self._blocks:
                b(_wrap(sample))

        per_block_names = []
        per_block_pmaps = []
        lowering = None
        for b in self._blocks:
            x_sym = sym_mod.Variable("__pp_x")
            out = b(x_sym)
            if isinstance(out, (list, tuple)):
                out = out[0]
            low = _GraphLowering(out)
            names = [n for n in low.var_names if n != "__pp_x"]
            per_block_names.append(names)
            per_block_pmaps.append(
                {p.name: p for p in b.collect_params().values()})
            if lowering is None:
                lowering = low
        shapes0 = [per_block_pmaps[0][n].shape for n in per_block_names[0]]
        for pmap, names in zip(per_block_pmaps[1:], per_block_names[1:]):
            shapes = [pmap[n].shape for n in names]
            if shapes != shapes0:
                raise MXNetError(
                    "pipeline stages must be structurally identical; "
                    f"got param shapes {shapes} vs {shapes0}")
        self._canonical = per_block_names[0]
        self._per_block_names = per_block_names
        self._per_block_pmaps = per_block_pmaps
        raw = lowering.lower(is_train=True)

        has_rng = lowering.has_rng

        def stage_fn(params, x):
            ins = dict(zip(self._canonical, params))
            ins["__pp_x"] = x
            # rng-capable ops (e.g. RNN's dropout arg) get a FIXED stream:
            # the pipeline schedule is traced once, so per-tick rng would
            # leak schedule state into the stage; in-stage dropout is
            # deterministic per trace — put stochastic dropout outside the
            # pipelined stack if that matters
            outs, _ = raw(ins, jax.random.PRNGKey(0) if has_rng else None)
            return outs[0]

        self._stage_fn = stage_fn
        from jax.sharding import NamedSharding
        stage_spec = NamedSharding(mesh, P(axis))
        self.stacked_params = tuple(
            jax.device_put(
                jnp.stack([_unwrap(per_block_pmaps[j][per_block_names[j][i]]
                                   .data())
                           for j in range(len(self._blocks))]), stage_spec)
            for i in range(len(self._canonical)))

    def apply(self, stacked_params, x_microbatches):
        """(n_micro, B, ...) -> (n_micro, B, ...) through the device-mapped
        stage stack (GPipe schedule, differentiable)."""
        from jax.sharding import NamedSharding
        stage_spec = NamedSharding(self._mesh, P(self._axis))
        repl = NamedSharding(self._mesh, P())

        def _put(a, spec):
            # concrete arrays get placed here for caller convenience; under
            # a jit trace placement is the enclosing jit's job (pass
            # mesh-placed params in, as the example recipe does)
            if isinstance(a, jax.core.Tracer):
                return a
            a = jnp.asarray(a)
            return a if a.sharding == spec else jax.device_put(a, spec)

        stacked_params = jax.tree_util.tree_map(
            lambda a: _put(a, stage_spec), stacked_params)
        x_microbatches = _put(x_microbatches, repl)
        return pipeline_apply(self._stage_fn, stacked_params, x_microbatches,
                              self._mesh, self._axis)

    def write_back(self, stacked_params) -> None:
        """Push a trained stacked pytree back into the gluon blocks."""
        for i in range(len(self._canonical)):
            leaf = stacked_params[i]
            for j in range(len(self._blocks)):
                name = self._per_block_names[j][i]
                self._per_block_pmaps[j][name].data()._set_data(
                    jnp.asarray(leaf[j]))


class HeterogeneousPipeline:
    """UNEVEN pipeline stages: arbitrary gluon blocks placed on distinct
    devices (reference docs/faq/model_parallel_lstm.md — embed, LSTM
    layers and decoder on different devices with cross-device copies).

    Unlike :class:`GluonPipelineStack` (one shared stage program ppermuted
    SPMD-style, which requires structurally identical stages), each block
    here becomes its own ``ctx_group`` and the whole chain binds through
    ``PipelinedExecutor``: per-device jitted segment programs with
    explicit transfers. Microbatch overlap comes from XLA's per-device
    async dispatch queues — ``step()`` issues every microbatch's
    forward/backward before synchronizing, so device k runs microbatch m
    while device k+1 still runs m-1 (the GPipe schedule, scheduled by the
    runtime rather than by a traced loop).

    Usage::

        pipe = HeterogeneousPipeline(
            [embed_block, body_block, head_block],
            [mx.cpu(0), mx.cpu(1), mx.cpu(2)],
            sample, loss=gluon.loss.SoftmaxCrossEntropyLoss())
        for epoch in ...:
            loss = pipe.step(x_microbatches, y_microbatches, lr=0.1)
        pipe.write_back()      # trained values -> the gluon blocks
    """

    def __init__(self, blocks, contexts, sample, loss=None):
        from .. import symbol as sym_mod
        from .. import autograd
        from ..attribute import AttrScope
        from ..base import MXNetError
        from ..ndarray.ndarray import _unwrap, _wrap

        if len(blocks) != len(contexts):
            raise MXNetError(
                f"one context per stage: {len(blocks)} blocks vs "
                f"{len(contexts)} contexts")
        self._blocks = list(blocks)
        self._contexts = list(contexts)

        sample = jnp.asarray(sample)
        with autograd.pause():                 # materialize deferred params
            cur_a = _wrap(sample)
            for b in self._blocks:
                cur_a = b(cur_a)
                if isinstance(cur_a, (list, tuple)):
                    cur_a = cur_a[0]

        cur = sym_mod.Variable("data")
        group2ctx = {}
        for i, (b, c) in enumerate(zip(self._blocks, self._contexts)):
            gname = f"pp_stage{i}"
            group2ctx[gname] = c
            with AttrScope(ctx_group=gname):
                cur = b(cur)
                if isinstance(cur, (list, tuple)):
                    cur = cur[0]
        self._raw_symbol = cur        # pre-loss chain, used for inference
        shapes = {"data": tuple(sample.shape)}
        if loss is not None:
            with AttrScope(ctx_group=f"pp_stage{len(blocks) - 1}"):
                label = sym_mod.Variable("label")
                cur = loss(cur, label)

        self._pmap = {}
        for b in self._blocks:
            self._pmap.update({p.name: p for p in b.collect_params().values()})
        self._has_loss = loss is not None
        self._symbol = cur
        self._group2ctx = group2ctx
        self._shapes = shapes
        self._exec = None
        self._infer_exec = None
        self._infer_shape = None

    def _seed_executor(self, ex) -> None:
        """Seed an executor's params: from the current training executor
        when one exists (a rebind must carry trained values forward, not
        reset to the blocks' initial state), else from the gluon blocks."""
        from ..ndarray.ndarray import _unwrap
        src_args = self._exec.arg_dict if self._exec is not None else {}
        src_aux = self._exec.aux_dict if self._exec is not None else {}
        for dst, src in ((ex.arg_dict, src_args), (ex.aux_dict, src_aux)):
            for n, a in dst.items():
                if n in ("data", "label"):
                    continue
                if n in src:
                    a._set_data(src[n]._data)
                elif n in self._pmap:
                    a._set_data(_unwrap(self._pmap[n].data()))

    def _bind(self, data_shape, label_shape):
        shapes = {"data": tuple(data_shape)}
        if self._has_loss:
            shapes["label"] = tuple(label_shape)
        # inputs need no cotangents: step() never reads them, and under
        # grad_req='add' they would cost an extra accumulation per micro
        grad_req = {n: ("null" if n in ("data", "label") else "add")
                    for n in self._symbol.list_arguments()}
        ex = self._symbol.simple_bind(self._contexts[0], grad_req=grad_req,
                                      group2ctx=self._group2ctx, **shapes)
        self._seed_executor(ex)
        self._exec = ex
        self._bound_shapes = (tuple(data_shape),
                              tuple(label_shape) if label_shape else None)

    def forward(self, x):
        """Single-microbatch inference: the PRE-LOSS chain's predictions
        (whether or not a loss block was attached for training), read with
        the current trained weights."""
        from .. import nd
        x = nd.array(x) if not hasattr(x, "_data") else x
        if self._infer_exec is None or self._infer_shape != tuple(x.shape):
            self._infer_exec = self._raw_symbol.simple_bind(
                self._contexts[0], grad_req="null",
                group2ctx=self._group2ctx, data=tuple(x.shape))
            self._infer_shape = tuple(x.shape)
        self._seed_executor(self._infer_exec)
        self._infer_exec.forward(is_train=False, data=x)
        return self._infer_exec.outputs[0]

    def step(self, x_microbatches, y_microbatches, lr=0.05):
        """One GPipe step: accumulate grads over all microbatches (their
        stage programs overlap via async dispatch), then one SGD apply.
        Returns the mean scalar loss."""
        from .. import nd
        from ..base import MXNetError
        if not self._has_loss:
            raise MXNetError("step() needs a loss block at construction")
        n_micro = len(x_microbatches)
        x0 = jnp.asarray(x_microbatches[0])
        y0 = jnp.asarray(y_microbatches[0])
        if self._exec is None or self._bound_shapes != (tuple(x0.shape),
                                                        tuple(y0.shape)):
            self._bind(x0.shape, y0.shape)
        ex = self._exec
        for n in ex.grad_dict:
            g = ex.grad_dict[n]
            g._set_data(jnp.zeros_like(g._data))   # keeps device placement
        losses = []
        for xm, ym in zip(x_microbatches, y_microbatches):
            ex.forward(is_train=True, data=nd.array(jnp.asarray(xm)),
                       label=nd.array(jnp.asarray(ym)))
            losses.append(ex.outputs[0])
            ex.backward()       # grad_req='add' accumulates across micro
        for n, a in ex.arg_dict.items():
            if n in ("data", "label"):
                continue
            g = ex.grad_dict.get(n)
            if g is None:
                continue
            gd = jax.device_put(g._data, next(iter(a._data.devices())))
            a._set_data(a._data - (lr / n_micro) * gd)
        return float(sum(float(l.asnumpy().mean()) for l in losses) / n_micro)

    def write_back(self) -> None:
        """Trained executor values -> the originating gluon blocks,
        re-homed onto each parameter's own device (stage placement must
        not leak into the imperative blocks)."""
        for n, a in list(self._exec.arg_dict.items()) + \
                list(self._exec.aux_dict.items()):
            if n in self._pmap:
                home = self._pmap[n].list_ctx()[0].jax_device()
                self._pmap[n].data()._set_data(
                    jax.device_put(a._data, home))
