"""Pipeline parallelism over the 'pp' mesh axis.

Absent from the reference (SURVEY.md §2.3: "nearest: DAG-level
auto-parallelism"); built first-class here. GPipe-style schedule expressed
the SPMD way: every device holds ONE stage's parameters (stacked arrays
sharded on their leading 'stage' dim); a ``lax.fori_loop`` runs
n_micro + n_stages - 1 ticks in which each device applies its stage to the
activation it holds and ``ppermute``s the result to the next device.
Bubble fraction = (n-1)/(m+n-1), as usual — choose n_micro accordingly.

Constraint (same as scan-based pipelining generally): all stages share one
activation shape, e.g. a stack of identical transformer/MLP blocks.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "GluonPipelineStack"]


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis: str = "pp"):
    """Run ``stage_fn(params_i, x) -> x`` over n_stages = mesh[axis] stages.

    stacked_params: pytree whose leaves have leading dim n_stages (sharded on
    ``axis``). x_microbatches: (n_micro, *batch_shape) replicated input; the
    return is (n_micro, *batch_shape) of the final stage's outputs.
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total = n_micro + n_stages - 1

    def local(params_stacked, xs):
        # params_stacked leaves: (1, ...) local slice -> squeeze stage dim
        params = jax.tree_util.tree_map(lambda a: a[0], params_stacked)
        rank = lax.axis_index(axis)
        from .ring_attention import _pvary
        state = _pvary(jnp.zeros_like(xs[0]), axis)  # activation currently held
        outs = _pvary(jnp.zeros_like(xs), axis)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outs = carry
            # stage 0 ingests microbatch t (if any remain)
            feed = xs[jnp.minimum(t, n_micro - 1)]
            state = jnp.where(rank == 0, feed, state)
            new_state = stage_fn(params, state)
            # last stage emits result of microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(rank == n_stages - 1, out_idx >= 0)
            slot = jnp.maximum(out_idx, 0)
            outs = outs.at[slot].set(jnp.where(emit, new_state, outs[slot]))
            state = lax.ppermute(new_state, axis, fwd_perm)
            return state, outs

        state, outs = lax.fori_loop(0, total, tick, (state, outs))
        # only the last rank's outs are real; broadcast them
        outs = lax.psum(jnp.where(rank == n_stages - 1, outs, 0.0), axis)
        return outs

    fn = shard_map(local, mesh=mesh,
                   in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params,
                                                    is_leaf=lambda l: hasattr(l, "shape")),
                             P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)


class GluonPipelineStack:
    """Bridge structurally-identical gluon Blocks onto ``pipeline_apply``.

    This is the TPU-native expression of the reference's model-parallel
    LSTM doc case (``docs/faq/model_parallel_lstm.md`` /
    ``group2ctx``-based layer placement): the homogeneous middle of a
    model — e.g. a stack of LSTM layers, each ``(B, T, H) -> (B, T, H)``
    — runs one-stage-per-device over the ``pp`` mesh axis, while the
    heterogeneous ends (embedding, decoder) stay replicated outside.

    Usage::

        stack = GluonPipelineStack(layer_blocks, sample, mesh, axis='pp')
        y_mb = stack.apply(stack.stacked_params, x_microbatches)
        # ... train on a params pytree via jax.grad, then:
        stack.write_back(trained_params)

    The blocks must already be initialized and share parameter structure
    (same shapes in the same topological order); an input microbatch shape
    equals the inter-stage activation shape.
    """

    def __init__(self, blocks, sample, mesh: Mesh, axis: str = "pp"):
        from ..base import MXNetError
        from .. import symbol as sym_mod
        from .. import autograd
        from ..executor import _GraphLowering
        from ..ndarray.ndarray import _unwrap, _wrap

        if mesh.shape[axis] != len(blocks):
            raise MXNetError(
                f"GluonPipelineStack needs one block per '{axis}' device: "
                f"{len(blocks)} blocks vs mesh[{axis!r}]={mesh.shape[axis]}")
        self._blocks = list(blocks)
        self._mesh = mesh
        self._axis = axis

        sample = jnp.asarray(sample)
        with autograd.pause():                 # materialize deferred params
            for b in self._blocks:
                b(_wrap(sample))

        per_block_names = []
        per_block_pmaps = []
        lowering = None
        for b in self._blocks:
            x_sym = sym_mod.Variable("__pp_x")
            out = b(x_sym)
            if isinstance(out, (list, tuple)):
                out = out[0]
            low = _GraphLowering(out)
            names = [n for n in low.var_names if n != "__pp_x"]
            per_block_names.append(names)
            per_block_pmaps.append(
                {p.name: p for p in b.collect_params().values()})
            if lowering is None:
                lowering = low
        shapes0 = [per_block_pmaps[0][n].shape for n in per_block_names[0]]
        for pmap, names in zip(per_block_pmaps[1:], per_block_names[1:]):
            shapes = [pmap[n].shape for n in names]
            if shapes != shapes0:
                raise MXNetError(
                    "pipeline stages must be structurally identical; "
                    f"got param shapes {shapes} vs {shapes0}")
        self._canonical = per_block_names[0]
        self._per_block_names = per_block_names
        self._per_block_pmaps = per_block_pmaps
        raw = lowering.lower(is_train=True)

        has_rng = lowering.has_rng

        def stage_fn(params, x):
            ins = dict(zip(self._canonical, params))
            ins["__pp_x"] = x
            # rng-capable ops (e.g. RNN's dropout arg) get a FIXED stream:
            # the pipeline schedule is traced once, so per-tick rng would
            # leak schedule state into the stage; in-stage dropout is
            # deterministic per trace — put stochastic dropout outside the
            # pipelined stack if that matters
            outs, _ = raw(ins, jax.random.PRNGKey(0) if has_rng else None)
            return outs[0]

        self._stage_fn = stage_fn
        from jax.sharding import NamedSharding
        stage_spec = NamedSharding(mesh, P(axis))
        self.stacked_params = tuple(
            jax.device_put(
                jnp.stack([_unwrap(per_block_pmaps[j][per_block_names[j][i]]
                                   .data())
                           for j in range(len(self._blocks))]), stage_spec)
            for i in range(len(self._canonical)))

    def apply(self, stacked_params, x_microbatches):
        """(n_micro, B, ...) -> (n_micro, B, ...) through the device-mapped
        stage stack (GPipe schedule, differentiable)."""
        from jax.sharding import NamedSharding
        stage_spec = NamedSharding(self._mesh, P(self._axis))
        repl = NamedSharding(self._mesh, P())

        def _put(a, spec):
            # concrete arrays get placed here for caller convenience; under
            # a jit trace placement is the enclosing jit's job (pass
            # mesh-placed params in, as the example recipe does)
            if isinstance(a, jax.core.Tracer):
                return a
            a = jnp.asarray(a)
            return a if a.sharding == spec else jax.device_put(a, spec)

        stacked_params = jax.tree_util.tree_map(
            lambda a: _put(a, stage_spec), stacked_params)
        x_microbatches = _put(x_microbatches, repl)
        return pipeline_apply(self._stage_fn, stacked_params, x_microbatches,
                              self._mesh, self._axis)

    def write_back(self, stacked_params) -> None:
        """Push a trained stacked pytree back into the gluon blocks."""
        for i in range(len(self._canonical)):
            leaf = stacked_params[i]
            for j in range(len(self._blocks)):
                name = self._per_block_names[j][i]
                self._per_block_pmaps[j][name].data()._set_data(
                    jnp.asarray(leaf[j]))
