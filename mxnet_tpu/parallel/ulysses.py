"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

Complement to ring attention (see PAPERS.md, DeepSpeed-Ulysses): with T
sharded over 'sp', two ``all_to_all`` collectives re-shard to heads-parallel
so each device computes FULL-sequence attention for H/n heads, then shard
back. Cheaper than ring when H ≥ n and T/n blocks are small; ring wins at
very long T. Both are exposed so models can pick per-config.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import local_attention

__all__ = ["ulysses_attention", "ulysses_sharded"]


def _ulysses_local(q, k, v, axis_name: str, causal: bool, scale):
    """q,k,v: (B, H, T_local, D). all_to_all → (B, H_local, T, D)."""
    # split heads across ranks, gather sequence
    def seq2head(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    out = local_attention(qh, kh, vh, causal=causal, scale=scale)
    return head2seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False, scale: Optional[float] = None):
    """Global entry: q,k,v (B, H, T, D), T sharded on ``axis``; H must be
    divisible by the axis size."""
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None))
    return fn(q, k, v)


def ulysses_sharded(axis: str = "sp", causal: bool = False, scale=None):
    return functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                             scale=scale)
