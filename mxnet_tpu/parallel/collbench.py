"""Collectives bandwidth lab — measure the interconnect, then pick levers.

ROADMAP item 5's measurement half: the reference grew a
``tools/bandwidth/measure.py`` harness to size its allreduce tree against
PCIe/NVLink reality; the TPU-native twin measures the XLA collective path —
psum / reduce-scatter / all-gather / ppermute bytes/sec vs device count and
payload size, plus the 2-bit-compressed allreduce (error-feedback codec
over an allgather of packed codes) against its dense baseline — so the
``DataParallelTrainer`` comm levers (``grad_reduce=``,
``grad_reduce_dtype=``, ``bucket_bytes=``, ``compression=``) are chosen
from data, not vibes ("measure bytes/s per collective, then pick the
reduction strategy from data" — the Julia-to-TPU pod-scaling methodology,
PAPERS.md).

Every measurement persists as a :class:`~mxnet_tpu.observability.xcost.
CostLedger` row (``label="collbench"``) and publishes
``mxtpu_collective_bytes_total`` / ``mxtpu_collective_ms`` telemetry.
:func:`scaling_row` is the multichip training benchmark behind
``bench.py --multichip``: img/s/chip at N devices vs 1 — the real
scaling-efficiency number the ≥90% claim is judged against.

Reported bandwidth is **algorithm bandwidth**: the ring-algorithm bus
bytes each chip moves per operation (all-reduce ``2(n-1)/n``, reduce-
scatter / all-gather ``(n-1)/n``, ppermute ``1x`` of the payload) divided
by wall time — the unit NCCL/collective benchmarks report, so numbers
compare across device counts.

CLI: ``tools/collbench.py`` (tunnel-session registered). Docs:
``docs/performance.md`` "Scale-out performance".
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError, logger
from ..observability import catalog as _telemetry
from ..observability import metrics as _metrics
from ..observability import xcost as _xcost
from . import collectives as _coll

__all__ = ["OPS", "algo_bytes", "bench_collective", "bench_compression",
           "run", "scaling_row", "default_device_counts"]

OPS = ("psum", "reduce_scatter", "all_gather", "ppermute")


def default_device_counts(n_total: Optional[int] = None) -> List[int]:
    """1, 2, 4, ... up to the device count (always including the total):
    the sweep axis of the bytes/sec-vs-devices curve."""
    n_total = int(n_total if n_total is not None else len(jax.devices()))
    counts = []
    c = 1
    while c < n_total:
        counts.append(c)
        c *= 2
    counts.append(n_total)
    return sorted(set(counts))


def _submesh(n_devices: int, axis: str) -> Mesh:
    devices = jax.devices()
    if n_devices > len(devices):
        raise MXNetError(f"collbench: asked for {n_devices} devices, have "
                         f"{len(devices)}")
    return Mesh(np.asarray(devices[:n_devices]), (axis,))


def algo_bytes(op: str, payload_bytes: int, n_devices: int) -> int:
    """Ring-algorithm bus bytes per chip for one operation on a
    ``payload_bytes`` global payload."""
    n = max(1, int(n_devices))
    if op == "psum":
        return int(2 * (n - 1) / n * payload_bytes)
    if op in ("reduce_scatter", "all_gather"):
        return int((n - 1) / n * payload_bytes)
    if op == "ppermute":
        return int(payload_bytes) if n > 1 else 0
    raise MXNetError(f"collbench: unknown op {op!r} (want one of {OPS})")


@functools.lru_cache(maxsize=64)
def _coll_fn(op: str, mesh: Mesh, axis: str):
    n = mesh.shape[axis]

    def f(x):                       # x: this member's local block (m,)
        if op == "psum":
            return _coll.allreduce(x, axis)
        if op == "reduce_scatter":
            return _coll.reduce_scatter(x, axis)       # (m/n,)
        if op == "all_gather":
            return _coll.allgather(x, axis)            # (n*m,)
        if op == "ppermute":
            return _coll.ppermute(x, axis,
                                  [(i, (i + 1) % n) for i in range(n)])
        raise MXNetError(f"collbench: unknown op {op!r}")

    return jax.jit(shard_map(f, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis)))


def _payload(payload_bytes: int, n: int, dtype) -> jnp.ndarray:
    """A global array of ~payload_bytes, sized so every op tiles: the
    element count is a multiple of n*n (reduce_scatter needs the local
    block divisible by n again)."""
    itemsize = jnp.dtype(dtype).itemsize
    quantum = n * n
    elems = max(quantum, (payload_bytes // itemsize) // quantum * quantum)
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.uniform(-1, 1, (elems,)).astype(dtype))


def bench_collective(op: str, n_devices: Optional[int] = None,
                     payload_bytes: int = 1 << 20, dtype="float32",
                     steps: int = 10, warmup: int = 2,
                     axis: str = "dp") -> Dict[str, Any]:
    """Measure one collective: returns a ledger-shaped row with ``ms``
    (mean wall per op), ``algo_bytes`` and ``bytes_per_s``."""
    if steps < 1:
        raise MXNetError("collbench: steps must be >= 1")
    n = int(n_devices if n_devices is not None else len(jax.devices()))
    mesh = _submesh(n, axis)
    x = _payload(payload_bytes, n, dtype)
    spec = NamedSharding(mesh, P(axis))
    xd = jax.device_put(x, spec)
    fn = _coll_fn(op, mesh, axis)
    out = fn(xd)
    jax.block_until_ready(out)          # compile outside the window
    for _ in range(max(0, warmup)):
        out = fn(xd)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(xd)
    jax.block_until_ready(out)
    dt = max(time.perf_counter() - t0, 1e-9) / steps
    nbytes = int(x.size) * jnp.dtype(dtype).itemsize
    moved = algo_bytes(op, nbytes, n)
    dev = mesh.devices.ravel()[0]
    row = {
        "label": "collbench", "op": op, "n_devices": n,
        "payload_bytes": nbytes, "algo_bytes": moved,
        "ms": dt * 1e3, "bytes_per_s": moved / dt,
        "dtype": str(jnp.dtype(dtype)), "compression": None,
        "steps": steps, "device_kind": dev.device_kind,
        "platform": dev.platform,
    }
    _publish(row)
    return row


def bench_compression(n_devices: Optional[int] = None,
                      payload_bytes: int = 1 << 20,
                      threshold: float = 0.5, steps: int = 10,
                      warmup: int = 2, axis: str = "dp",
                      dense_row: Optional[Dict[str, Any]] = None
                      ) -> List[Dict[str, Any]]:
    """The gradient-compression on/off bandwidth comparison: one dense
    psum row and one 2-bit-compressed allreduce row (error-feedback codec
    via ``collectives.bucketed_allreduce(compression=...)``) over the same
    payload. The compressed row's ``algo_bytes`` counts the PACKED codes
    the allgather exchange actually moves — 16x fewer wire bytes than f32,
    bought with quantize/dequantize compute; this comparison is where that
    trade is measured instead of assumed. ``dense_row`` reuses an
    already-measured psum row for this (count, size) cell instead of
    measuring (and counting telemetry for) the dense baseline twice."""
    from ..gradient_compression import GradientCompression
    n = int(n_devices if n_devices is not None else len(jax.devices()))
    mesh = _submesh(n, axis)
    x = _payload(payload_bytes, n, "float32")
    spec = NamedSharding(mesh, P(axis))
    xd = jax.device_put(x, spec)
    rows = [dense_row if dense_row is not None else
            bench_collective("psum", n_devices=n,
                             payload_bytes=payload_bytes, steps=steps,
                             warmup=warmup, axis=axis)]
    gc = GradientCompression({"type": "2bit", "threshold": threshold})
    res = None

    def one():
        nonlocal res
        out, res = _coll.bucketed_allreduce(
            [xd], mesh, axis, bucket_bytes=1 << 62,
            compression=gc, residuals=res)
        return out[0]

    out = one()                         # compile outside the window
    jax.block_until_ready(out)
    for _ in range(max(0, warmup)):
        out = one()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = one()
    jax.block_until_ready(out)
    dt = max(time.perf_counter() - t0, 1e-9) / steps
    # wire bytes: every rank allgathers each peer's packed shard — the
    # all_gather algo bytes of the PACKED payload
    local = int(x.size) // n
    packed_global = n * gc.compressed_nbytes(local)
    moved = algo_bytes("all_gather", packed_global, n)
    dev = mesh.devices.ravel()[0]
    row = {
        "label": "collbench", "op": "psum_compressed", "n_devices": n,
        "payload_bytes": int(x.size) * 4, "algo_bytes": moved,
        "ms": dt * 1e3, "bytes_per_s": moved / dt if moved else 0.0,
        "dtype": "float32",
        "compression": {"type": "2bit", "threshold": threshold},
        "wire_reduction_x": (rows[0]["algo_bytes"] / moved
                            if moved else None),
        "steps": steps, "device_kind": dev.device_kind,
        "platform": dev.platform,
    }
    _publish(row)
    rows.append(row)
    return rows


def _publish(row: Dict[str, Any]) -> None:
    if _metrics.enabled():
        _telemetry.COLL_MS.observe(row["ms"], op=row["op"])
        _telemetry.COLL_BYTES.inc(int(row["payload_bytes"]), op=row["op"])


def run(ops: Sequence[str] = OPS,
        device_counts: Optional[Sequence[int]] = None,
        payload_sizes: Sequence[int] = (1 << 16, 1 << 20, 4 << 20),
        dtype="float32", steps: int = 10, warmup: int = 2,
        compression: Optional[float] = None, axis: str = "dp",
        ledger: Optional[_xcost.CostLedger] = None,
        emit=None) -> List[Dict[str, Any]]:
    """The full sweep: every (op, device count, payload size) cell, plus
    the compressed-vs-dense pair per (count, size) when ``compression``
    (a threshold) is given. Rows stream through ``emit`` as they land and
    persist to ``ledger`` (or the ambient ``MXNET_PERF_LEDGER``)."""
    led = ledger if ledger is not None else _xcost.get_ledger()
    rows: List[Dict[str, Any]] = []

    def _land(row):
        rows.append(row)
        if led is not None:
            try:
                led.append(row)
            except Exception as e:   # the lab must not die on bookkeeping
                logger.warning("collbench: ledger append failed: %r", e)
        if emit is not None:
            emit(row)

    for n in (device_counts if device_counts is not None
              else default_device_counts()):
        for size in payload_sizes:
            dense = None
            for op in ops:
                row = bench_collective(op, n_devices=n, payload_bytes=size,
                                       dtype=dtype, steps=steps,
                                       warmup=warmup, axis=axis)
                if op == "psum" and str(jnp.dtype(dtype)) == "float32":
                    dense = row     # reusable baseline for the compressed
                    #                 comparison: same payload, same cell
                _land(row)
            if compression is not None:
                pair = bench_compression(
                    n_devices=n, payload_bytes=size,
                    threshold=compression, steps=steps,
                    warmup=warmup, axis=axis, dense_row=dense)
                if dense is None:
                    # the ops loop did not measure the dense baseline this
                    # cell (psum absent / non-f32 dtype): the comparison's
                    # freshly-measured dense side must land too, not be
                    # paid for and dropped
                    _land(pair[0])
                for row in pair[1:]:
                    _land(row)
    return rows


# --------------------------------------------------------- scaling benchmark
def _scaling_net(prefix: str, classes: int):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential(prefix=prefix)
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu",
                      prefix=prefix + "c0_"),
            nn.GlobalAvgPool2D(prefix=prefix + "p0_"),
            nn.Dense(classes, prefix=prefix + "d0_"))
    net.initialize(mx.init.Xavier())
    return net, gluon.loss.SoftmaxCrossEntropyLoss()


def _measure_throughput(trainer, x, y, steps: int, warmup: int) -> float:
    spec = NamedSharding(trainer.mesh, P("dp"))
    loss = trainer.step(x, y)          # compile
    float(loss)
    xd = jax.device_put(jnp.asarray(x), spec)
    yd = jax.device_put(jnp.asarray(y), spec)
    for _ in range(max(0, warmup)):
        loss = trainer.step(xd, yd)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(xd, yd)
    float(loss)
    dt = max(time.perf_counter() - t0, 1e-9)
    return steps * int(x.shape[0]) / dt


def scaling_row(batch_per_chip: int = 8, image: int = 16, classes: int = 4,
                steps: int = 6, warmup: int = 2,
                grad_reduce: str = "reduce_scatter",
                grad_reduce_dtype=None,
                n_devices: Optional[int] = None,
                builder=None, data=None,
                ledger: Optional[_xcost.CostLedger] = None,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The REAL multichip scaling-efficiency measurement (``bench.py
    --multichip``): the same per-chip batch trained on 1 device and on N,
    with the gradient reduction configured by the comm levers, reported as
    ``img/s/chip at N / img/s/chip at 1`` — the number the ≥90% claim
    (ROADMAP item 5) is judged against, with full lever provenance in the
    row. ``builder(prefix, classes) -> (net, loss_fn)`` and
    ``data(global_batch) -> (x, y)`` override the default tiny conv
    workload (bench.py passes ResNet on a real chip window)."""
    from .data_parallel import DataParallelTrainer
    builder = builder or _scaling_net
    n = int(n_devices if n_devices is not None else len(jax.devices()))
    if data is None:
        rng = np.random.RandomState(0)

        def data(gbatch):
            x = rng.uniform(-1, 1, (gbatch, 3, image, image)) \
                .astype("float32")
            y = (np.arange(gbatch) % classes).astype("float32")
            return x, y

    results = {}
    comm = None
    opt_bytes = {}
    for label, count in (("1", 1), ("n", n)):
        if label == "n" and n == 1:
            results["n"] = results["1"]
            break
        mesh = _submesh(count, "dp")
        net, loss_fn = builder("collb_%s_" % label, classes)
        trainer = DataParallelTrainer(
            net, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
            mesh=mesh, grad_reduce=grad_reduce if count > 1 else "all_reduce",
            grad_reduce_dtype=grad_reduce_dtype if count > 1 else None)
        x, y = data(batch_per_chip * count)
        results[label] = _measure_throughput(trainer, x, y, steps, warmup) \
            / count
        if count == n:
            comm = trainer.comm_config()
            opt_bytes = trainer.opt_state_bytes()
        del trainer, net
    # published throughputs are rounded; derive the ratio from the SAME
    # rounded numbers so the row is self-consistent for any reader that
    # recomputes efficiency from its own fields
    per_1 = round(results["1"], 2)
    per_n = round(results["n"], 2)
    eff = per_n / per_1 if per_1 else 0.0
    dev = jax.devices()[0]
    row = {
        "metric": "multichip_scaling_efficiency",
        "value": round(eff, 4), "unit": "ratio",
        "label": "bench.multichip",
        "n_devices": n,
        "img_s_per_chip_1": per_1,
        "img_s_per_chip_n": per_n,
        "batch_per_chip": batch_per_chip,
        "comm_config": comm,
        "opt_state_bytes": opt_bytes,
        "device_kind": dev.device_kind, "platform": dev.platform,
        "steps": steps,
    }
    if extra:
        # caller provenance (model / provenance / degraded) merged BEFORE
        # the ledger append, so the persisted row carries the same
        # identity as the printed one — a model-filtered baseline reader
        # must never match a row whose model field only existed in memory
        row.update(extra)
    led = ledger if ledger is not None else _xcost.get_ledger()
    if led is not None:
        try:
            led.append(row)
        except Exception as e:
            logger.warning("collbench: scaling-row ledger append failed: %r",
                           e)
    return row
