"""mxnet_tpu.parallel — SPMD parallelism over device meshes.

The TPU-native expression of SURVEY.md §2.3's strategy inventory:

=====================  ==============================================
reference mechanism     here
=====================  ==============================================
KVStore local/device    DataParallelTrainer / bucketed_allreduce (psum on 'dp')
KVStore dist_sync       same + jax.distributed multi-host mesh
(absent) ZeRO-1         DataParallelTrainer(grad_reduce='reduce_scatter')
tools/bandwidth         collbench (collectives bytes/sec lab + scaling row)
group2ctx model par.    shard_gluon_params / NamedSharding placement
(absent) tensor par.    tensor_parallel.* (Megatron col/row split on 'tp')
(absent) pipeline       pipeline.pipeline_apply (GPipe over 'pp')
(absent) seq/context    ring_attention / ulysses_attention on 'sp'
(absent) expert par.    expert_parallel.ep_moe_ffn (MoE all_to_all on 'ep')
=====================  ==============================================
"""
from .mesh import (make_mesh, auto_mesh, local_mesh, replicated, shard_spec,
                   Mesh, NamedSharding, PartitionSpec)
from . import collectives
from .collectives import psum_arrays, bucketed_allreduce
from . import collbench
from .data_parallel import DataParallelTrainer
from .ring_attention import ring_attention, local_attention
from .ulysses import ulysses_attention
from . import tensor_parallel
from .tensor_parallel import shard_gluon_params
from .pipeline import (pipeline_apply, GluonPipelineStack,
                       HeterogeneousPipeline)
from . import expert_parallel
from .expert_parallel import ep_moe_ffn, moe_ffn_reference, MoEParams
