"""Device mesh construction.

The TPU-native substrate for every parallelism strategy in SURVEY.md §2.3:
data parallel (the reference's KVStore tiers), tensor parallel (absent in the
reference — first-class here), pipeline, and sequence/context parallel.
Axis convention: ('dp', 'fsdp', 'tp', 'pp', 'sp', 'ep') — any subset.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "auto_mesh", "local_mesh", "replicated", "shard_spec",
           "Mesh", "NamedSharding", "PartitionSpec"]


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the device
    count (use -1 once for 'the rest'). Axis order follows insertion order —
    put the fastest-varying (highest-bandwidth, usually 'tp') LAST so it maps
    to the innermost ICI ring."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("only one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(axes, sizes))} does not cover "
                         f"{n} devices")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def auto_mesh(dp: int = -1, tp: int = 1, pp: int = 1, sp: int = 1,
              devices=None) -> Mesh:
    """Common 4-axis mesh with dp inferred."""
    axes = {}
    if pp != 1:
        axes["pp"] = pp
    axes["dp"] = dp
    if sp != 1:
        axes["sp"] = sp
    if tp != 1:
        axes["tp"] = tp
    if "pp" not in axes:
        axes.setdefault("dp", -1)
    return make_mesh(axes, devices)


def local_mesh(axis: str = "dp", devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_spec(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*axes))
