"""Collective primitives over ICI/DCN.

TPU-native replacement for the reference's comm stack (SURVEY.md §5.8):
ncclAllReduce/Bcast (kvstore_nccl.h:402,482), the CommDeviceTree spanning
trees (comm_tree.h, gpu_topology.h), and ps-lite ZPush/ZPull all become XLA
collectives on a named mesh axis. The topology-aware tree construction the
reference builds by parsing PCIe/NVLink link matrices is XLA's job here —
collectives ride the ICI torus with compiler-chosen algorithms.

These wrappers are meant for use inside ``shard_map``-ed functions; outside,
use ``psum_arrays`` which wraps its own shard_map.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import catalog as _telemetry
from ..observability import metrics as _obs_metrics


def _count_dispatch(op: str, arrays) -> None:
    """Host-side dispatch accounting (counters only, never inside a traced
    function — the inside-shard_map primitives above stay untouched)."""
    if not _obs_metrics.enabled():
        return
    _telemetry.COLL_DISPATCHES.inc(op=op)
    nbytes = 0
    for a in arrays:
        size = getattr(a, "size", None)
        dt = getattr(a, "dtype", None)
        if size is not None and dt is not None:
            nbytes += int(size) * int(jnp.dtype(dt).itemsize)
    if nbytes:
        _telemetry.COLL_BYTES.inc(nbytes, op=op)

__all__ = ["allreduce", "allgather", "reduce_scatter", "broadcast", "ppermute",
           "all_to_all", "psum_arrays", "cross_process_allreduce",
           "cross_process_allreduce_many", "cross_process_alltoall",
           "cross_process_allgather_tiled", "cross_process_broadcast0",
           "bucket_assignment", "bucketed_allreduce"]


# ---- inside-shard_map primitives (thin, named-axis) -----------------------
def allreduce(x, axis_name: str):
    return lax.psum(x, axis_name)


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: str, src: int = 0):
    """Every member gets the ``src`` member's value: mask every other
    contribution to zero and psum (one collective; XLA lowers the
    one-nonzero-operand psum to a broadcast from ``src`` on TPU)."""
    idx = lax.axis_index(axis_name)
    return lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)), axis_name)


def ppermute(x, axis_name: str, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


# ---- coordination-service fallback ----------------------------------------
# XLA cross-process collectives need backend support (TPU ICI/DCN, or a
# CPU/GPU build with cross-host collectives). jax 0.4.x's CPU backend has
# none — every multiprocess computation raises "Multiprocess computations
# aren't implemented on the CPU backend" — yet the dist kvstore must still
# work there (tests/test_dist.py runs real multi-process clusters on CPU).
# The coordination service (already joined for barriers/heartbeats) is a
# correct, slow wire: each rank publishes its host array under a
# round-numbered key and reads every peer's. Used only when the XLA path
# is impossible; TPU traffic never touches it.

_coord_rounds: dict = {}


@functools.lru_cache(maxsize=1)
def _xla_cross_process_ok() -> bool:
    """Probe (once, collectively — every rank calls this before its first
    host-level collective, in the same program order) whether the backend
    can run a real multiprocess computation."""
    if jax.process_count() == 1:
        return True
    try:
        from jax.experimental import multihost_utils
        multihost_utils.process_allgather(jnp.zeros((1,), jnp.float32)[None],
                                          tiled=True)
        return True
    except Exception:
        return False


def _coord_timeout_ms() -> int:
    from ..base import get_env
    return int(float(get_env("MXNET_KVSTORE_BARRIER_TIMEOUT", 300.0)) * 1000)


def _coord_gather(x, tag: str):
    """Rank-ordered list of every process's copy of host array ``x``,
    exchanged over the coordination KV. Per-tag round numbers keep
    successive calls collision-free (ranks call collectives in identical
    program order — the same invariant barrier ids rely on)."""
    import numpy as np

    from .. import kvstore as _kv
    client = _kv._dist_client()
    if client is None:
        raise RuntimeError("coordination-service collective fallback "
                           "requires a joined jax.distributed cluster")
    nprocs, rank = jax.process_count(), jax.process_index()
    rnd = _coord_rounds.get(tag, 0)
    _coord_rounds[tag] = rnd + 1
    key = lambda rr, p: "mxcoll/%s/%d/%d" % (tag, rr, p)
    client.key_value_set_bytes(key(rnd, rank),
                               _kv._encode_array(np.asarray(x)))
    timeout_ms = _coord_timeout_ms()
    out = [np.asarray(_kv._decode_array(
        client.blocking_key_value_get_bytes(key(rnd, p), timeout_ms)))
        for p in range(nprocs)]
    # reclaim this rank's round-(n-2) key: every peer observed in round
    # rnd-1 had fully finished its rnd-2 reads (calls are sequential per
    # rank), so nobody can still need it
    if rnd >= 2:
        try:
            client.key_value_delete(key(rnd - 2, rank))
        except Exception:
            pass
    return out


def cross_process_broadcast0(x):
    """Every process gets process 0's host-local array (the kvstore init
    weight broadcast). XLA collective when the backend supports it, the
    coordination KV otherwise (one write by rank 0, one read per peer;
    keys are kept — init runs a bounded number of times and a reader may
    lag arbitrarily, so reclaiming here could strand it)."""
    if jax.process_count() == 1:
        return jnp.asarray(x)
    _count_dispatch("cp_broadcast", (x,))
    if _xla_cross_process_ok():
        from jax.experimental import multihost_utils
        return jnp.asarray(multihost_utils.broadcast_one_to_all(x))
    from .. import kvstore as _kv
    client = _kv._dist_client()
    rnd = _coord_rounds.get("bcast0", 0)
    _coord_rounds["bcast0"] = rnd + 1
    key = "mxcoll/bcast0/%d" % rnd
    if jax.process_index() == 0:
        import numpy as np
        client.key_value_set_bytes(key, _kv._encode_array(np.asarray(x)))
    blob = client.blocking_key_value_get_bytes(key, _coord_timeout_ms())
    return jnp.asarray(_kv._decode_array(blob))


# ---- host-level helpers ----------------------------------------------------
@functools.lru_cache(maxsize=64)
def _psum_fn(mesh: Mesh, axis: str, n: int):
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=tuple(P(axis) for _ in range(n)),
                       out_specs=tuple(P(axis) for _ in range(n)))
    def f(*xs):
        return tuple(lax.psum(x, axis) for x in xs)

    return jax.jit(f)


def psum_arrays(arrays: Sequence, mesh: Mesh, axis: str = "dp") -> List:
    """Allreduce a list of arrays sharded on ``axis`` (leading dim)."""
    _count_dispatch("psum", arrays)
    fn = _psum_fn(mesh, axis, len(arrays))
    return list(fn(*arrays))


def cross_process_allreduce(x):
    """Sum an identical-shaped host-local array across processes (the
    dist_sync push path). Gathers on a new leading axis (tiled concat — the
    stacking path rejects multi-host arrays) and reduces it."""
    if jax.process_count() == 1:
        return x
    _count_dispatch("cp_allreduce", (x,))
    if not _xla_cross_process_ok():
        import numpy as np
        parts = _coord_gather(x, "allreduce")
        return jnp.asarray(np.sum(np.stack(parts, axis=0), axis=0))
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(x[None], tiled=True)
    return jnp.asarray(gathered).sum(axis=0)


def cross_process_allreduce_many(arrays: Sequence) -> List:
    """Allreduce a whole bucket of host-local arrays with ONE collective:
    flatten+concat per dtype, gather once, sum, split back. This is the
    network-level half of the reference's MXNET_UPDATE_AGGREGATION_SIZE
    batching (kvstore_nccl.h aggregates push/pull pairs the same way)."""
    arrays = list(arrays)
    if jax.process_count() == 1 or len(arrays) <= 1:
        return [cross_process_allreduce(a) for a in arrays]
    out: List = [None] * len(arrays)
    by_dtype: dict = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(jnp.asarray(a).dtype, []).append(i)
    for dt, idxs in by_dtype.items():
        flat = jnp.concatenate([jnp.ravel(jnp.asarray(arrays[i]))
                                for i in idxs])
        red = cross_process_allreduce(flat)
        off = 0
        for i in idxs:
            n = arrays[i].size
            out[i] = red[off:off + n].reshape(arrays[i].shape)
            off += n
    return out


def cross_process_alltoall(x):
    """All-to-all exchange of per-destination rows across processes.

    ``x`` is a host-local ``(nprocs, s)`` array whose row ``j`` is this
    rank's payload for process ``j``. Returns a host-local ``(nprocs, s)``
    array whose row ``p`` is process ``p``'s payload for THIS rank.

    This is the wire primitive behind the reduce-scatter-shaped compressed
    gradient exchange (kvstore ``_reduce_compressed``): each rank ships only
    one 1/N-sized shard to each peer (total bytes on the wire per rank ~= the
    full payload ONCE, vs N x for an allgather), mirroring how the
    reference's compressed push fans worker payloads out across server
    shards (kvstore_dist.h:593-643 part offsets) rather than replicating
    them to every node.
    """
    nprocs = jax.process_count()
    x = jnp.asarray(x)
    if nprocs == 1:
        return x
    _count_dispatch("cp_alltoall", (x,))
    if not _xla_cross_process_ok():
        import numpy as np
        # row p of MY result is row my_rank of rank p's matrix
        parts = _coord_gather(x, "alltoall")
        mine = jax.process_index()
        return jnp.asarray(np.stack([parts[p][mine]
                                     for p in range(nprocs)], axis=0))
    from jax.experimental import multihost_utils
    mesh, fn = _alltoall_fn(nprocs)
    g = multihost_utils.host_local_array_to_global_array(
        x[None], mesh, P("proc"))
    out = fn(g)
    local = multihost_utils.global_array_to_host_local_array(
        out, mesh, P("proc"))
    return jnp.asarray(local)[0]


@functools.lru_cache(maxsize=8)
def _alltoall_fn(nprocs: int):
    """One process mesh + jitted alltoall per cluster size — jax.jit caches
    compilations per (shape, dtype) under the stable function identity (the
    module's _psum_fn pattern), so the per-step compressed exchange does not
    retrace."""
    import numpy as np
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    mesh = Mesh(np.array(devs).reshape(nprocs, -1), ("proc", "dev"))

    def f(blk):                       # (1, nprocs, s) local block
        y = lax.all_to_all(blk, "proc", split_axis=1, concat_axis=0,
                           tiled=True)          # (nprocs, 1, s)
        return y.reshape(blk.shape)             # (1, nprocs, s)

    try:
        fn = shard_map(f, mesh=mesh, in_specs=P("proc"), out_specs=P("proc"))
    except TypeError:  # older shard_map signature
        fn = shard_map(f, mesh, in_specs=P("proc"), out_specs=P("proc"))
    return mesh, jax.jit(fn)


def cross_process_allgather_tiled(x):
    """Tiled allgather of a host-local 1-D shard: returns the rank-order
    concatenation ``(nprocs * s,)`` on every process."""
    if jax.process_count() == 1:
        return jnp.asarray(x)
    _count_dispatch("cp_allgather", (x,))
    if not _xla_cross_process_ok():
        import numpy as np
        parts = _coord_gather(np.asarray(x), "allgather")
        return jnp.asarray(np.concatenate(parts, axis=0).reshape(-1))
    from jax.experimental import multihost_utils
    return jnp.asarray(
        multihost_utils.process_allgather(jnp.asarray(x)[None], tiled=True)
    ).reshape(-1)


def bucket_assignment(nbytes: Sequence[int],
                      bucket_bytes: int) -> List[List[int]]:
    """Greedy order-preserving bucketing: indices are appended in order
    until a bucket reaches ``bucket_bytes``, then a new one starts. This is
    the ONE bucket-assignment rule — shared by :func:`bucketed_allreduce`
    and by ``DataParallelTrainer``'s in-trace gradient bucketing
    (``bucket_bytes=``), so a tuner-searched bucket size means the same
    grouping on both paths."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    size = 0
    for i, n in enumerate(nbytes):
        cur.append(i)
        size += int(n)
        if size >= bucket_bytes:
            buckets.append(cur)
            cur, size = [], 0
    if cur:
        buckets.append(cur)
    return buckets


@functools.lru_cache(maxsize=32)
def _compressed_psum_fn(mesh: Mesh, axis: str, threshold: float, n: int):
    """shard_map'd compressed allreduce of ``n`` arrays: each member 2-bit
    quantizes its local block against its residual shard, the 16x-smaller
    packed payloads cross the interconnect via one tiled all_gather per
    array, and every member dequantize-sums all ranks' codes locally —
    wire bytes = ranks x packed vs ranks x f32 for the dense psum."""
    from ..gradient_compression import (_quantize_2bit, _dequantize_sum_rows)

    def f(*xs_and_res):
        xs, res = xs_and_res[:n], xs_and_res[n:]
        outs, new_res = [], []
        for x, r in zip(xs, res):
            shape = x.shape
            packed, nr = _quantize_2bit(x.astype(jnp.float32),
                                        r.astype(jnp.float32),
                                        threshold=threshold)
            rows = lax.all_gather(packed, axis)          # (ranks, s) uint8
            dense = _dequantize_sum_rows(rows, threshold=threshold)
            outs.append(dense[:x.size].reshape(shape).astype(x.dtype))
            new_res.append(nr)
        return tuple(outs) + tuple(new_res)

    specs = tuple(P(axis) for _ in range(2 * n))
    return jax.jit(shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs))


def bucketed_allreduce(grads: List, mesh: Mesh, axis: str = "dp",
                       bucket_bytes: int = 4 << 20,
                       compression=None, residuals: Optional[List] = None):
    """Bucket small gradients into fused allreduce dispatches, preserving
    order so early (high-priority) buckets land first — the reference's
    priority=-index comm overlap (model.py:150-160) and
    MXNET_UPDATE_AGGREGATION_SIZE batching (kvstore_nccl.h).

    ``compression`` (a :class:`~mxnet_tpu.gradient_compression.
    GradientCompression` or its params dict) routes every bucket through
    the 2-bit error-feedback codec: each mesh member quantizes its local
    shard, only the packed codes cross the interconnect (allgather + local
    dequantize-sum — the reference's compressed push shape), and the
    caller-held ``residuals`` (same shapes/shardings as ``grads``; zeros
    when None) carry the error feedback. With compression the return value
    is ``(reduced, new_residuals)`` so the caller can thread the residual
    stream into the next call; without it, just ``reduced`` (unchanged
    signature)."""
    gc = None
    if compression is not None:
        from ..gradient_compression import GradientCompression
        gc = compression if isinstance(compression, GradientCompression) \
            else GradientCompression(compression)
    out: List = [None] * len(grads)
    new_res: List = [None] * len(grads)
    if gc is not None and residuals is None:
        residuals = [jnp.zeros_like(jnp.asarray(g, jnp.float32))
                     for g in grads]
    for bucket in bucket_assignment(
            [g.size * g.dtype.itemsize for g in grads], bucket_bytes):
        if gc is None:
            reduced = psum_arrays([grads[j] for j in bucket], mesh, axis)
            for j, r in zip(bucket, reduced):
                out[j] = r
        else:
            _count_dispatch("psum_compressed", [grads[j] for j in bucket])
            fn = _compressed_psum_fn(mesh, axis, gc.threshold, len(bucket))
            res = fn(*([grads[j] for j in bucket]
                       + [residuals[j] for j in bucket]))
            for k, j in enumerate(bucket):
                out[j] = res[k]
                new_res[j] = res[len(bucket) + k]
    if gc is None:
        return out
    return out, new_res
