"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

The reference predates attention entirely (SURVEY.md §5.7: its long-sequence
story is bucketing + fused cuDNN RNN); this module is the long-context
capability the north star requires as first-class. Design follows the
blockwise/ring formulation (Liu et al.; see PAPERS.md): each device holds a
sequence shard of Q, K, V; K/V blocks rotate around the ICI ring via
``ppermute`` while each device accumulates its Q-shard's attention with an
online (log-sum-exp) softmax — memory O(T/n · T/n), full overlap of compute
with neighbor transfers.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "local_attention", "ring_attention_sharded"]


def _pvary(x, axis_name):
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return lax.pvary(x, (axis_name,))


def local_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    q_offset: int = 0, k_offset: int = 0):
    """Plain single-device attention; q,k,v: (B, H, T, D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2]) + k_offset
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    return jax.nn.softmax(scores, axis=-1) @ v


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Runs inside shard_map. q,k,v: (B, H, Tq_local, D) on each device."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    sc = scale if scale is not None else 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]  # pass kv to the next rank

    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    # constants start 'unvarying' over the manual axis; the loop carry becomes
    # varying after the first iteration — pre-cast so types line up (jax vma)
    acc0, m0, l0 = (_pvary(x, axis_name) for x in (acc0, m0, l0))

    def body(i, carry):
        acc, m, l, k_blk, v_blk = carry
        src = (my - i) % n  # whose kv shard we hold this tick
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * sc
        if causal:
            qpos = jnp.arange(Tq) + my * Tq
            kpos = jnp.arange(Tk) + src * Tk
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask, scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked blocks (exp(-inf - -inf))
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return acc_new, m_new, l_new, k_next, v_next

    acc, m, l, _, _ = lax.fori_loop(0, n, body, (acc0, m0, l0, k, v))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Global-array entry: q,k,v (B, H, T, D) with T sharded over ``axis``."""
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None))
    return fn(q, k, v)


def ring_attention_sharded(axis: str = "sp", causal: bool = False,
                           scale: Optional[float] = None):
    """For composition inside an existing shard_map region."""
    return functools.partial(_ring_attention_local, axis_name=axis,
                             causal=causal, scale=scale)
