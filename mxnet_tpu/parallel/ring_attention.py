"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

The reference predates attention entirely (SURVEY.md §5.7: its long-sequence
story is bucketing + fused cuDNN RNN); this module is the long-context
capability the north star requires as first-class. Design follows the
blockwise/ring formulation (Liu et al.; see PAPERS.md): each device holds a
sequence shard of Q, K, V; K/V blocks rotate around the ICI ring via
``ppermute`` while each device accumulates its Q-shard's attention with an
online (log-sum-exp) softmax — memory O(T/n · T/n), full overlap of compute
with neighbor transfers.

The per-ring-step partial attention is the Pallas flash kernel
(``ops.pallas_kernels.flash_attention_with_lse``) on TPU; the whole ring loop
carries a custom VJP implementing the ring-flash backward: a second ring pass
where dK/dV accumulators rotate with their K/V blocks, so each shard's
gradient arrives back at its owner after n hops with every device's
contribution summed — no cross-shard gather, all traffic on ICI.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas_kernels import (flash_attention, flash_attention_with_lse,
                                  flash_attention_bwd, _NEG_INF)

__all__ = ["ring_attention", "local_attention", "ring_attention_sharded"]


def _pvary(x, axis_name):
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))
    # jax < 0.5: no varying-axis type system inside shard_map — values are
    # implicitly device-varying, so the cast is the identity
    return x


def local_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    q_offset: int = 0, k_offset: int = 0):
    """Single-device attention (flash path); q,k,v: (B, H, T, D)."""
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           q_offset=q_offset, k_offset=k_offset)


def _merge(acc, lse, o_blk, lse_blk):
    """Merge a normalized partial (o_blk, lse_blk) into the running (acc, lse).

    out = Σ_b exp(lse_b − lse_tot)·o_b with lse_tot = logaddexp over blocks.
    """
    m = jnp.maximum(lse, lse_blk)
    safe_m = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    e_old = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(lse - safe_m))
    e_blk = jnp.where(lse_blk <= _NEG_INF / 2, 0.0, jnp.exp(lse_blk - safe_m))
    denom = jnp.maximum(e_old + e_blk, 1e-30)
    lse_comb = jnp.where((lse <= _NEG_INF / 2) & (lse_blk <= _NEG_INF / 2),
                         _NEG_INF, safe_m + jnp.log(denom))
    # invariant: acc = Σ_b o_b · exp(lse_b − lse_comb)  (exact, normalized)
    w_old = e_old / denom
    w_blk = e_blk / denom
    acc_new = acc * w_old[..., None] + o_blk.astype(jnp.float32) * w_blk[..., None]
    return acc_new, lse_comb


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Runs inside shard_map. q,k,v: (B, H, T_local, D) on each device."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    sc = scale if scale is not None else 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]  # pass kv to the next rank

    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    lse0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    # constants start 'unvarying' over the manual axis; the loop carry becomes
    # varying after the first iteration — pre-cast so types line up (jax vma)
    acc0, lse0 = (_pvary(x, axis_name) for x in (acc0, lse0))

    def body(i, carry):
        acc, lse, k_blk, v_blk = carry
        src = (my - i) % n  # whose kv shard we hold this tick
        o_blk, lse_blk = flash_attention_with_lse(
            q, k_blk, v_blk, causal=causal, scale=sc,
            q_offset=my * Tq, k_offset=src * Tk)
        acc, lse = _merge(acc, lse, o_blk, lse_blk)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return acc, lse, k_next, v_next

    acc, lse, _, _ = lax.fori_loop(0, n, body, (acc0, lse0, k, v))
    return acc.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, g):
    """Second ring pass: dK/dV accumulators travel WITH their K/V blocks."""
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    sc = scale if scale is not None else 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def flat(x):
        return x.reshape(B * H, x.shape[2], D)

    qf, outf, gf = flat(q), flat(out), flat(g)
    lsef = lse.reshape(B * H, Tq)

    dq0 = jnp.zeros_like(q, dtype=jnp.float32)  # varying (inherits from q)
    dk0 = _pvary(jnp.zeros((B, H, Tk, D), jnp.float32), axis_name)
    dv0 = _pvary(jnp.zeros((B, H, Tk, D), jnp.float32), axis_name)

    def body(i, carry):
        dq, dk, dv, k_blk, v_blk = carry
        src = (my - i) % n
        # shared blockwise flash backward (O(Tq·block) memory per step)
        dq_c, dk_c, dv_c = flash_attention_bwd(
            qf, flat(k_blk), flat(v_blk), outf, lsef, gf, sc, causal,
            q_offset=my * Tq, k_offset=src * Tk)
        dq = dq + dq_c.reshape(B, H, Tq, D)
        # accumulators ride the ring alongside their kv block
        dk = lax.ppermute(dk + dk_c.reshape(B, H, Tk, D), axis_name, perm)
        dv = lax.ppermute(dv + dv_c.reshape(B, H, Tk, D), axis_name, perm)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return dq, dk, dv, k_next, v_next

    dq, dk, dv, _, _ = lax.fori_loop(
        0, n, body, (dq0, dk0, dv0, k, v))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention_local.defvjp(_ring_fwd, _ring_bwd)


def _ring_local(q, k, v, *, axis_name, causal, scale):
    # custom_vjp nondiff args must be positional — keyword-friendly shim
    return _ring_attention_local(q, k, v, axis_name, causal, scale)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Global-array entry: q,k,v (B, H, T, D) with T sharded over ``axis``."""
    fn = shard_map(
        functools.partial(_ring_local, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None))
    return fn(q, k, v)


def ring_attention_sharded(axis: str = "sp", causal: bool = False,
                           scale: Optional[float] = None):
    """For composition inside an existing shard_map region."""
    return functools.partial(_ring_local, axis_name=axis,
                             causal=causal, scale=scale)
