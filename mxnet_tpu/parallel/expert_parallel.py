"""Expert parallelism — Mixture-of-Experts with token dispatch over the
'ep' mesh axis.

Absent from the reference (SURVEY.md §2.3 "Expert parallelism: Absent");
built first-class here because EP is how modern long-context/distributed
workloads scale FFN capacity. TPU-native shape: experts live one (or more)
per device along 'ep'; tokens route to their expert via ONE all_to_all,
run the expert FFN as dense batched matmuls on the MXU, and return via a
second all_to_all. Capacity-factor truncation keeps every shape static for
XLA; dropped tokens fall back to the residual path (standard Switch-style
behavior).

Surfaces mirror tensor_parallel.py:
- ``moe_dispatch``/``moe_combine``/``ep_moe_ffn`` — functional pieces for
  use INSIDE shard_map regions (axis_name = 'ep');
- ``MoEParams.init`` + ``moe_ffn_reference`` — a single-device reference
  implementation tests compare the sharded path against.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["MoEParams", "moe_ffn_reference", "ep_moe_ffn", "top1_gate"]


class MoEParams(NamedTuple):
    """Per-device shard: this device's experts' weights.
    w_gate is replicated; w1/b1/w2/b2 lead with a local-experts axis."""
    w_gate: jax.Array        # (D, E_total)
    w1: jax.Array            # (E_local, D, H)
    b1: jax.Array            # (E_local, H)
    w2: jax.Array            # (E_local, H, D)
    b2: jax.Array            # (E_local, D)

    @staticmethod
    def init(key, d_model: int, d_hidden: int, n_experts: int,
             n_local: int = None, dtype=jnp.float32) -> "MoEParams":
        n_local = n_local or n_experts
        ks = jax.random.split(key, 3)
        scale1 = 1.0 / jnp.sqrt(d_model)
        scale2 = 1.0 / jnp.sqrt(d_hidden)
        return MoEParams(
            w_gate=jax.random.normal(ks[0], (d_model, n_experts),
                                     dtype) * scale1,
            w1=jax.random.normal(ks[1], (n_local, d_model, d_hidden),
                                 dtype) * scale1,
            b1=jnp.zeros((n_local, d_hidden), dtype),
            w2=jax.random.normal(ks[2], (n_local, d_hidden, d_model),
                                 dtype) * scale2,
            b2=jnp.zeros((n_local, d_model), dtype))


def top1_gate(x, w_gate):
    """Switch-style top-1 gating: (expert id, gate probability) per token."""
    logits = jnp.einsum("td,de->te", x, w_gate)
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    return idx, jnp.take_along_axis(probs, idx[:, None], axis=1)[:, 0]


def _expert_ffn(w1, b1, w2, b2, tokens):
    """(E, C, D) tokens through per-expert FFN — batched MXU matmuls."""
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", tokens, w1) + b1[:, None, :])
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def moe_ffn_reference(params: MoEParams, x, capacity_factor: float = 1.25):
    """Single-device MoE (all experts local) — the semantics the EP path
    must reproduce. x: (T, D) -> (T, D)."""
    T, D = x.shape
    E = params.w_gate.shape[1]
    cap = int(max(1, capacity_factor * T / E))
    idx, gate = top1_gate(x, params.w_gate)

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)          # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1             # (T, E)
    pos_in_e = jnp.max(pos, axis=1)                           # (T,)
    keep = pos_in_e < cap

    buf = jnp.zeros((E, cap, D), x.dtype)
    buf = buf.at[idx, jnp.clip(pos_in_e, 0, cap - 1)].add(
        jnp.where(keep[:, None], x, 0))
    out_buf = _expert_ffn(params.w1, params.b1, params.w2, params.b2, buf)
    y = out_buf[idx, jnp.clip(pos_in_e, 0, cap - 1)]
    # dropped tokens pass through the residual (zero expert contribution)
    return jnp.where(keep[:, None], gate[:, None] * y, 0.0)


def ep_moe_ffn(params: MoEParams, x_local, axis_name: str = "ep",
               capacity_factor: float = 1.25):
    """Expert-parallel MoE for use INSIDE shard_map: tokens sharded on
    ``axis_name`` (x_local: (T/n, D)), experts sharded the same way
    (params.w1 etc: (E/n, ...), w_gate replicated).

    all_to_all #1 routes each device's per-expert capacity buffers to the
    expert's owner; the FFN runs locally; all_to_all #2 routes results
    back. Shapes stay static (capacity truncation), so XLA overlaps the
    collectives with compute on the ICI torus.
    """
    n = lax.psum(1, axis_name)
    Tl, D = x_local.shape
    E_local = params.w1.shape[0]
    E = n * E_local
    cap = int(max(1, capacity_factor * Tl / E))

    idx, gate = top1_gate(x_local, params.w_gate)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
    pos_in_e = jnp.max(jnp.cumsum(onehot, axis=0) * onehot - 1, axis=1)
    keep = pos_in_e < cap
    slot = jnp.clip(pos_in_e, 0, cap - 1)

    # local capacity buffers for EVERY global expert: (E, cap, D)
    buf = jnp.zeros((E, cap, D), x_local.dtype)
    buf = buf.at[idx, slot].add(jnp.where(keep[:, None], x_local, 0))

    # (E, cap, D) -> (n, E_local, cap, D): split by owner, trade buffers so
    # each device holds its experts' tokens from all devices
    buf = buf.reshape(n, E_local, cap, D)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)   # (n=source device, E_local, cap, D)
    recv = buf.transpose(1, 0, 2, 3).reshape(E_local, n * cap, D)

    out = _expert_ffn(params.w1, params.b1, params.w2, params.b2, recv)

    # route results back to the owning devices
    out = out.reshape(E_local, n, cap, D).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                     # (n, E_local, cap, D)
    out = out.reshape(E, cap, D)

    y = out[idx, slot]
    return jnp.where(keep[:, None], gate[:, None] * y, 0.0)
