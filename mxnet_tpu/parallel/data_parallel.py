"""SPMD data-parallel training.

This is the TPU-native replacement for the reference's whole data-parallel
stack (SURVEY.md §2.3 row 1-2): DataParallelExecutorGroup batch slicing
(executor_group.py:281-310) + KVStore gradient reduction + per-device
optimizer updates collapse into ONE jitted XLA computation over a device
mesh: the batch arrives sharded on the 'dp' axis, XLA inserts the gradient
AllReduce over ICI (latency-hidden behind the backward pass — the reference's
priority-queue overlap, for free), and the optimizer update runs sharded.

The gluon net is captured through the same Symbol trace hybridize() uses;
parameters live as a pytree; after training, ``sync_to_net()`` writes back.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError, logger
from ..executor import _GraphLowering
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap, _wrap
from ..observability import attribution as _attribution
from ..observability import catalog as _telemetry
from ..observability import flight_recorder as _flight
from ..observability import memwatch as _memwatch
from ..observability import metrics as _metrics
from ..observability import xcost as _xcost
from ..passes import manager as _passes
from ..resilience import recovery as _recovery
from .mesh import local_mesh

__all__ = ["DataParallelTrainer", "make_train_step", "sgd_momentum_init",
           "sgd_momentum_update"]


# ---- minimal fused optimizer rules usable inside the jitted step ----------
def sgd_momentum_init(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_momentum_update(params, grads, state, lr, momentum=0.9, wd=0.0):
    def upd(w, g, m):
        g = g + wd * w
        m_new = momentum * m - lr * g
        return w + m_new, m_new

    flat = jax.tree_util.tree_map(upd, params, grads, state)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                        is_leaf=lambda t: isinstance(t, tuple))
    new_state = jax.tree_util.tree_map(lambda t: t[1], flat,
                                       is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_state


def _shape_key(arrays):
    """Exact (shape, dtype) signature of a batch — the unit the AOT
    executable is keyed to, shared by _aot_key/aot_save/aot_load/step."""
    return [tuple(a.shape) + (str(a.dtype),) for a in arrays]


# ---- grad-anomaly guard (NaN/Inf + norm-spike skip inside the step) -------
def _guard_config(grad_guard):
    """Normalize the ``grad_guard`` ctor arg. None/False = off; True = NaN/
    Inf + spike detection with defaults; a dict overrides ``spike_factor``
    (0 disables spike detection, keeping only the NaN/Inf check),
    ``ema_decay`` and ``warmup`` (good steps before spikes can fire)."""
    if not grad_guard:
        return None
    g = dict(grad_guard) if isinstance(grad_guard, dict) else {}
    return {"spike_factor": float(g.get("spike_factor", 10.0)),
            "ema_decay": float(g.get("ema_decay", 0.99)),
            "warmup": int(g.get("warmup", 5))}


def _guard_init_state():
    return {"ema": jnp.zeros((), jnp.float32),
            "last_norm": jnp.zeros((), jnp.float32),
            "skips": jnp.zeros((), jnp.int32),
            "good": jnp.zeros((), jnp.int32),
            "steps": jnp.zeros((), jnp.int32),
            "last_skipped": jnp.zeros((), jnp.int32)}


def _guard_apply(cfg, gstate, gnorm, new_tree, old_tree):
    """Inside the jitted step: keep ``new_tree`` on a healthy step, fall
    back to ``old_tree`` (skip-step) when the gradient norm is NaN/Inf or
    spikes past ``spike_factor``× its EMA. Returns (tree, new_gstate, bad);
    extra keys riding in ``gstate`` (loss-scaler state, lr_scale) pass
    through untouched."""
    gnorm = gnorm.astype(jnp.float32)
    finite = jnp.isfinite(gnorm)
    if cfg["spike_factor"] > 0:
        warm = gstate["good"] >= cfg["warmup"]
        spike = jnp.logical_and(
            warm, gnorm > cfg["spike_factor"] * gstate["ema"])
    else:
        spike = jnp.zeros((), jnp.bool_)
    bad = jnp.logical_or(jnp.logical_not(finite), spike)
    tree = jax.tree_util.tree_map(
        lambda o, n: jnp.where(bad, o, n), old_tree, new_tree)
    d = cfg["ema_decay"]
    safe_norm = jnp.where(finite, gnorm, gstate["ema"])
    ema = jnp.where(
        bad, gstate["ema"],
        jnp.where(gstate["good"] == 0, safe_norm,
                  d * gstate["ema"] + (1.0 - d) * safe_norm))
    badi = bad.astype(jnp.int32)
    new_gstate = dict(gstate)
    new_gstate.update({"ema": ema, "last_norm": gnorm,
                       "skips": gstate["skips"] + badi,
                       "good": gstate["good"] + (1 - badi),
                       "steps": gstate["steps"] + 1,
                       "last_skipped": badi})
    return tree, new_gstate, bad


def _scaled_loss_run(raw_fn, rng, scale):
    """Innermost loss closure shared by both capture paths: mean f32 loss,
    multiplied by the live scale when one is threaded. The UNSCALED loss
    rides in the aux slot so the host always observes the true value."""
    def run(ins_):
        outs, aux_updates = raw_fn(ins_, rng)
        loss_ = jnp.mean(outs[0].astype(jnp.float32))
        if scale is None:
            return loss_, aux_updates
        return loss_ * scale, (aux_updates, loss_)
    return run


def _unscale_grads(grads, loss, aux_updates, scale, cast_f32):
    """Post-backward epilogue shared by both capture paths: recover the
    unscaled loss smuggled through aux and divide the f32 gradients by the
    scale (exact — the scale stays a power of two)."""
    if scale is not None:
        aux_updates, loss = aux_updates
        grads = {k: g.astype(jnp.float32) / scale for k, g in grads.items()}
    elif cast_f32:
        grads = {k: g.astype(jnp.float32) for k, g in grads.items()}
    return grads, loss, aux_updates


def _guard_scaler_apply(guard_cfg, scaler_cfg, gstate, grads,
                        new_tree, old_tree):
    """Guard + scaler epilogue shared by the fused step and the kv
    apply_step: skip-step on an anomalous gradient norm, then advance the
    in-trace scaler off the same norm (overflow = non-finite)."""
    import optax
    gnorm = optax.global_norm(grads)
    tree, gstate, bad = _guard_apply(guard_cfg, gstate, gnorm,
                                     new_tree, old_tree)
    if scaler_cfg is not None:
        overflow = jnp.logical_not(jnp.isfinite(gnorm))
        gstate = dict(gstate)
        gstate.update(_recovery.scaler_apply(
            scaler_cfg, gstate, overflow, bad))
    return tree, gstate


def _make_optax(optimizer: str, optimizer_params: Dict):
    import optax
    p = dict(optimizer_params or {})
    lr = p.pop("learning_rate", 0.01)
    wd = p.pop("wd", 0.0)
    name = optimizer.lower() if isinstance(optimizer, str) else optimizer
    if name == "sgd":
        mom = p.pop("momentum", 0.0)
        tx = optax.sgd(lr, momentum=mom if mom else None)
    elif name == "nag":
        tx = optax.sgd(lr, momentum=p.pop("momentum", 0.9), nesterov=True)
    elif name == "adam":
        tx = optax.adam(lr, b1=p.pop("beta1", 0.9), b2=p.pop("beta2", 0.999),
                        eps=p.pop("epsilon", 1e-8))
    elif name == "rmsprop":
        tx = optax.rmsprop(lr, decay=p.pop("gamma1", 0.9),
                           eps=p.pop("epsilon", 1e-8))
    elif name == "adagrad":
        tx = optax.adagrad(lr)
    else:
        raise MXNetError(f"fused path does not know optimizer {optimizer!r}; "
                         f"use gluon.Trainer for the full registry")
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


class DataParallelTrainer:
    """Jitted whole-step data-parallel trainer for a Gluon net.

    Usage::

        mesh = parallel.auto_mesh()            # all devices on 'dp'
        step = parallel.DataParallelTrainer(net, loss_fn, 'sgd',
                                            {'learning_rate': 0.1}, mesh=mesh)
        loss = step.step(x, y)                 # x, y: global batch
        step.sync_to_net()                     # write back into net params
    """

    def __init__(self, net, loss, optimizer="sgd", optimizer_params=None,
                 mesh: Optional[Mesh] = None, data_axis: str = "dp",
                 compute_dtype=None, donate: bool = True, kvstore=None,
                 remat=None, grad_guard=None, loss_scaling=None,
                 dynamic_lr_scale: bool = False, step_attribution=None,
                 passes=None, grad_reduce: str = "all_reduce",
                 grad_reduce_dtype=None, bucket_bytes: Optional[int] = None,
                 compression=None):
        self._net = net
        self._loss_block = loss
        # graph-pass pipeline run over the captured symbol graph BEFORE
        # lowering (mxnet_tpu.passes): the measured perf levers — NHWC
        # layout propagation, space-to-depth stem, constant folding,
        # fusion-friendly reordering — as automatic defaults.  None =
        # MXNET_PASSES-configured default pipeline; False = off (the
        # captured graph is bitwise what it was before this framework
        # existed); a PassManager / spec string = custom.  Re-homed
        # parameter layouts are handled transparently: the trainer applies
        # the recorded value transforms at capture and inverts them in
        # sync_to_net, so the gluon net keeps its original layout.
        self._passes = _passes.resolve(passes)
        self._pass_result = None
        self._pass_info: Dict[str, Any] = {}
        if mesh is None and kvstore is not None:
            # hybrid mode: the jitted step spans only THIS process's devices
            # (the kvstore is the cross-process channel), so the mesh must
            # be local — a global mesh would make XLA itself the channel
            mesh = local_mesh(data_axis, devices=jax.local_devices())
        self._mesh = mesh or local_mesh(data_axis)
        self._axis = data_axis
        self._compute_dtype = (jnp.dtype(compute_dtype)
                               if compute_dtype is not None else None)
        # rematerialization of the forward during backward — the lever
        # that lets batch 512 fit without XLA spilling (reference
        # MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:232). None = keep
        # all activations; "full" = recompute everything (max memory
        # savings, ~1.3x FLOPs); "dots" = keep matmul outputs only; or
        # pass any jax.checkpoint_policies callable.
        if remat in (None, "none"):
            self._remat_policy = False
        elif remat == "full":
            self._remat_policy = None
        elif remat == "dots":
            self._remat_policy = \
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif callable(remat):
            self._remat_policy = remat
        else:
            raise MXNetError(f"unknown remat mode {remat!r}")
        self._remat = remat not in (None, "none")
        self._remat_mode = remat
        # ---- communication-optimization levers (scale-out path) ----------
        # grad_reduce: how the cross-chip gradient reduction runs.
        #   "all_reduce"      (default) XLA's implicit AllReduce; params and
        #                     optimizer state replicated on every chip.
        #   "reduce_scatter"  ZeRO-1 sharded optimizer: gradients are
        #                     reduce-scattered over the data axis, the
        #                     optimizer update runs on each chip's 1/N
        #                     parameter shard (optimizer state LIVES sharded
        #                     — per-chip opt-state HBM shrinks N x), and the
        #                     updated params all-gather back to replication.
        #                     Parameters/state leaves whose leading dim does
        #                     not tile the mesh stay replicated (all-reduce).
        self._grad_reduce = str(grad_reduce or "all_reduce")
        if self._grad_reduce not in ("all_reduce", "reduce_scatter"):
            raise MXNetError(
                f"unknown grad_reduce mode {grad_reduce!r} "
                "(want 'all_reduce' or 'reduce_scatter')")
        # grad_reduce_dtype: the dtype gradients travel in through the
        # reduction (bf16 halves the collective bytes); the unsharded
        # master math stays f32 — grads are cast back before the optimizer
        # consumes them (accumulate-in-f32 semantics, tolerance-tested).
        self._grad_reduce_dtype = None
        if grad_reduce_dtype not in (None, "none", "float32", "f32"):
            alias = {"bf16": "bfloat16", "fp16": "float16"}
            dt = jnp.dtype(alias.get(str(grad_reduce_dtype),
                                     grad_reduce_dtype))
            if not jnp.issubdtype(dt, jnp.floating) or \
                    dt == jnp.dtype(jnp.float64):
                raise MXNetError(
                    f"grad_reduce_dtype must be a sub-f32 float "
                    f"(bfloat16/float16), got {grad_reduce_dtype!r}")
            if dt != jnp.dtype(jnp.float32):
                self._grad_reduce_dtype = dt
        # bucket_bytes: fuse small gradients into flat buckets of this many
        # bytes before the reduction (one collective per bucket instead of
        # one per tensor) — the in-trace twin of collectives.
        # bucketed_allreduce, sharing its bucket_assignment rule. An
        # all-reduce-path lever: the ZeRO path already reduces per-shard.
        self._bucket_bytes = None
        if bucket_bytes not in (None, 0):
            if self._grad_reduce == "reduce_scatter":
                raise MXNetError(
                    "bucket_bytes= is an all_reduce-path lever; "
                    "grad_reduce='reduce_scatter' fuses its own per-leaf "
                    "reduce-scatters (drop one of the two)")
            if kvstore is not None:
                # the kv path pushes gradients per key and the kvstore does
                # its own aggregation; a silently-inert lever would stamp
                # false provenance into comm_config()/tuner rows
                raise MXNetError(
                    "bucket_bytes= applies to the fused in-XLA gradient "
                    "reduction; the kvstore path aggregates with "
                    "MXNET_UPDATE_AGGREGATION_SIZE instead (drop one of "
                    "the two)")
            self._bucket_bytes = int(bucket_bytes)
            if self._bucket_bytes <= 0:
                raise MXNetError(f"bucket_bytes must be positive, got "
                                 f"{bucket_bytes!r}")
        # compression: 2-bit error-feedback gradient compression on the
        # kvstore wire (GradientCompression; reference
        # gradient_compression.cc). A WIRE lever: the compiled programs are
        # untouched, so it deliberately stays out of the AOT key.
        self._compression_params = None
        if compression:
            if kvstore is None:
                raise MXNetError(
                    "compression= rides the kvstore gradient wire; pass "
                    "kvstore= (the fused in-XLA collectives have no "
                    "host-codec hook) or drop compression")
            from ..gradient_compression import GradientCompression
            if isinstance(compression, GradientCompression):
                params = {"type": compression.type,
                          "threshold": compression.threshold}
            else:
                params = dict(compression)
            kvstore.set_gradient_compression(params)
            self._compression_params = params
        # per-leaf ZeRO sharding decisions, derived at capture time
        self._zero_shard: Dict[str, bool] = {}
        self._opt_specs = None
        # recorded for the AOT key: lr/momentum/wd are baked into the
        # compiled executable as constants, so a blob from different
        # hyperparameters must never be silently reused
        self._opt_desc = (str(optimizer),
                          tuple(sorted((str(k), repr(v)) for k, v in
                                       (optimizer_params or {}).items())))
        self._tx = _make_optax(optimizer, optimizer_params)
        # grad-anomaly guard: when enabled, the jitted step computes the
        # global grad norm, skips the update on NaN/Inf or spike steps
        # (params/aux/opt_state pass through unchanged) and counts skips in
        # a small state tree that rides along the step like opt_state. The
        # counters surface through anomaly_stats() / Monitor.install_trainer.
        self._guard_cfg = _guard_config(grad_guard)
        # in-trace dynamic loss scaling (ISSUE 5 tentpole): LossScaler
        # semantics as functional device-scalar state riding in the guard
        # state tree — the loss is multiplied by the live scale before the
        # backward and the f32 grads unscaled after (exact: scale stays a
        # power of two), overflow halves the scale and skips the update,
        # growth_interval clean steps double it. Everything happens INSIDE
        # the jitted step: zero per-step host syncs (contrast
        # contrib.amp.init_trainer's imperative bool(overflow) read).
        self._scaler_cfg = _recovery.scaler_config(loss_scaling)
        if self._scaler_cfg is not None and self._guard_cfg is None:
            # the scaler's overflow response IS the guard's skip-step; a
            # scaler without a guard would rescale but never skip. Any
            # explicit off spelling (False/0/{}) is rejected — only the
            # unset default (None) silently upgrades to guard-on
            if grad_guard is not None:
                raise MXNetError(
                    "loss_scaling= requires the grad-anomaly guard; drop "
                    "grad_guard=%r or disable loss scaling" % (grad_guard,))
            self._guard_cfg = _guard_config(True)
        # a device-scalar multiplier on the optimizer update (recovery
        # ladder's LR backoff lever — lr itself is baked into the compiled
        # executable). Off by default so the step HLO is untouched.
        self._dynamic_lr = bool(dynamic_lr_scale)
        self._guard_state = None
        self._step_fn = None
        self._n_inputs = None
        self._param_names = None
        self._params = None
        self._aux = None
        self._opt_state = None
        self._rng_counter = 0
        self._donate = donate
        # hybrid multi-host mode (reference dist_sync_device: fast intra-node
        # reduce + PS inter-node): the fused step computes LOCAL grads over
        # this process's mesh, the kvstore moves them across processes
        # (optionally 2-bit-compressed on the wire), a second jitted program
        # applies the optimizer. kvstore=None keeps the fully-fused
        # single-program path where XLA's allreduce spans the whole mesh.
        self._kv = kvstore
        self._kv_inited = False
        self._grad_fn = None
        self._apply_fn = None
        self._compiled = None   # AOT-deserialized executable (aot_load)
        self._compiled_shapes = None  # exact input shapes the AOT exe accepts
        # step-time attribution (ISSUE 6): host-side decomposition of the
        # step cadence into dispatch/transfer/feed-stall/... buckets plus
        # live MFU/device-util gauges. Pure bookkeeping around the step —
        # the jitted program and its HLO are untouched (tier-1 guards it).
        self._attr_cfg = _attribution.attribution_config(step_attribution)
        _dev0 = self._mesh.devices.ravel()[0]
        self._perf = (_attribution.StepAttribution(
            self._attr_cfg, device_kind=_dev0.device_kind,
            n_devices=int(self._mesh.devices.size))
            if self._attr_cfg is not None else None)
        # per-executable XLA cost capture (observability.xcost): FLOPs /
        # bytes / roofline row persisted once per compiled step when the
        # ledger is enabled (MXNET_PERF_LEDGER); also the flops source for
        # the live MFU gauge
        self._flops_per_step = None
        self._cost_rows: Dict[Tuple, Any] = {}

    # ------------------------------------------------------------- passes
    def _run_passes(self, loss_sym, data_syms, init_arrays):
        """Run the configured graph-pass pipeline over the captured loss
        graph (mxnet_tpu.passes).  Input shapes come from the init-view
        sample batch (the NET's layout); parameter shapes from the
        materialized gluon params.  A pipeline failure never kills a
        capture — the unrewritten graph is used and a warning logged."""
        from ..passes.layout import is_nchw_conv
        self._pass_result = None
        data_names = [s.name for s in data_syms] + ["__label"]
        nchw_convs = sum(1 for n in loss_sym.topo_nodes()
                         if not n.is_var and is_nchw_conv(n))
        self._pass_info = {
            "nchw_convs": nchw_convs,
            "layout_enabled": (self._passes is not None
                               and "layout" in self._passes.names)}
        if self._passes is None:
            return loss_sym
        shapes = {}
        pnames = set()
        for p in self._net.collect_params().values():
            pnames.add(p.name)
            if p.shape and all(int(d) > 0 for d in p.shape):
                shapes[p.name] = tuple(int(d) for d in p.shape)
        if init_arrays is not None:
            for name, a in zip(data_names, init_arrays):
                if hasattr(a, "shape"):
                    shapes[name] = tuple(int(d) for d in a.shape)
        try:
            res = self._passes.run(loss_sym, shapes=shapes,
                                   input_vars=data_names,
                                   param_names=pnames)
        except Exception as e:
            logger.warning("graph-pass pipeline failed; capturing the "
                           "unrewritten graph: %r", e)
            return loss_sym
        self._pass_info["rewrites"] = dict(res.counts)
        if res.total_rewrites == 0:
            return loss_sym
        self._pass_result = res
        return res.symbol

    def _placed_param(self, name, value):
        """A net parameter's value as the REWRITTEN graph expects it: the
        pass pipeline may have re-homed the variable (NHWC weight, s2d
        stem), in which case the recorded transform maps the net's value
        into the captured layout (sync_to_net applies the inverse)."""
        if self._pass_result is None or \
                name not in self._pass_result.var_transforms:
            return value
        return jnp.asarray(
            self._pass_result.transform_var(name, jax.device_get(value)))

    def passes_provenance(self) -> Dict[str, Any]:
        """Which graph passes this trainer runs and what they rewrote —
        stamped into bench rows so perf baselines are attributable (one
        schema with Module: passes.manager.provenance)."""
        return _passes.provenance(self._passes, self._pass_result,
                                  self._pass_info.get("rewrites"))

    # ------------------------------------------------------------- capture
    def _capture(self, n_inputs: int, sample_arrays=None):
        from .. import symbol as sym_mod
        from .. import autograd
        if _metrics.enabled():
            _telemetry.CAPTURES_TOTAL.inc()
            # the live device-set gauge elastic resumes reconcile against
            _telemetry.ACTIVE_DEVICES.set(int(self._mesh.devices.size))
        # a re-capture rebuilds params/opt_state from the net; any loaded
        # executable is keyed to the OLD pytree/placement and must not be
        # re-entered afterwards — and any captured cost rows describe the
        # old executable
        self._compiled = None
        self._compiled_shapes = None
        self._cost_rows = {}
        self._flops_per_step = None
        init_arrays = sample_arrays
        if sample_arrays is not None:
            # materialize deferred-init params with one tiny host forward;
            # the sample batch may arrive pre-sharded over the mesh (e.g.
            # from DeviceFeedIter) — uncommit it to host first so the
            # imperative forward isn't pinned to mismatched devices.
            # Under a passes pipeline with input_layout="NHWC" the caller
            # feeds channel-last batches to an NCHW-built net: init_view
            # permutes rank-4 arrays back for the init forward only.
            if self._passes is not None:
                init_arrays = self._passes.init_view(sample_arrays)
            with autograd.pause():
                self._net(*[_wrap(jnp.asarray(jax.device_get(a)))
                            for a in init_arrays[:-1]])
        data_syms = [sym_mod.Variable(f"__data{i}") for i in range(n_inputs - 1)]
        label_sym = sym_mod.Variable("__label")
        out = self._net(*data_syms)
        if isinstance(out, (list, tuple)):
            out = out[0]
        loss_sym = self._loss_block(out, label_sym)
        loss_sym = self._run_passes(loss_sym, data_syms, init_arrays)
        lowering = _GraphLowering(loss_sym)
        var_names = [n.name for n in loss_sym.topo_nodes() if n.is_var]
        data_names = [s.name for s in data_syms] + ["__label"]
        pmap = {p.name: p for p in self._net.collect_params().values()
                if p.name in var_names}
        param_names = [n for n in var_names
                       if n in pmap and pmap[n].grad_req != "null"]
        aux_names = [n for n in var_names if n in pmap
                     and pmap[n].grad_req == "null"]
        self._param_names = param_names
        self._aux_names = aux_names
        self._pmap = pmap
        self._params = {n: self._placed_param(n, _unwrap(pmap[n].data()))
                        for n in param_names}
        self._aux = {n: self._placed_param(n, _unwrap(pmap[n].data()))
                     for n in aux_names}
        self._opt_state = self._tx.init(self._params)
        self._guard_state = _guard_init_state()
        if self._scaler_cfg is not None:
            self._guard_state.update(
                _recovery.scaler_init_state(self._scaler_cfg))
        if self._dynamic_lr:
            self._guard_state["lr_scale"] = jnp.ones((), jnp.float32)
        raw_fn = lowering.lower(is_train=True)

        mesh, axis = self._mesh, self._axis
        repl = NamedSharding(mesh, P())
        dataspec = NamedSharding(mesh, P(axis))
        cdtype = self._compute_dtype
        tx = self._tx
        guard_cfg = self._guard_cfg
        scaler_cfg = self._scaler_cfg
        # a key (str) rather than a bool flag: closure-captured Python
        # scalars are exactly what mxlint MXL-T202 flags in our own step
        lr_key = "lr_scale" if self._dynamic_lr else None

        # ---- comm-optimization epilogue (grad_reduce / dtype / buckets) --
        # ZeRO-1 shardability: a leaf shards over the data axis when its
        # leading dim tiles the mesh; everything else stays replicated.
        # Optimizer-state leaves mirror their param's shape (sgd momentum,
        # adam mu/nu), so the same shape rule lands the same verdict on a
        # param and its state; scalar counts stay replicated. The divisor
        # is the DATA axis extent — on a multi-axis mesh only 'dp' shards.
        n_dev = int(mesh.shape[axis])
        shard1 = NamedSharding(mesh, P(axis))
        g_mode = self._grad_reduce

        def _zero_ok(v):
            shp = tuple(getattr(v, "shape", ()))
            return (g_mode == "reduce_scatter" and len(shp) >= 1
                    and int(shp[0]) > 0 and int(shp[0]) % n_dev == 0)

        self._zero_shard = {n: _zero_ok(v) for n, v in self._params.items()}
        self._opt_specs = jax.tree_util.tree_map(
            lambda l: shard1 if _zero_ok(l) else repl, self._opt_state)
        if g_mode == "reduce_scatter":
            # the optimizer state LIVES sharded between steps — per-chip
            # opt-state HBM is 1/N of the replicated baseline from step 0
            self._opt_state = jax.tree_util.tree_map(
                jax.device_put, self._opt_state, self._opt_specs)
        zshard = dict(self._zero_shard)
        rdt = self._grad_reduce_dtype
        bucket_names = None
        if self._bucket_bytes:
            from .collectives import bucket_assignment
            itemsize = (jnp.dtype(rdt).itemsize if rdt is not None else 4)
            sizes = [int(np.prod(self._params[n].shape)) * itemsize
                     for n in param_names]
            bucket_names = [[param_names[i] for i in b] for b in
                            bucket_assignment(sizes, self._bucket_bytes)]

        def _shard_tree(t, sp):
            return {k: (jax.lax.with_sharding_constraint(v, sp)
                        if zshard[k] else v) for k, v in t.items()}

        def _reduce_grads(grads):
            """Comm epilogue on the freshly-unscaled f32 grads: cast to the
            wire dtype, fuse buckets (one collective per flat bucket —
            collectives.bucket_assignment order), anchor the ZeRO
            reduce-scatter, cast back to f32 (accumulate-in-f32: the
            master math downstream never sees the wire dtype)."""
            if rdt is not None:
                grads = {k: v.astype(rdt) for k, v in grads.items()}
            if bucket_names is not None:
                out = dict(grads)
                for names_ in bucket_names:
                    flat = jnp.concatenate([grads[n].ravel()
                                            for n in names_]) \
                        if len(names_) > 1 else grads[names_[0]].ravel()
                    flat = jax.lax.with_sharding_constraint(flat, repl)
                    off = 0
                    for n in names_:
                        sz = grads[n].size
                        out[n] = flat[off:off + sz].reshape(grads[n].shape)
                        off += sz
                grads = out
            if g_mode == "reduce_scatter":
                # the constraint sits on the WIRE-dtype value so XLA's
                # implicit psum lowers to a reduce-scatter of those bytes
                grads = _shard_tree(grads, shard1)
            if rdt is not None:
                grads = {k: v.astype(jnp.float32) for k, v in grads.items()}
            return grads

        def _opt_apply(grads, opt_state, params, gstate):
            """Optimizer update bracketed by the ZeRO shard/gather: the
            update runs on each chip's 1/N shard of grads/params/state and
            the fresh params all-gather back to replication. Shared by the
            fused step and the kv apply_step so the two paths cannot
            drift."""
            import optax
            if g_mode == "reduce_scatter":
                grads = _shard_tree(grads, shard1)
                params = _shard_tree(params, shard1)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            if lr_key is not None:
                lrs = gstate[lr_key]
                updates = jax.tree_util.tree_map(lambda u: u * lrs, updates)
            new_params = optax.apply_updates(params, updates)
            if g_mode == "reduce_scatter":
                new_params = _shard_tree(new_params, repl)
            return new_params, new_opt_state

        def train_step(params, aux, opt_state, gstate, rng, *data):
            inputs = {}
            if cdtype is not None:
                inputs.update({k: v.astype(cdtype) for k, v in params.items()})
            else:
                inputs.update(params)
            inputs.update(aux)
            for name, x in zip(data_names, data):
                inputs[name] = x.astype(cdtype) if (
                    cdtype is not None and jnp.issubdtype(x.dtype, jnp.floating)
                    and name != "__label") else x

            # live loss scale (a traced scalar from the state tree): the
            # loss is scaled BEFORE the backward so tiny low-precision
            # grads stay representable, and the f32 grads are unscaled
            # after. Scale transitions are powers of two, so in f32 the
            # round trip is bitwise-exact.
            scale = gstate["loss_scale"] if scaler_cfg is not None else None

            def loss_of(p):
                ins = dict(inputs)
                if cdtype is not None:
                    ins.update({k: v.astype(cdtype) for k, v in p.items()})
                else:
                    ins.update(p)
                run = _scaled_loss_run(raw_fn, rng, scale)
                if self._remat:
                    run = jax.checkpoint(run, policy=self._remat_policy)
                return run(ins)

            (loss, aux_updates), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            grads, loss, aux_updates = _unscale_grads(
                grads, loss, aux_updates, scale, cdtype is not None)
            grads = _reduce_grads(grads)
            new_params, new_opt_state = _opt_apply(grads, opt_state,
                                                   params, gstate)
            new_aux = dict(aux)
            for k, v in aux_updates.items():
                if k in new_aux:
                    new_aux[k] = v.astype(new_aux[k].dtype)
            if guard_cfg is not None:
                # skip-step: an anomalous gradient keeps params, aux AND
                # opt_state at their pre-step values (a NaN forward would
                # poison batchnorm running stats too)
                (new_params, new_aux, new_opt_state), gstate = \
                    _guard_scaler_apply(guard_cfg, scaler_cfg, gstate, grads,
                                        (new_params, new_aux, new_opt_state),
                                        (params, aux, opt_state))
            return new_params, new_aux, new_opt_state, gstate, loss

        gstate_spec = {k: repl for k in self._guard_state}
        in_shardings = (jax.tree_util.tree_map(lambda _: repl, self._params),
                        {k: repl for k in self._aux},
                        self._opt_specs,
                        gstate_spec,
                        repl) + tuple(dataspec for _ in data_names)
        out_shardings = (jax.tree_util.tree_map(lambda _: repl, self._params),
                         {k: repl for k in self._aux},
                         self._opt_specs,
                         gstate_spec,
                         repl)
        donate = (0, 1, 2, 3) if self._donate else ()
        self._step_fn = jax.jit(train_step, in_shardings=in_shardings,
                                out_shardings=out_shardings,
                                donate_argnums=donate)
        self._n_inputs = n_inputs

        if self._kv is not None:
            # with a scaler, grad_step takes the live scale as an extra
            # scalar arg: the backward runs on the SCALED loss, and the
            # grads are unscaled to f32 before they touch the wire, so the
            # kvstore sums plain gradients and every worker (whose state is
            # identical) applies the same scale transition in apply_step.
            def grad_step(params, aux, rng, *data, scale=None):
                inputs = dict(aux)
                for name, x in zip(data_names, data):
                    inputs[name] = x.astype(cdtype) if (
                        cdtype is not None
                        and jnp.issubdtype(x.dtype, jnp.floating)
                        and name != "__label") else x

                def loss_of(p):
                    ins = dict(inputs)
                    if cdtype is not None:
                        ins.update({k: v.astype(cdtype)
                                    for k, v in p.items()})
                    else:
                        ins.update(p)
                    run = _scaled_loss_run(raw_fn, rng, scale)
                    if self._remat:
                        run = jax.checkpoint(run, policy=self._remat_policy)
                    return run(ins)

                (loss, aux_updates), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params)
                # kv grads always go to f32 before they touch the wire
                grads, loss, aux_updates = _unscale_grads(
                    grads, loss, aux_updates, scale, True)
                new_aux = dict(aux)
                for k, v in aux_updates.items():
                    if k in new_aux:
                        new_aux[k] = v.astype(new_aux[k].dtype)
                return grads, new_aux, loss

            if scaler_cfg is not None:
                def scaled_grad_step(params, aux, scale, rng, *data):
                    return grad_step(params, aux, rng, *data, scale=scale)

            def apply_step(params, opt_state, gstate, grads):
                new_params, new_opt_state = _opt_apply(grads, opt_state,
                                                       params, gstate)
                if guard_cfg is not None:
                    # guard the synced (cross-worker summed) gradient: a NaN
                    # from ANY worker poisons the sum, so the skip decision
                    # is naturally global. aux was already updated by
                    # grad_step — on the hybrid path only params/opt_state
                    # are protected.
                    (new_params, new_opt_state), gstate = \
                        _guard_scaler_apply(guard_cfg, scaler_cfg, gstate,
                                            grads,
                                            (new_params, new_opt_state),
                                            (params, opt_state))
                return new_params, new_opt_state, gstate

            gspec = jax.tree_util.tree_map(lambda _: repl, self._params)
            # one jit call for both variants: the scaled wrapper only adds
            # a replicated scale scalar ahead of rng
            scaled = scaler_cfg is not None
            self._grad_fn = jax.jit(
                scaled_grad_step if scaled else grad_step,
                in_shardings=(gspec, {k: repl for k in self._aux})
                + ((repl,) if scaled else ()) + (repl,)
                + tuple(dataspec for _ in data_names),
                out_shardings=(gspec, {k: repl for k in self._aux},
                               repl))
            self._apply_fn = jax.jit(
                apply_step,
                in_shardings=(gspec, self._opt_specs, gstate_spec, gspec),
                out_shardings=(gspec, self._opt_specs, gstate_spec),
                donate_argnums=(0, 1, 2) if self._donate else ())

    # ---------------------------------------------------- AOT serialization
    # The compiled fused step can be serialized and reloaded by a LATER
    # process, skipping XLA compilation entirely (the reference's analogue
    # is the cuDNN algo registry persisting autotune results; here we keep
    # the whole executable). Critical on remote-compile backends where the
    # ResNet-50 step takes minutes to compile.
    def _aot_key(self, arrays):
        import jax as _jax
        dev = self._mesh.devices.ravel()[0]
        return {
            "jax": _jax.__version__,
            "device_kind": dev.device_kind,
            "n_devices": int(self._mesh.devices.size),
            "in_shapes": _shape_key(arrays),
            "compute_dtype": str(self._compute_dtype),
            "remat": str(getattr(self, "_remat_mode", None)),
            "optimizer": self._opt_desc,
            # guard thresholds are baked constants in the executable: a blob
            # compiled with different anomaly policy must not be reused
            "grad_guard": repr(sorted(self._guard_cfg.items())
                               if self._guard_cfg else None),
            # ditto for the scaler policy constants and the lr_scale state
            # key — both change the compiled program
            "loss_scaling": repr(sorted(self._scaler_cfg.items())
                                 if self._scaler_cfg else None),
            "dynamic_lr_scale": self._dynamic_lr,
            # the pass pipeline rewrites the captured graph (and may
            # re-home the parameter pytree): a blob compiled under a
            # different pipeline must not be reused (the StableHLO digest
            # is the strong check; this is the cheap first filter)
            "passes": repr((self._passes.names, self._passes.input_layout)
                           if self._passes is not None else None),
            # the comm levers change the compiled programs (collective
            # pattern, wire dtype, bucket fusion) AND the opt-state
            # placement the executable expects; kvstore wire compression
            # deliberately absent — it never enters the executable
            "grad_reduce": self._grad_reduce,
            "grad_reduce_dtype": str(self._grad_reduce_dtype),
            "bucket_bytes": self._bucket_bytes,
        }

    def _lowered_digest(self, lowered) -> str:
        """Hash of the FULL lowered computation (StableHLO text): the model
        graph, loss, optimizer constants — everything baked into the
        executable. This is what actually guarantees a blob matches; the
        config fields in the key are a cheap first filter."""
        import hashlib
        return hashlib.sha256(
            lowered.as_text().encode("utf-8", "replace")).hexdigest()

    def aot_save(self, path, *data) -> None:
        """Compile the fused step for this batch spec and serialize the
        executable (+ a compatibility key) to ``path``."""
        import os
        import pickle
        from jax.experimental.serialize_executable import serialize
        arrays = [_unwrap(d) if isinstance(d, NDArray) else jnp.asarray(d)
                  for d in data]
        if self._step_fn is None or self._n_inputs != len(arrays):
            self._capture(len(arrays), sample_arrays=arrays)
        dataspec = NamedSharding(self._mesh, P(self._axis))
        arrays = [jax.device_put(a, dataspec) for a in arrays]
        rng = jax.random.PRNGKey(0)
        lowered = self._step_fn.lower(
            self._params, self._aux, self._opt_state, self._guard_state,
            rng, *arrays)
        digest = self._lowered_digest(lowered)
        compiled = lowered.compile()
        if _metrics.enabled() and _xcost.enabled():
            # aot_save IS the compile: capture the ledger row here with the
            # compiled executable attached (adds XLA's memory analysis)
            dev = self._mesh.devices.ravel()[0]
            row = _xcost.capture(
                lowered, key=self._aot_key(arrays), fingerprint=digest,
                label="DataParallelTrainer.aot_save",
                device_kind=dev.device_kind, platform=dev.platform,
                n_devices=int(self._mesh.devices.size), compiled=compiled)
            if row is not None:
                self._cost_rows[tuple(_shape_key(arrays))] = row
                if row.get("flops"):
                    self._flops_per_step = float(row["flops"])
        ser, in_tree, out_tree = serialize(compiled)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "wb") as f:
            pickle.dump({"key": self._aot_key(arrays), "digest": digest,
                         "exe": ser, "in_tree": in_tree,
                         "out_tree": out_tree}, f)
        os.replace(tmp, path)
        self._compiled = compiled
        self._compiled_shapes = _shape_key(arrays)
        self._place_state()

    def aot_load(self, path, *data) -> bool:
        """Load a serialized step executable; returns False (and stays on
        the jit path) if the blob is missing or its key does not match.

        Trust boundary: the blob is unpickled BEFORE the digest check, so
        ``path`` must point at a cache this process itself wrote (e.g.
        ``.bench_aot/`` under the repo) — never at untrusted bytes. An
        attacker who can write the cache file can already write the code
        that loads it, so the boundary is the filesystem, not the format."""
        import os
        import pickle
        from jax.experimental.serialize_executable import deserialize_and_load
        if not os.path.exists(path):
            return False
        arrays = [_unwrap(d) if isinstance(d, NDArray) else jnp.asarray(d)
                  for d in data]
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
        except Exception:
            return False
        if self._step_fn is None or self._n_inputs != len(arrays):
            self._capture(len(arrays), sample_arrays=arrays)
        if blob.get("key") != self._aot_key(arrays):
            return False
        # the executable is keyed to the exact input pytree (param names!);
        # a structural mismatch must be a clean refusal here, not a
        # confusing TypeError at the first step
        my_tree = jax.tree_util.tree_structure(
            ((self._params, self._aux, self._opt_state, self._guard_state,
              jax.random.PRNGKey(0)) + tuple(arrays), {}))
        if str(my_tree) != str(blob["in_tree"]):
            return False
        # strongest check: the blob must come from THIS lowered computation
        # (model graph + loss + baked constants), not merely one with the
        # same shapes. Lowering is local tracing — seconds, not the
        # minutes a remote compile costs.
        dataspec = NamedSharding(self._mesh, P(self._axis))
        placed = [jax.device_put(a, dataspec) for a in arrays]
        lowered = self._step_fn.lower(
            self._params, self._aux, self._opt_state, self._guard_state,
            jax.random.PRNGKey(0), *placed)
        if blob.get("digest") != self._lowered_digest(lowered):
            return False
        try:
            self._compiled = deserialize_and_load(
                blob["exe"], blob["in_tree"], blob["out_tree"])
        except Exception:
            return False
        self._compiled_shapes = _shape_key(arrays)
        self._place_state()
        return True

    def _place_state(self):
        """Pin params/aux/opt_state to their home shardings (params
        replicated; opt-state per-leaf — ZeRO leaves sharded over the data
        axis): unlike jit, a deserialized executable does not auto-reshard
        its inputs — and every restore path (checkpoint, rolling snapshot)
        funnels through here, so a ZeRO-sharded optimizer lands back
        sharded bitwise."""
        repl = NamedSharding(self._mesh, P())
        put = lambda t: jax.device_put(t, repl)  # noqa: E731
        self._params = jax.tree_util.tree_map(put, self._params)
        self._aux = jax.tree_util.tree_map(put, self._aux)
        if self._opt_specs is not None:
            self._opt_state = jax.tree_util.tree_map(
                jax.device_put, self._opt_state, self._opt_specs)
        else:
            self._opt_state = jax.tree_util.tree_map(put, self._opt_state)
        if self._guard_state is not None:
            self._guard_state = jax.tree_util.tree_map(put, self._guard_state)

    # ------------------------------------------------------------- stepping
    def step(self, *data) -> float:
        """One fused fwd+bwd+allreduce+update step on a global batch.
        Returns the scalar loss (an async device value; float() to sync).

        Telemetry (``observability``): step wall time, samples/sec and a
        flight-recorder record per step — all strictly host-side, OUTSIDE
        the jitted function, so the compiled HLO is identical with
        telemetry on or off, and nothing here syncs the device (the loss
        stays an async value; the recorder resolves it only at dump time).
        """
        tel = _metrics.enabled()
        perf = self._perf if tel else None
        t0 = time.perf_counter() if tel else 0.0
        arrays = [_unwrap(d) if isinstance(d, NDArray) else jnp.asarray(d)
                  for d in data]
        if self._step_fn is None or self._n_inputs != len(arrays):
            self._capture(len(arrays), sample_arrays=arrays)
        dataspec = NamedSharding(self._mesh, P(self._axis))
        tx0 = time.perf_counter() if perf is not None else 0.0
        arrays = [jax.device_put(a, dataspec) for a in arrays]
        tx1 = time.perf_counter() if perf is not None else 0.0
        from .. import random as _random
        rng = jax.random.fold_in(jax.random.PRNGKey(_random.current_seed()),
                                 self._rng_counter)
        self._rng_counter += 1
        if tel and _xcost.enabled():
            # once per executable, BEFORE dispatch (params still alive):
            # lower + cost_analysis + persist the ledger row (host-side
            # metadata only; the compiled program is untouched)
            self._maybe_capture_cost(rng, arrays)
        td0 = time.perf_counter() if perf is not None else 0.0
        try:
            if self._kv is not None:
                loss = self._kv_step(rng, arrays)
            else:
                fn = self._step_fn
                if (self._compiled is not None
                        and _shape_key(arrays) == self._compiled_shapes):
                    # the deserialized executable is shape-exact; a batch
                    # with other shapes (e.g. a ragged final batch) takes
                    # the jit path for that call only, keeping the
                    # executable for exact matches
                    fn = self._compiled
                    rng = jax.device_put(rng, NamedSharding(self._mesh, P()))
                (self._params, self._aux, self._opt_state, self._guard_state,
                 loss) = fn(self._params, self._aux, self._opt_state,
                            self._guard_state, rng, *arrays)
        except Exception as e:
            # the trainer dispatch boundary: a device RESOURCE_EXHAUSTED
            # leaves forensics (mxtpu_oom.json) and re-raises typed;
            # every other failure passes through untouched
            oom = _memwatch.to_hbm_exhausted(e, context="trainer",
                                             trainer=self)
            if oom is not None:
                raise oom from e
            raise
        if tel:
            t1 = time.perf_counter()
            dt = t1 - t0
            ms = dt * 1000.0
            samples = int(arrays[0].shape[0]) if (
                arrays and getattr(arrays[0], "ndim", 0)) else 0
            _telemetry.STEP_MS.observe(ms)
            _telemetry.STEPS_TOTAL.inc()
            if samples:
                _telemetry.SAMPLES_TOTAL.inc(samples)
                if dt > 0:
                    _telemetry.SAMPLES_PER_SEC.set(samples / dt)
            if perf is not None:
                # FLOPs are per-executable: resolve THIS signature's ledger
                # row (a second batch shape is a different program with
                # different FLOPs — MFU must never mix them)
                row = self._cost_rows.get(tuple(_shape_key(arrays)))
                self._flops_per_step = (
                    float(row["flops"]) if row and row.get("flops")
                    else None)
                # host-side decomposition + live MFU; the loss reference is
                # kept one step and polled non-blocking, never synced
                perf.observe(t0, t1, transfer_ms=(tx1 - tx0) * 1e3,
                             dispatch_ms=(t1 - td0) * 1e3, loss_ref=loss,
                             flops_per_step=self._flops_per_step)
            # rng_counter just advanced: it IS the completed-step count
            # (ResilientTrainer.step_count tracks the same number)
            _flight.record_step(self._rng_counter, loss=loss, step_ms=ms)
        return loss

    def _maybe_capture_cost(self, rng, arrays) -> None:
        """Persist this step's cost-ledger row (once per input signature).
        Lowering is local tracing — no compile, no device work — and the
        row is keyed by the same aot_key + StableHLO digest the AOT cache
        trusts. The fused path costs ``_step_fn``; the kv path costs the
        two programs it ACTUALLY runs (``_grad_fn`` + ``_apply_fn``,
        summed — the fused step never executes there and its fingerprint
        would name a nonexistent executable)."""
        key = tuple(_shape_key(arrays))
        if key in self._cost_rows:
            return
        self._cost_rows[key] = None       # one attempt per signature
        try:
            dev = self._mesh.devices.ravel()[0]
            common = dict(key=self._aot_key(arrays),
                          device_kind=dev.device_kind, platform=dev.platform,
                          n_devices=int(self._mesh.devices.size))
            mem_on = _memwatch.capture_enabled()
            if self._kv is None:
                lowered = self._step_fn.lower(
                    self._params, self._aux, self._opt_state,
                    self._guard_state, rng, *arrays)
                row = _xcost.capture(
                    lowered, fingerprint=self._lowered_digest(lowered),
                    label="DataParallelTrainer.step",
                    compile_for_memory=mem_on, **common)
            else:
                gargs = (self._params, self._aux)
                if self._scaler_cfg is not None:
                    gargs += (self._guard_state["loss_scale"],)
                glow = self._grad_fn.lower(*(gargs + (rng,) + tuple(arrays)))
                # grads share the params avals exactly — params stand in
                alow = self._apply_fn.lower(
                    self._params, self._opt_state, self._guard_state,
                    self._params)
                import hashlib
                extra = None
                if mem_on:
                    # the kv step IS two programs: memory is their sum
                    # (same contract as merge_costs — all parts or none)
                    try:
                        mems = [_xcost.memory_of(p.compile())
                                for p in (glow, alow)]
                    except Exception:
                        mems = [None]
                    if all(mems):
                        mem = {k: sum(m[k] for m in mems) for k in mems[0]}
                        extra = {"memory": mem,
                                 "peak_memory_bytes": (
                                     mem["temp_bytes"]
                                     + mem["argument_bytes"]
                                     + mem["output_bytes"])}
                row = _xcost.capture(
                    cost=_xcost.merge_costs(_xcost.cost_of(glow),
                                            _xcost.cost_of(alow)),
                    fingerprint=hashlib.sha256(
                        (self._lowered_digest(glow)
                         + self._lowered_digest(alow)).encode()).hexdigest(),
                    label="DataParallelTrainer.kv_step", extra=extra,
                    **common)
        except Exception as e:   # never let the perf layer kill a step
            logger.warning("cost-ledger capture failed: %r", e)
            return
        if row is not None:
            self._cost_rows[key] = row
            if row.get("flops"):
                self._flops_per_step = float(row["flops"])

    def _kv_step(self, rng, arrays):
        """Grad -> kvstore wire sync (summed across workers; 2-bit codec if
        active) -> jitted optimizer apply."""
        if self._scaler_cfg is not None:
            grads, self._aux, loss = self._grad_fn(
                self._params, self._aux, self._guard_state["loss_scale"],
                rng, *arrays)
        else:
            grads, self._aux, loss = self._grad_fn(
                self._params, self._aux, rng, *arrays)
        kv = self._kv
        # grad_reduce_dtype applies to the kv WIRE too: gradients travel
        # (and merge) in the reduction dtype, and come back to f32 before
        # the jitted apply — same accumulate-in-f32 contract as the fused
        # path's in-trace cast
        rdt = self._grad_reduce_dtype

        def wire(g):
            return g.astype(rdt) if rdt is not None else g

        if not self._kv_inited:
            for n in self._param_names:
                kv.init("dpt_grad_" + n, _wrap(wire(jnp.zeros_like(grads[n]))))
            self._kv_inited = True
            # the apply program spans the local mesh: params must sit
            # replicated on it, not wherever capture left them
            self._place_state()
        for i, n in enumerate(self._param_names):
            kv.push("dpt_grad_" + n, _wrap(wire(grads[n])), priority=-i)
        nworkers = max(1, getattr(kv, "num_workers", 1))
        repl = NamedSharding(self._mesh, P())
        synced = {}
        for n in self._param_names:
            out = _wrap(wire(grads[n]))
            kv.pull("dpt_grad_" + n, out=out)
            # the store round-trip (esp. the codec decode) may land the
            # gradient on a single device; re-replicate over the mesh so
            # the jitted apply sees one consistent placement
            synced[n] = jax.device_put(
                out._data.astype(jnp.float32) / nworkers, repl)
        self._params, self._opt_state, self._guard_state = self._apply_fn(
            self._params, self._opt_state, self._guard_state, synced)
        return loss

    def lower(self, *data):
        """Capture (if needed) and lower the fused step for a batch spec
        WITHOUT compiling or dispatching anything: the data arguments are
        abstracted to shape/dtype structs, and a deferred-init net is
        materialized with a batch-1 host forward only. This is the public
        surface the tuner's predictor and the HLO audit use — cost
        analysis, fingerprinting (``_lowered_digest``) — so external
        modules don't each re-implement the step-state argument list.
        Returns the ``jax.stages.Lowered``."""
        arrays = [_unwrap(d) if isinstance(d, NDArray) else d
                  for d in data]
        if self._step_fn is not None and self._n_inputs != len(arrays):
            # a diagnostics entry point must never silently re-capture a
            # live trainer (params/opt-state reset, loaded AOT executable
            # dropped) — same refusal as analysis.lint_trainer
            raise MXNetError(
                f"lower: batch has {len(arrays)} array(s) but the captured "
                f"step takes {self._n_inputs}; pass a batch of the "
                "training arity (lower never recaptures a live trainer)")
        if self._step_fn is None:
            # one-row slices are enough for deferred-init shape inference
            # and avoid a full-batch host forward in a predict-only path
            sample = [np.asarray(a[:1]) if getattr(a, "ndim", 0) else a
                      for a in arrays]
            self._capture(len(arrays), sample_arrays=sample)
        specs = [jax.ShapeDtypeStruct(tuple(a.shape), np.dtype(a.dtype))
                 for a in arrays]
        rng = jax.random.PRNGKey(0)
        return self._step_fn.lower(
            self._params, self._aux, self._opt_state, self._guard_state,
            rng, *specs)

    def sync_to_net(self) -> None:
        """Write the trained params/aux back into the gluon net (resharded
        onto each parameter's home device).  Pass-re-homed parameters are
        inverse-transformed first, so the net always sees its own layout."""
        def back(n, v):
            if self._pass_result is not None and \
                    n in self._pass_result.var_transforms:
                return jnp.asarray(
                    self._pass_result.inverse_var(n, jax.device_get(v)))
            return v
        for n in self._param_names:
            home = self._pmap[n].list_ctx()[0].jax_device()
            self._pmap[n].data()._set_data(
                jax.device_put(back(n, self._params[n]), home))
        for n in self._aux_names:
            home = self._pmap[n].list_ctx()[0].jax_device()
            self._pmap[n].data()._set_data(
                jax.device_put(back(n, self._aux[n]), home))

    def lint(self, *data, suppress=()) -> Any:
        """Trace-lint the fused step against a sample batch (mxlint trace
        front end): donation, f64, baked constants, host syncs. Captures the
        net if needed; nothing executes on device. Returns an
        ``analysis.Report``."""
        from ..analysis import lint_trainer
        return lint_trainer(self, *data, suppress=suppress)

    def anomaly_stats(self) -> Dict[str, Any]:
        """Grad-anomaly guard counters (empty dict when the guard is off or
        no step ran): skipped-step count, grad-norm EMA, last step's norm
        and whether it was skipped. Reading syncs the small scalars to host;
        surfaced through ``Monitor.install_trainer``."""
        if self._guard_cfg is None or self._guard_state is None:
            return {}
        gs = self._guard_state
        stats = {"grad_skipped_steps": int(gs["skips"]),
                 "grad_norm_ema": float(gs["ema"]),
                 "last_grad_norm": float(gs["last_norm"]),
                 "last_step_skipped": bool(int(gs["last_skipped"]))}
        if self._scaler_cfg is not None:
            stats["loss_scale"] = float(gs["loss_scale"])
            stats["scaler_overflows"] = int(gs["ls_overflows"])
            stats["scaler_good_steps"] = int(gs["ls_good"])
        if self._dynamic_lr:
            stats["lr_scale"] = float(gs["lr_scale"])
        if _metrics.enabled():
            # publish at drain time (Monitor interval / user poll), never
            # per step — reading the guard scalars syncs the device
            _telemetry.GRAD_SKIPPED.set(stats["grad_skipped_steps"])
            _telemetry.GRAD_NORM_EMA.set(stats["grad_norm_ema"])
            _telemetry.GRAD_LAST_NORM.set(stats["last_grad_norm"])
            if "loss_scale" in stats:
                _telemetry.LOSS_SCALE.set(stats["loss_scale"])
        return stats

    def perf_stats(self) -> Dict[str, Any]:
        """Step-attribution window stats (empty dict when attribution is
        off or no step ran): rolling bucket means, device_util, cadence —
        plus flops_per_step and live MFU when the cost ledger captured this
        executable. All host-side reads; never syncs the device."""
        if self._perf is None or self._perf.steps == 0:
            return {}
        stats = self._perf.stats()
        if self._flops_per_step:
            stats["flops_per_step"] = self._flops_per_step
            mfu = self._perf.mfu(self._flops_per_step)
            if mfu is not None:
                stats["mfu"] = mfu
        return stats

    def topology(self) -> Dict[str, Any]:
        """The mesh identity this trainer trains on: device count, data-
        axis (dp) extent, full mesh axes and the grad-reduce mode. This is
        what ``ResilientTrainer.save`` stamps into every resume manifest
        and what an elastic restore reconciles a checkpoint against
        (``resilience.elastic``)."""
        dev = self._mesh.devices.ravel()[0]
        try:
            dp = int(self._mesh.shape[self._axis])
        except (KeyError, TypeError):
            dp = int(self._mesh.devices.size)
        return {"n_devices": int(self._mesh.devices.size), "dp": dp,
                "axis": self._axis,
                "mesh_axes": {str(n): int(self._mesh.shape[n])
                              for n in self._mesh.axis_names},
                "device_kind": dev.device_kind, "platform": dev.platform,
                "grad_reduce": self._grad_reduce}

    def comm_config(self) -> Dict[str, Any]:
        """The communication-lever configuration this trainer runs — the
        scale-out half of the perf provenance (stamped into bench rows the
        way ``passes_provenance`` stamps the graph-pass half)."""
        return {"grad_reduce": self._grad_reduce,
                "grad_reduce_dtype": (str(self._grad_reduce_dtype)
                                      if self._grad_reduce_dtype is not None
                                      else None),
                "bucket_bytes": self._bucket_bytes,
                "compression": self._compression_params,
                "n_devices": int(self._mesh.devices.size)}

    def opt_state_bytes(self) -> Dict[str, int]:
        """Optimizer-state memory: ``total_bytes`` (the logical tree) and
        ``per_chip_bytes`` (what one chip actually holds — the number the
        ZeRO-1 sharded optimizer divides by N). Empty dict before capture."""
        if self._opt_state is None:
            return {}
        dev0 = self._mesh.devices.ravel()[0]
        total = per_chip = 0
        for leaf in jax.tree_util.tree_leaves(self._opt_state):
            nbytes = int(getattr(leaf, "nbytes", 0))
            total += nbytes
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                per_chip += sum(int(s.data.nbytes) for s in shards
                                if s.device == dev0)
            else:
                per_chip += nbytes
        return {"total_bytes": total, "per_chip_bytes": per_chip}

    def footprint(self) -> Dict[str, Any]:
        """Estimated resident HBM of this trainer (host-side tree sums —
        never syncs the device): params + aux + guard (replicated: each
        chip holds a full copy), opt-state via :meth:`opt_state_bytes`
        (ZeRO-aware per-chip share), and ``donated_bytes`` — the params +
        opt-state buffers the fused step donates, i.e. the transient the
        step does NOT double-buffer (XLA reuses donated inputs for the
        matching outputs). ``step_peak_bytes`` rides along when the memory
        ledger captured this trainer's executable."""
        params = _memwatch.tree_bytes(self._params)
        aux = _memwatch.tree_bytes(self._aux)
        guard = _memwatch.tree_bytes(self._guard_state)
        opt = self.opt_state_bytes()
        total = params + aux + guard + int(opt.get("total_bytes", 0))
        per_chip = params + aux + guard + int(opt.get("per_chip_bytes", 0))
        fp: Dict[str, Any] = {
            "params_bytes": params, "aux_bytes": aux, "guard_bytes": guard,
            "opt_state_bytes": opt,
            "donated_bytes": params + int(opt.get("total_bytes", 0)),
            "total_bytes": total, "per_chip_bytes": per_chip,
        }
        peaks = [r.get("peak_memory_bytes") for r in
                 (self._cost_rows or {}).values()
                 if r and r.get("peak_memory_bytes")]
        if peaks:
            fp["step_peak_bytes"] = int(max(peaks))
        return fp

    # ------------------------------------------------- recovery state hooks
    def set_loss_scale(self, scale: float) -> None:
        """Host-side override of the in-trace loss scale (the recovery
        ladder's ``cut_scale`` rung). A no-op trainer error when no scaler
        is configured."""
        if self._scaler_cfg is None or self._guard_state is None:
            raise MXNetError("trainer has no in-trace loss scaler "
                             "(construct with loss_scaling=...)")
        # the override obeys the same invariants as every in-trace
        # transition: power of two (bitwise-exact scaling) and the
        # configured clamp range
        _recovery._require_pow2("loss scale override", scale)
        scale = min(max(float(scale), float(self._scaler_cfg["min_scale"])),
                    float(self._scaler_cfg["max_scale"]))
        self._guard_state = dict(self._guard_state)
        self._guard_state["loss_scale"] = jax.device_put(
            jnp.asarray(scale, jnp.float32),
            NamedSharding(self._mesh, P()))
        self._guard_state["ls_good"] = jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(self._mesh, P()))

    def set_lr_scale(self, scale: float) -> None:
        """Host-side override of the dynamic LR multiplier (recovery
        rollback backoff / heal restore)."""
        if not self._dynamic_lr or self._guard_state is None:
            raise MXNetError("trainer has no dynamic lr scale "
                             "(construct with dynamic_lr_scale=True)")
        self._guard_state = dict(self._guard_state)
        self._guard_state["lr_scale"] = jax.device_put(
            jnp.asarray(float(scale), jnp.float32),
            NamedSharding(self._mesh, P()))

    @property
    def mesh(self) -> Mesh:
        return self._mesh
