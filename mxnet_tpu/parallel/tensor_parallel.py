"""Tensor parallelism — parameter sharding over the 'tp' mesh axis.

Absent from the reference (SURVEY.md §2.3 "Tensor parallelism: Absent —
build as first-class"). Megatron-style pairing: a column-parallel matmul
(output features sharded, no comm) feeds a row-parallel matmul (input
features sharded, one psum) — one allreduce per MLP/attention block.

Two surfaces:
- functional ops for use inside shard_map regions;
- ``shard_gluon_params``: annotate a gluon net's Parameters with
  PartitionSpecs by regex rule so pjit-based trainers shard them (the
  sharding-annotation route: XLA's SPMD partitioner then inserts the same
  collectives automatically).
"""
from __future__ import annotations

import functools
import re
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["column_parallel_dense", "row_parallel_dense", "tp_mlp",
           "shard_gluon_params", "DEFAULT_TP_RULES"]


# ---- inside-shard_map functional layers ----------------------------------
def column_parallel_dense(x, w_shard, b_shard=None):
    """x: (..., I) replicated; w_shard: (O/n, I) local. Output (..., O/n)
    stays sharded — no communication."""
    y = jnp.einsum("...i,oi->...o", x, w_shard)
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel_dense(x_shard, w_shard, axis_name: str, b=None):
    """x_shard: (..., I/n); w_shard: (O, I/n). psum reduces the partial
    products; bias added once post-reduce."""
    y = lax.psum(jnp.einsum("...i,oi->...o", x_shard, w_shard), axis_name)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w1_shard, b1_shard, w2_shard, b2, axis_name: str, act=jax.nn.relu):
    """Fused column→row parallel MLP block: ONE allreduce total."""
    h = act(column_parallel_dense(x, w1_shard, b1_shard))
    return row_parallel_dense(h, w2_shard, axis_name, b2)


# ---- gluon param annotation ------------------------------------------------
# rule: regex on parameter name -> PartitionSpec (axis names must exist in
# the mesh; None entries replicate that dim)
DEFAULT_TP_RULES = [
    (r".*_i2h_weight$", P("tp", None)),     # RNN input projections: col-parallel
    (r".*dense\d*_weight$", P("tp", None)),  # Dense weight (O, I): col-parallel
    (r".*conv\d*_weight$", P("tp", None, None, None)),  # conv out-channels
]


def shard_gluon_params(net, mesh: Mesh, rules=None, default=P()) -> Dict[str, NamedSharding]:
    """Assign a NamedSharding to every Parameter of ``net`` by first-match
    regex rule; stores it on ``Parameter.sharding`` and returns the map."""
    rules = rules if rules is not None else DEFAULT_TP_RULES
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = {}
    for p in net.collect_params().values():
        spec = default
        for pat, s in compiled:
            if pat.match(p.name):
                # drop axes that exceed the param's rank
                s = P(*list(s)[:len(p.shape or ())]) if p.shape else s
                spec = s
                break
        sh = NamedSharding(mesh, spec)
        p.sharding = sh
        out[p.name] = sh
    return out
